"""Repo invariants checker: the internal/tools sanitycheck analogue.

The reference keeps a tooling dir with lint pins and a sanity script
(/root/reference/internal/tools/, Makefile `check` target :93-96). This
framework's equivalent checks the contracts the driver and judge rely
on, without importing jax (fast, no device):

  - bench.py exists and its contract (ONE json line with
    metric/value/unit/vs_baseline) is declared in code;
  - __graft_entry__ exposes entry() and dryrun_multichip();
  - every tracetesting suite parses and targets a known service dir;
  - proto/demo.proto compiles if protoc is available;
  - deploy/k8s manifests parse as YAML k8s objects;
  - overload-protection invariants hold statically: the pipeline's
    shed-lane contract excludes the error lane, the bounded-admission
    suite asserts the budget and zero-error-lane-shed invariants, and
    every OVERLOAD_KNOBS env knob is threaded through the daemon, the
    compose overlay and the k8s generator;
  - no Python file accidentally imports from /root/reference.

Run via `make check`.
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAILS: list[str] = []


def check(ok: bool, msg: str) -> None:
    print(("ok   " if ok else "FAIL ") + msg)
    if not ok:
        FAILS.append(msg)


def main() -> int:
    # bench contract
    bench = os.path.join(ROOT, "bench.py")
    check(os.path.exists(bench), "bench.py exists")
    if os.path.exists(bench):
        src = open(bench).read()
        for key in ('"metric"', '"value"', '"unit"', '"vs_baseline"'):
            check(key in src, f"bench.py emits {key}")

    # graft entry contract
    entry_path = os.path.join(ROOT, "__graft_entry__.py")
    check(os.path.exists(entry_path), "__graft_entry__.py exists")
    if os.path.exists(entry_path):
        tree = ast.parse(open(entry_path).read())
        fns = {n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)}
        check("entry" in fns, "__graft_entry__.entry defined")
        check("dryrun_multichip" in fns, "__graft_entry__.dryrun_multichip defined")

    # tracetesting suites parse and target known services
    import yaml

    # Service names as the suites spell them (dirs use the reference's
    # kebab-case; the services package registers the same names on its
    # classes).
    known_services = {
        "ad", "cart", "checkout", "currency", "email", "frontend",
        "payment", "product-catalog", "quote", "recommendation",
        "shipping", "fraud-detection", "accounting",
        # Cross-cutting suites beyond the per-service set: the edge
        # observability surfaces (/jaeger + /grafana).
        "observability",
    }
    tdir = os.path.join(ROOT, "tracetesting")
    suites = sorted(os.listdir(tdir)) if os.path.isdir(tdir) else []
    check(len(suites) >= 10, f"tracetesting covers {len(suites)} services (>=10)")
    for svc in suites:
        check(svc in known_services, f"tracetesting/{svc} targets a known service")
        for fname in os.listdir(os.path.join(tdir, svc)):
            path = os.path.join(tdir, svc, fname)
            try:
                docs = list(yaml.safe_load_all(open(path)))
                check(all(d for d in docs), f"tracetesting/{svc}/{fname} parses")
            except yaml.YAMLError as e:
                check(False, f"tracetesting/{svc}/{fname} parses ({e})")

    # proto compiles
    if shutil.which("protoc"):
        r = subprocess.run(
            ["protoc", "--python_out", "/tmp", "proto/demo.proto"],
            cwd=ROOT, capture_output=True,
        )
        check(r.returncode == 0, "proto/demo.proto compiles")
    else:
        print("skip proto (no protoc)")

    # k8s manifests parse (aggregates + the per-component breakout dir)
    kdir = os.path.join(ROOT, "deploy", "k8s")
    check(os.path.isdir(kdir), "deploy/k8s exists")
    manifest_paths = []
    for dirpath, _dirs, files in os.walk(kdir):
        manifest_paths += [
            os.path.join(dirpath, f) for f in files if f.endswith(".yaml")
        ]
    for path in sorted(manifest_paths):
        docs = list(yaml.safe_load_all(open(path)))
        rel = os.path.relpath(path, ROOT)
        check(
            all(d and "apiVersion" in d and "kind" in d for d in docs),
            f"{rel} is valid k8s YAML",
        )

    # overload-protection invariants (all static — no jax import):
    # 1) the shed-lane contract in runtime/pipeline.py must exclude the
    #    error lane (SHED_LANES is the pinned constant);
    pipeline_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "pipeline.py"
    )
    shed_lanes = None
    for node in ast.walk(ast.parse(open(pipeline_py).read())):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "SHED_LANES"
            for t in node.targets
        ):
            shed_lanes = ast.literal_eval(node.value)
    check(shed_lanes is not None, "pipeline.py declares SHED_LANES")
    check(
        shed_lanes is not None and "error" not in shed_lanes,
        "shed policy never touches the error lane (SHED_LANES)",
    )
    check(
        "queue_max_rows" in open(pipeline_py).read(),
        "pipeline.py implements the bounded pending-queue budget",
    )
    # 2) the overload suite asserts the budget bound and the
    #    zero-error-lane-shed counters (the runtime proof of #1);
    overload_tests = os.path.join(ROOT, "tests", "test_overload.py")
    check(os.path.exists(overload_tests), "tests/test_overload.py exists")
    if os.path.exists(overload_tests):
        tsrc = open(overload_tests).read()
        check(
            "pending_rows() <= pipe.queue_max_rows" in tsrc,
            "overload suite asserts the pending-queue bound",
        )
        check(
            'shed_rows["error"] == 0' in tsrc,
            "overload suite asserts zero error-lane shed",
        )
    # 3) every overload AND ingest-pool knob (utils/config.py
    #    OVERLOAD_KNOBS / INGEST_KNOBS — read via AST, importing would
    #    pull jax) reaches the daemon, the compose overlay and the k8s
    #    generator: one registry per knob family, no drift.
    config_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "utils", "config.py"
    )
    registries: dict[str, dict] = {}
    for node in ast.walk(ast.parse(open(config_py).read())):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id in (
                        "DAEMON_KNOBS", "OVERLOAD_KNOBS", "INGEST_KNOBS",
                        "REPLICATION_KNOBS", "FRAME_KNOBS",
                        "QUERY_KNOBS", "SPINE_KNOBS", "SELFTRACE_KNOBS",
                        "HISTORY_KNOBS", "REMEDIATION_KNOBS",
                        "FLEET_KNOBS", "AUTOSCALE_KNOBS",
                        "SHADOW_KNOBS", "PROVENANCE_KNOBS",
                        "FRONTDOOR_KNOBS", "KEYSPACE_KNOBS",
                    )
                    and node.value is not None
                ):
                    registries[t.id] = ast.literal_eval(node.value)
    for reg_name in (
        "DAEMON_KNOBS", "OVERLOAD_KNOBS", "INGEST_KNOBS",
        "REPLICATION_KNOBS", "FRAME_KNOBS", "QUERY_KNOBS",
        "SPINE_KNOBS", "SELFTRACE_KNOBS", "HISTORY_KNOBS",
        "REMEDIATION_KNOBS", "FLEET_KNOBS", "AUTOSCALE_KNOBS",
        "SHADOW_KNOBS", "PROVENANCE_KNOBS", "FRONTDOOR_KNOBS",
        "KEYSPACE_KNOBS",
    ):
        knobs = registries.get(reg_name)
        check(bool(knobs), f"utils/config.py declares {reg_name}")
        for consumer in (
            os.path.join("opentelemetry_demo_tpu", "runtime", "daemon.py"),
            os.path.join("deploy", "docker-compose.anomaly.yml"),
            os.path.join("opentelemetry_demo_tpu", "utils", "k8s.py"),
        ):
            text = open(os.path.join(ROOT, consumer)).read()
            if consumer.endswith("k8s.py"):
                # k8s.py consumes the registry itself — the reference
                # must be the import, not copied strings.
                check(
                    reg_name in text,
                    f"{consumer} consumes the {reg_name} registry",
                )
                continue
            for knob in knobs or ():
                check(knob in text, f"{consumer} threads {knob}")
    # The generated manifests actually carry the knob env (the
    # generator could consume the registry and still drop the env
    # block): spot-check the sidecar bundle.
    sidecar = os.path.join(ROOT, "deploy", "k8s", "anomaly-detector-sidecar.yaml")
    if os.path.exists(sidecar):
        stext = open(sidecar).read()
        for knobs in registries.values():
            for knob in knobs:
                check(knob in stext, f"deploy/k8s sidecar carries {knob}")
    # 4) ingest-pool invariants: the pool queue is bounded (no
    #    unbounded buffer ahead of the pipeline's admission), and the
    #    pooled path proves bit-exactness + no-aliasing in tests.
    pool_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "ingest_pool.py"
    )
    check(os.path.exists(pool_py), "runtime/ingest_pool.py exists")
    if os.path.exists(pool_py):
        ptext = open(pool_py).read()
        check(
            "IngestPoolSaturated" in ptext and "max_pending" in ptext,
            "ingest pool bounds its request queue (IngestPoolSaturated)",
        )
    pool_tests = os.path.join(ROOT, "tests", "test_ingest_pool.py")
    check(os.path.exists(pool_tests), "tests/test_ingest_pool.py exists")
    if os.path.exists(pool_tests):
        ttext = open(pool_tests).read()
        for marker in (
            "test_pooled_bit_exact",
            "test_scratch_reuse_no_aliasing",
            "test_native_decode_releases_gil",
        ):
            check(marker in ttext, f"ingest-pool suite pins {marker}")

    # 5) hot-standby replication invariants: both deploy surfaces
    #    define the standby service (a replication layer nobody can
    #    deploy is dead code), and the suite pins the fencing +
    #    anti-entropy proofs.
    compose_text = open(
        os.path.join(ROOT, "deploy", "docker-compose.anomaly.yml")
    ).read()
    check(
        "anomaly-detector-standby:" in compose_text,
        "compose overlay defines the anomaly-detector-standby service",
    )
    check(
        "ANOMALY_ROLE=standby" in compose_text,
        "compose standby service runs ANOMALY_ROLE=standby",
    )
    k8s_text = open(
        os.path.join(ROOT, "opentelemetry_demo_tpu", "utils", "k8s.py")
    ).read()
    check(
        "anomaly-detector-standby" in k8s_text,
        "k8s generator emits the anomaly-detector-standby deployment",
    )
    if os.path.exists(sidecar):
        check(
            "anomaly-detector-standby" in open(sidecar).read(),
            "deploy/k8s sidecar bundle carries the standby deployment",
        )
    repl_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "replication.py"
    )
    check(os.path.exists(repl_py), "runtime/replication.py exists")
    repl_tests = os.path.join(ROOT, "tests", "test_replication.py")
    check(os.path.exists(repl_tests), "tests/test_replication.py exists")
    if os.path.exists(repl_tests):
        rtext = open(repl_tests).read()
        for marker in (
            "test_stale_primary_fenced_on_all_three_paths",
            "test_blackholed_standby_converges_by_merge",
            "test_failover_drill_sigkill_primary",
        ):
            check(marker in rtext, f"replication suite pins {marker}")

    # 6) ONE verified wire format (runtime/frame.py): the checksummed
    #    columnar frame is the single source of truth for every state
    #    byte layout — ingest scratch→pipeline, replication payloads,
    #    checkpoint files. The byte-primitive monopoly itself
    #    (np.savez/np.load/np.frombuffer/struct.pack fenced to the
    #    layout owners) is DELEGATED to scripts/staticcheck's
    #    frame-monopoly pass — an AST import-resolution check a renamed
    #    import can't dodge, and one implementation so sanitycheck and
    #    staticcheck can never disagree. The literal pins kept here are
    #    the frame module's own contract markers.
    frame_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "frame.py"
    )
    check(os.path.exists(frame_py), "runtime/frame.py exists")
    if os.path.exists(frame_py):
        ftext = open(frame_py).read()
        for marker in ("FRAME_MAGIC", "FRAME_VERSION", "def encode",
                       "def decode", "crc32c"):
            check(marker in ftext, f"runtime/frame.py declares {marker}")
    if os.environ.get("SANITYCHECK_SKIP_STATICCHECK") == "1":
        # make check just ran the FULL staticcheck (frame-monopoly
        # included) in the previous step — re-running the delegated
        # pass here would parse the whole tree a second time for no
        # new information. Standalone sanitycheck runs still delegate.
        check(True, "frame monopoly delegated (staticcheck already ran)")
    else:
        sys.path.insert(0, ROOT)
        from scripts.staticcheck.core import run_repo as _staticcheck_run

        frame_violations, frame_pragma_errs, _supp = _staticcheck_run(
            ROOT, select=["frame-monopoly"]
        )
        # Pragma misuse (reasonless/stale/unknown-id) fails HERE too,
        # not only under `python -m scripts.staticcheck` — delegation
        # means sanitycheck and staticcheck cannot disagree.
        frame_problems = frame_violations + frame_pragma_errs
        check(
            not frame_problems,
            "frame monopoly holds (staticcheck frame-monopoly pass) "
            f"{[v.render() for v in frame_problems] or ''}",
        )
    frame_tests = os.path.join(ROOT, "tests", "test_frame.py")
    check(os.path.exists(frame_tests), "tests/test_frame.py exists")
    if os.path.exists(frame_tests):
        fttext = open(frame_tests).read()
        for marker in (
            "test_every_single_bit_flip_is_caught",
            "test_corrupt_link_quarantines_and_converges",
            "test_checkpoint_v0_npz_migrates",
            "test_truncated_trailer_quarantined",
        ):
            check(marker in fttext, f"frame suite pins {marker}")

    # 7) live query plane (runtime/query.py): reads over live sketch
    #    state happen ONLY through the role-dispatched snapshot helper
    #    (live dispatch DONATES the detector's device buffers — a
    #    direct read races "Array has been deleted", and a forked read
    #    path would break the primary/replica bit-consistency
    #    contract). Pinned grep-level, same style as the frame.py
    #    np.frombuffer pin:
    #    a) query.py consumes a snapshot_fn and NEVER names the
    #       detector state or the dispatch lock;
    #    b) the daemon wires the engine to its snapshot helper;
    #    c) the suite pins the failover/consistency/exemplar proofs.
    query_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "query.py"
    )
    check(os.path.exists(query_py), "runtime/query.py exists")
    if os.path.exists(query_py):
        qtext = open(query_py).read()
        for marker in (
            "class QueryEngine", "class QueryService", "snapshot_fn",
            "def dispatch", "/search", "/annotations", "/query/flight",
        ):
            check(marker in qtext, f"runtime/query.py declares {marker!r}")
        check(
            "detector.state" not in qtext and "_dispatch_lock" not in qtext,
            "query.py reads state only via the snapshot helper "
            "(no detector.state / _dispatch_lock reference)",
        )
    daemon_text = open(os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "daemon.py"
    )).read()
    check(
        "def _query_snapshot" in daemon_text
        and "snapshot_fn=self._query_snapshot" in daemon_text,
        "daemon wires the query engine to the role-dispatched "
        "snapshot helper",
    )
    query_tests = os.path.join(ROOT, "tests", "test_query.py")
    check(os.path.exists(query_tests), "tests/test_query.py exists")
    if os.path.exists(query_tests):
        qttext = open(query_tests).read()
        for marker in (
            "test_read_replica_survives_primary_sigkill",
            "test_replica_answers_bit_identical_at_same_seq",
            "test_exemplars_round_trip_to_ingested_traces",
            "test_queries_never_race_dispatch_donation",
            "test_grafana_datasource_contract",
        ):
            check(marker in qttext, f"query suite pins {marker}")

    # 8) detector self-telemetry (runtime/selftrace.py +
    #    runtime/flightrec.py): the span/phase vocabulary is declared
    #    (the trace-discipline staticcheck pass polices its use), the
    #    tracer samples deterministically, the flight recorder dumps
    #    evidence, and the suite pins the proofs.
    selftrace_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "selftrace.py"
    )
    check(os.path.exists(selftrace_py), "runtime/selftrace.py exists")
    if os.path.exists(selftrace_py):
        sttext = open(selftrace_py).read()
        for marker in (
            "class SelfTracer", "class BatchTrace", "def splitmix64",
            "def sampled", "SPAN_BATCH", "SPAN_FLAG", "PHASE_DECODE",
            "def encode_selftrace_request", "def decode_selftrace_request",
        ):
            check(marker in sttext, f"runtime/selftrace.py declares {marker}")
    flight_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "flightrec.py"
    )
    check(os.path.exists(flight_py), "runtime/flightrec.py exists")
    if os.path.exists(flight_py):
        fltext = open(flight_py).read()
        for marker in ("class FlightRecorder", "def record", "def dump"):
            check(marker in fltext, f"runtime/flightrec.py declares {marker}")
    selftrace_tests = os.path.join(ROOT, "tests", "test_selftrace.py")
    check(os.path.exists(selftrace_tests), "tests/test_selftrace.py exists")
    if os.path.exists(selftrace_tests):
        stt = open(selftrace_tests).read()
        for marker in (
            "test_span_parent_and_links_round_trip",
            "test_sampling_is_deterministic",
            "test_flight_ring_is_bounded",
            "test_dump_on_saturated_transition",
            "test_phase_histograms_on_metrics",
            "test_selftrace_overhead_canary",
        ):
            check(marker in stt, f"selftrace suite pins {marker}")

    # 9) time-travel tier (runtime/history.py + runtime/replaybench.py):
    #    the frame-native history store is the ONLY frame consumer
    #    outside the live path. Pinned structurally: an AST scan of the
    #    package's import statements must find `frame` imported by
    #    EXACTLY the live-path owners (ingest scratch→pipeline,
    #    replication link, checkpoint file, the daemon's boot-time
    #    frame.configure) plus history.py — a sixth importer is a new
    #    frame consumer nobody reviewed. Plus the subsystem's own
    #    contract markers, the replay/requires_env marker registrations,
    #    and the suite pins.
    history_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "history.py"
    )
    check(os.path.exists(history_py), "runtime/history.py exists")
    if os.path.exists(history_py):
        htext = open(history_py).read()
        for marker in (
            "class HistoryStore", "class HistoryWriter",
            "class HistoryReader", "def merge_record_arrays",
            "RECORD_MAGIC", "fence.check", "quarantine",
        ):
            check(marker in htext, f"runtime/history.py declares {marker}")
    frame_importers: set[str] = set()
    pkg_root = os.path.join(ROOT, "opentelemetry_demo_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, fname)
            try:
                tree = ast.parse(open(fpath).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                names = []
                if isinstance(node, ast.ImportFrom):
                    if node.module and node.module.split(".")[-1] == "frame":
                        names = ["frame"]
                    else:
                        names = [a.name for a in node.names]
                elif isinstance(node, ast.Import):
                    names = [a.name.split(".")[-1] for a in node.names]
                if "frame" in names:
                    frame_importers.add(
                        os.path.relpath(fpath, pkg_root).replace(os.sep, "/")
                    )
    expected_frame_importers = {
        "runtime/checkpoint.py",   # frames ON DISK (live durability)
        "runtime/daemon.py",       # boot-time frame.configure()
        "runtime/ingest_pool.py",  # scratch→pipeline hop (live)
        "runtime/replication.py",  # primary→standby payloads (live)
        "runtime/history.py",      # THE one consumer outside the live path
    }
    check(
        frame_importers == expected_frame_importers,
        "history.py is the only frame consumer outside the live path "
        f"(importers {sorted(frame_importers)})",
    )
    replaybench_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "replaybench.py"
    )
    check(os.path.exists(replaybench_py), "runtime/replaybench.py exists")
    check(
        "replaybench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a replaybench target",
    )
    pyproject = open(os.path.join(ROOT, "pyproject.toml")).read()
    for marker_name in ("history:", "replay:", "requires_env(resource):"):
        check(
            marker_name in pyproject,
            f"pyproject registers the {marker_name.rstrip(':')} marker",
        )
    for env_test in (
        "test_graft_entry.py", "test_multihost.py",
        "test_parallel.py", "test_tracetest.py",
    ):
        ttext = open(os.path.join(ROOT, "tests", env_test)).read()
        check(
            "requires_env" in ttext,
            f"tests/{env_test} carries the requires_env marker "
            "(its failures are env gaps, not regressions)",
        )
    history_tests = os.path.join(ROOT, "tests", "test_history.py")
    check(os.path.exists(history_tests), "tests/test_history.py exists")
    if os.path.exists(history_tests):
        httext = open(history_tests).read()
        for marker in (
            "test_ladder_fold_bit_identical_to_direct_merge",
            "test_corrupt_record_quarantined_and_skipped",
            "test_stale_writer_append_refused",
            "test_range_queries_serve_from_disk",
            "test_replay_verdicts_bit_identical",
            "test_grafana_range_honored",
        ):
            check(marker in httext, f"history suite pins {marker}")

    # 10) closed-loop auto-mitigation (runtime/remediation.py): the
    #     controller exists with its guardrail surface, auto-mitigation
    #     defaults OFF (opt-in is a hard product decision, not a knob
    #     default someone can drift), the FLAG-WRITER MONOPOLY holds —
    #     the atomic flag-file write primitive (flags.atomic_write_doc)
    #     is imported by EXACTLY the flag editor UI and the remediation
    #     actuator (an AST import scan, closed set, same discipline as
    #     the frame-importer pin: a third flag writer is a reviewed
    #     decision, not drift) — and the chaos suite pins the proofs.
    remediation_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "remediation.py"
    )
    check(os.path.exists(remediation_py), "runtime/remediation.py exists")
    if os.path.exists(remediation_py):
        rmtext = open(remediation_py).read()
        for marker in (
            "class RemediationController", "class FlagdActuator",
            "class SamplingActuator", "class TokenBucket",
            'path="remediation"', "STATE_FAILED", "rollback",
        ):
            check(marker in rmtext, f"runtime/remediation.py declares {marker}")
    rem_knobs = registries.get("REMEDIATION_KNOBS") or {}
    enable_spec = rem_knobs.get("ANOMALY_REMEDIATION_ENABLE")
    check(
        enable_spec is not None and enable_spec[1] == 0,
        "auto-mitigation defaults OFF (ANOMALY_REMEDIATION_ENABLE=0)",
    )
    flag_writers: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, fname)
            try:
                tree = ast.parse(open(fpath).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                names = []
                if isinstance(node, ast.ImportFrom):
                    names = [a.name for a in node.names]
                elif isinstance(node, ast.Import):
                    names = [a.name.split(".")[-1] for a in node.names]
                if "atomic_write_doc" in names:
                    flag_writers.add(
                        os.path.relpath(fpath, pkg_root).replace(os.sep, "/")
                    )
    expected_flag_writers = {
        "utils/flag_ui.py",        # the flagd-ui editor surface
        "runtime/remediation.py",  # the mitigation actuator
    }
    check(
        flag_writers == expected_flag_writers,
        "remediation.py + flag_ui.py are the only flag-store writers "
        f"(atomic_write_doc importers {sorted(flag_writers)})",
    )
    mitigbench_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "mitigbench.py"
    )
    check(os.path.exists(mitigbench_py), "runtime/mitigbench.py exists")
    check(
        "mitigbench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a mitigbench target",
    )
    check(
        "remediation:" in pyproject,
        "pyproject registers the remediation marker",
    )
    remediation_tests = os.path.join(ROOT, "tests", "test_remediation.py")
    check(
        os.path.exists(remediation_tests), "tests/test_remediation.py exists"
    )
    if os.path.exists(remediation_tests):
        rttext = open(remediation_tests).read()
        for marker in (
            "test_flapping_detector_cannot_oscillate_flags",
            "test_degraded_flagd_never_blocks_the_hot_path",
            "test_standby_observes_but_never_actuates",
            "test_fenced_daemon_actuation_refused",
            "test_rollback_on_failed_recovery",
            "test_flight_evidence_on_act_revert_rollback",
        ):
            check(marker in rttext, f"remediation suite pins {marker}")
    flag_ui_tests = os.path.join(ROOT, "tests", "test_flag_ui.py")
    if os.path.exists(flag_ui_tests):
        fut = open(flag_ui_tests).read()
        check(
            "test_torn_flag_file_write_never_corrupts_live_store" in fut,
            "flag suite pins the torn-write regression",
        )

    # 11) sharded detector fleet (runtime/fleet.py ring + membership +
    #     guardrailed reshard; runtime/aggregator.py scatter-gather):
    #     the aggregator NEVER touches detector state (the query-plane
    #     no-direct-read discipline, pinned the same grep way), the
    #     ring's placement hash is process-stable (no hash()), the
    #     Makefile has the fleetbench drill, and the fleet suite pins
    #     the property/chaos proofs.
    fleet_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "fleet.py"
    )
    check(os.path.exists(fleet_py), "runtime/fleet.py exists")
    if os.path.exists(fleet_py):
        fleet_text = open(fleet_py).read()
        for marker in (
            "class HashRing", "class FleetMembership",
            "def merge_shard_arrays", "def key_hash64",
            "def shard_key", "TokenBucket", "health_check",
        ):
            check(marker in fleet_text, f"runtime/fleet.py declares {marker}")
        check(
            "blake2b" in fleet_text,
            "fleet.py hashes ring keys with a process-stable digest",
        )
    agg_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "aggregator.py"
    )
    check(os.path.exists(agg_py), "runtime/aggregator.py exists")
    if os.path.exists(agg_py):
        agg_text = open(agg_py).read()
        for marker in (
            "class FleetAggregator", "class AggregatorService",
            "shards_answered", "shards_total",
        ):
            check(marker in agg_text, f"runtime/aggregator.py declares {marker}")
        check(
            "detector.state" not in agg_text
            and "_dispatch_lock" not in agg_text
            and "snapshot_fn" not in agg_text,
            "aggregator.py reads shards only over HTTP (no detector "
            "state / dispatch lock / snapshot helper reference)",
        )
    check(
        "fleetbench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a fleetbench target",
    )
    check(
        "fleet:" in pyproject,
        "pyproject registers the fleet marker",
    )
    check(
        "def measure_reshard" in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "replbench.py"
        )).read(),
        "replbench.py grows the shard-kill -> reshard drill",
    )
    fleet_tests = os.path.join(ROOT, "tests", "test_fleet.py")
    check(os.path.exists(fleet_tests), "tests/test_fleet.py exists")
    if os.path.exists(fleet_tests):
        fttext = open(fleet_tests).read()
        for marker in (
            "test_ring_balance_within_bound",
            "test_minimal_key_movement_on_leave_and_join",
            "test_placement_deterministic_across_processes",
            "test_flapping_shard_freezes_ring_within_budget",
            "test_stalled_but_serving_shard_not_declared_dead",
            "test_blackholed_shard_degrades_to_labeled_partial",
            "test_noisy_tenant_sheds_alone",
            "test_reshard_converges_bit_exact",
        ):
            check(marker in fttext, f"fleet suite pins {marker}")

    # 12) two-pass native scanner (native/ingest.cc + runtime/native.py,
    #     the r15 decode-wall rework): the raw C entry points are
    #     called ONLY from runtime/native.py (monopoly pin, same
    #     pattern as frame.py's byte-primitive fence — a second caller
    #     would fork the ctypes contract and the GIL-release story),
    #     native and the Python fallback share ONE verdict taxonomy
    #     (malformed → ValueError/-1 → the receivers' 400; no new bare
    #     error path), and the decodebench/fuzz surfaces exist.
    native_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "native.py"
    )
    check(os.path.exists(native_py), "runtime/native.py exists")
    otd_entry_callers: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, fname)
            text = open(fpath, errors="replace").read()
            # The C ABI surface: any otd_decode/otd_scan/otd_extract
            # reference outside native.py is a second ctypes caller.
            if any(
                marker in text
                for marker in (
                    "otd_decode_otlp", "otd_decode_otlp_many",
                    "otd_scan_otlp", "otd_extract_otlp",
                    "otd_decode_orders",
                )
            ):
                otd_entry_callers.add(
                    os.path.relpath(fpath, pkg_root).replace(os.sep, "/")
                )
    check(
        otd_entry_callers == {"runtime/native.py"},
        "native decode entry points are called only from native.py "
        f"(callers {sorted(otd_entry_callers)})",
    )
    ntext = open(native_py).read()
    for marker in (
        "def scan_otlp", "def extract_otlp", "def decode_otlp_many",
        "SHARD_MIN_BYTES_DEFAULT", "malformed OTLP payload",
    ):
        check(marker in ntext, f"runtime/native.py declares {marker!r}")
    ingest_cc = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "native", "ingest.cc"
    )
    cctext = open(ingest_cc).read()
    for marker in ("scan_request", "extract_span", "otd_scan_otlp",
                   "otd_extract_otlp", "payload_rows"):
        check(marker in cctext, f"native/ingest.cc declares {marker}")
    # One verdict taxonomy: the pool maps BOTH engines' per-payload
    # verdicts into the same errors dict the receivers answer 400
    # from; native.py raises ValueError for whole-batch failures
    # exactly like otlp.decode_export_request's WireError(ValueError).
    ptext = open(pool_py).read()
    check(
        'ValueError("malformed OTLP payload")' in ptext,
        "ingest pool maps native per-payload verdicts to the "
        "fallback's ValueError taxonomy",
    )
    check(
        "decodebench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a decodebench target",
    )
    ni_tests = os.path.join(ROOT, "tests", "test_native_ingest.py")
    if os.path.exists(ni_tests):
        nitext = open(ni_tests).read()
        for marker in (
            "test_native_and_python_verdicts_agree_on_every_seed",
            "test_shard_split_varints_bit_exact",
            "test_truncation_at_every_pass1_boundary",
            "test_max_nesting_submessages",
        ):
            check(marker in nitext, f"scanner fuzz suite pins {marker}")

    # 13) elastic fleet (runtime/autoscale.py + the adoption tier in
    #     fleet.py/daemon.py): the autoscaler defaults OFF (the same
    #     hard opt-in as remediation — a ring that resizes itself is a
    #     product decision, not a knob drift), every decision passes
    #     the SIXTH fenced epoch path (path="autoscale"), dead-peer
    #     keyspace adoption is automatic in-daemon (ring_heir + adopt
    #     + merge under the dispatch lock), the k8s generator emits
    #     the collector-side fleet routing from the REAL ring, and the
    #     chaos suite pins the proofs.
    autoscale_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "autoscale.py"
    )
    check(os.path.exists(autoscale_py), "runtime/autoscale.py exists")
    if os.path.exists(autoscale_py):
        astext = open(autoscale_py).read()
        for marker in (
            "class AutoscaleController", "TokenBucket",
            'path="autoscale"', "observe_only", "budget_exhausted",
            "refused_apply",
        ):
            check(marker in astext, f"runtime/autoscale.py declares {marker!r}")
    as_knobs = registries.get("AUTOSCALE_KNOBS") or {}
    as_enable = as_knobs.get("ANOMALY_AUTOSCALE_ENABLE")
    check(
        as_enable is not None and as_enable[1] == 0,
        "autoscaling defaults OFF (ANOMALY_AUTOSCALE_ENABLE=0)",
    )
    if os.path.exists(fleet_py):
        fleet_text = open(fleet_py).read()
        for marker in ("def adopt", "def ring_heir", "adoptive"):
            check(
                marker in fleet_text,
                f"runtime/fleet.py grows the adoption tier ({marker})",
            )
    for marker in (
        "_adopt_shard", "_retarget_adoption_mirror",
        "AutoscaleController",
    ):
        check(
            marker in daemon_text,
            f"daemon wires automatic adoption + autoscaler ({marker})",
        )
    check(
        "fleet_routing_configmap" in k8s_text,
        "k8s generator emits the ring-derived fleet routing configmap",
    )
    check(
        "def measure_adoption" in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "replbench.py"
        )).read(),
        "replbench.py grows the autoscale + SIGKILL-adoption drill",
    )
    check(
        "autoscalebench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has an autoscalebench target",
    )
    if os.path.exists(fleet_tests):
        fttext = open(fleet_tests).read()
        for marker in (
            "test_dead_peer_frame_adopted_automatically",
            "test_stalled_but_serving_shard_never_auto_adopted",
            "test_budget_exhausted_freezes_adoption",
            "test_observe_only_default_never_proposes",
            "test_fenced_decision_refused",
            "test_autoscale_sigkill_adoption_live",
        ):
            check(marker in fttext, f"elastic-fleet suite pins {marker}")

    # 14) counterfactual control (runtime/shadow.py + the preflight
    #     interlude in remediation.py): the pre-flight verifier
    #     defaults OFF (same hard opt-in as remediation/autoscale — a
    #     gate that can refuse mitigations is a product decision), the
    #     shadow replay is built by the SAME pipeline builder
    #     replaybench uses (bit-identity by construction), it touches
    #     live state through the disk-backed HistoryReader ONLY (the
    #     query.py isolation contract), and the suite + bench legs pin
    #     both verdict directions.
    shadow_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "shadow.py"
    )
    check(os.path.exists(shadow_py), "runtime/shadow.py exists")
    if os.path.exists(shadow_py):
        shtext = open(shadow_py).read()
        for marker in (
            "class ShadowVerifier", "def build_shadow_pipeline",
            "def suppress_transform", "PreflightVerdict",
            "REASON_DEADLINE", "REASON_INSUFFICIENT",
        ):
            check(marker in shtext, f"runtime/shadow.py declares {marker!r}")
        check(
            "detector.state" not in shtext
            and "_dispatch_lock" not in shtext,
            "shadow.py replays from the disk-backed reader only "
            "(no detector.state / _dispatch_lock reference)",
        )
    sh_knobs = registries.get("SHADOW_KNOBS") or {}
    sh_enable = sh_knobs.get("ANOMALY_SHADOW_ENABLE")
    check(
        sh_enable is not None and sh_enable[1] == 0,
        "pre-flight verification defaults OFF (ANOMALY_SHADOW_ENABLE=0)",
    )
    rem_text = open(os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "remediation.py"
    )).read()
    for marker in (
        "STATE_PREFLIGHT", "_finish_preflight", "class CollectorActuator",
    ):
        check(
            marker in rem_text,
            f"remediation.py grows the preflight interlude ({marker})",
        )
    check(
        "build_shadow_pipeline" in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "replaybench.py"
        )).read(),
        "replaybench builds its replay pipeline through the ONE "
        "shared builder (shadow.build_shadow_pipeline)",
    )
    check(
        "def measure_shadow" in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "mitigbench.py"
        )).read(),
        "mitigbench.py grows the shadow pre-flight leg",
    )
    check(
        "shadowbench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a shadowbench target",
    )
    check(
        "shadow:" in open(os.path.join(ROOT, "pyproject.toml")).read(),
        "pyproject registers the shadow marker",
    )
    shadow_tests = os.path.join(ROOT, "tests", "test_shadow.py")
    check(os.path.exists(shadow_tests), "tests/test_shadow.py exists")
    if os.path.exists(shadow_tests):
        sttext = open(shadow_tests).read()
        for marker in (
            "test_bit_identity_with_replaybench",
            "test_would_help_mitigation_released",
            "test_wrong_mitigation_refused",
            "test_deadline_miss_refuses",
            "test_refused_verdict_refunds_and_stays_pending",
            "test_fenced_daemon_never_preflights",
            "test_isolation_pin_no_live_state",
            "test_exact_revert_prior_restored",
            "test_refcounted_shared_holds",
        ):
            check(marker in sttext, f"shadow suite pins {marker}")

    # §15 native front door (r19): the zero-Python OTLP/HTTP door —
    # the native acceptor exists with its framing verdicts, the Python
    # control plane exists WITHOUT any Python HTTP machinery (the
    # per-payload loop is native by construction, and this pin keeps
    # it that way), the knob registry stays strictly opt-in, and the
    # parity/fuzz suite + bench legs are pinned by name.
    fd_cc = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "native", "frontdoor.cc"
    )
    check(os.path.exists(fd_cc), "native/frontdoor.cc exists")
    if os.path.exists(fd_cc):
        fdcc = open(fd_cc).read()
        for marker in (
            "otd_fd_start", "otd_fd_next", "otd_fd_respond",
            "otd_fd_quiesce", "otd_fd_stop", "Content-Length",
        ):
            check(marker in fdcc, f"native/frontdoor.cc declares {marker}")
    fd_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "frontdoor.py"
    )
    check(os.path.exists(fd_py), "runtime/frontdoor.py exists")
    if os.path.exists(fd_py):
        fdtext = open(fd_py).read()
        for marker in (
            "class FrontDoorServer", "frontdoor_next", "frontdoor_body",
            "IngestPoolSaturated",
        ):
            check(marker in fdtext, f"runtime/frontdoor.py declares {marker!r}")
        # AST, not substring: the module's docstring is ALLOWED to
        # name the machinery it bans; only a real import trips this.
        fd_imports: set[str] = set()
        for node in ast.walk(ast.parse(fdtext)):
            if isinstance(node, ast.Import):
                fd_imports.update(a.name for a in node.names)
            elif isinstance(node, ast.ImportFrom) and node.module:
                fd_imports.add(node.module)
        fd_banned = {"http", "socketserver", "urllib", "wsgiref"}
        check(
            not any(
                m.split(".", 1)[0] in fd_banned for m in fd_imports
            ),
            "frontdoor.py imports no Python HTTP machinery (the "
            "zero-Python per-payload pin: bodies go socket→native "
            "buffer→decode ticket, never through a Python request "
            "object)",
        )
    check(
        "frontdoor" in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "native.py"
        )).read(),
        "runtime/native.py binds the front-door surface",
    )
    fd_knobs = registries.get("FRONTDOOR_KNOBS") or {}
    fd_enable = fd_knobs.get("ANOMALY_FRONTDOOR_ENABLE")
    check(
        fd_enable is not None and fd_enable[1] == 0,
        "front door defaults OFF (ANOMALY_FRONTDOOR_ENABLE=0 — the "
        "Python receiver stays the default path)",
    )
    check(
        "frontdoorbench:" in open(os.path.join(ROOT, "Makefile")).read(),
        "Makefile has a frontdoorbench target",
    )
    check(
        "BENCH_FRONTDOOR" in open(os.path.join(ROOT, "bench.py")).read(),
        "bench.py grows the BENCH_FRONTDOOR leg",
    )
    check(
        "frontdoor:" in open(os.path.join(ROOT, "pyproject.toml")).read(),
        "pyproject registers the frontdoor marker",
    )
    fdb_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "frontdoorbench.py"
    )
    check(os.path.exists(fdb_py), "runtime/frontdoorbench.py exists")
    if os.path.exists(fdb_py):
        fdbtext = open(fdb_py).read()
        for marker in (
            "def measure_frontdoor_vs_pool",
            "def measure_million_key_soak",
            "rss_per_million_keys_mb",
        ):
            check(
                marker in fdbtext,
                f"frontdoorbench.py declares {marker!r}",
            )
    fd_tests = os.path.join(ROOT, "tests", "test_frontdoor.py")
    check(os.path.exists(fd_tests), "tests/test_frontdoor.py exists")
    if os.path.exists(fd_tests):
        fttext = open(fd_tests).read()
        for marker in (
            "test_frontdoor_status_parity_shared_corpus",
            "test_frontdoor_columns_byte_identical",
            "test_frontdoor_truncation_every_boundary",
            "test_frontdoor_slowloris",
            "test_frontdoor_pipelined_requests",
            "test_frontdoor_oversized_413",
            "test_frontdoor_chunked_rejected",
            "test_frontdoor_faultwire_chaos",
            "test_frontdoor_saturation_retry_after",
            "test_frontdoor_graceful_drain",
            "test_frontdoor_no_python_http_in_payload_path",
            "test_intern_100k_one_flush_bit_identity",
            "test_intern_known_batch_lock_free",
            "test_fleet_drift_refusal_large_tables",
        ):
            check(marker in fttext, f"front-door suite pins {marker}")

    # §16 key lifecycle plane (r20): the bounded interner, the idle
    # evictor, the degradation ladder, and the generation fence. The
    # knob registry is consumer-threaded by the loop above; here we
    # pin the semantics the knobs promise (two-edge hysteresis needs
    # high > low; a 0-key evict batch would make the ladder's evict
    # rung a no-op), the one concurrency invariant everything rests
    # on (interner retirement happens inside the pipeline dispatch
    # lock — an evictor that retires outside it races the pump's
    # intern path), and the suite names.
    ks_knobs = registries.get("KEYSPACE_KNOBS") or {}
    ks_enable = ks_knobs.get("ANOMALY_KEYSPACE_ENABLE")
    check(
        ks_enable is not None and ks_enable[1] == 1,
        "keyspace plane defaults ON (ANOMALY_KEYSPACE_ENABLE=1 — "
        "bounded memory is the default posture, not an opt-in)",
    )
    ks_hi = ks_knobs.get("ANOMALY_KEYSPACE_HIGH_WATERMARK")
    ks_lo = ks_knobs.get("ANOMALY_KEYSPACE_LOW_WATERMARK")
    check(
        ks_hi is not None and ks_lo is not None and ks_lo[1] < ks_hi[1] <= 1.0,
        "keyspace watermarks form a hysteresis band "
        "(LOW < HIGH <= 1.0 — equal edges would flap the ladder)",
    )
    ks_batch = ks_knobs.get("ANOMALY_KEYSPACE_EVICT_BATCH")
    check(
        ks_batch is not None and ks_batch[1] >= 1,
        "keyspace evict batch >= 1 (a 0 batch silently disables the "
        "evict rung)",
    )
    check(
        "ANOMALY_QUERY_EVICTED_LOOKBACK_S"
        in (registries.get("QUERY_KNOBS") or {}),
        "QUERY_KNOBS carries ANOMALY_QUERY_EVICTED_LOOKBACK_S "
        "(evicted-key answers need a bounded history search window)",
    )
    ks_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "keyspace.py"
    )
    check(os.path.exists(ks_py), "runtime/keyspace.py exists")
    if os.path.exists(ks_py):
        kstext = open(ks_py).read()
        for marker in (
            "class KeyspaceManager", "def evict_idle", "def tick",
            "def process_rss_bytes",
        ):
            check(marker in kstext, f"runtime/keyspace.py declares {marker!r}")
        # AST, not substring: every retire_services(...) call in the
        # evictor must sit under a `with ... _dispatch_lock:` block.
        # (scripts/staticcheck's eviction-lock pass enforces this
        # repo-wide; this pin keeps the module itself honest even if
        # the pass is ever skipped.)
        unlocked = []
        tree = ast.parse(kstext)

        def _locked(node: ast.AST, guarded: bool) -> None:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "retire_services"
                and not guarded
            ):
                unlocked.append(node.lineno)
            inside = guarded
            if isinstance(node, ast.With):
                for item in node.items:
                    src = ast.unparse(item.context_expr)
                    if "_dispatch_lock" in src:
                        inside = True
            for child in ast.iter_child_nodes(node):
                _locked(child, inside)

        _locked(tree, False)
        check(
            not unlocked,
            "keyspace.py retires interner ids only under the dispatch "
            f"lock (unguarded retire_services at lines {unlocked or '—'})",
        )
    pl_py = os.path.join(
        ROOT, "opentelemetry_demo_tpu", "runtime", "pipeline.py"
    )
    pltext = open(pl_py).read()
    for marker in (
        "KEYSPACE_LEVEL_EVICT", "KEYSPACE_LEVEL_THROTTLE",
        "KEYSPACE_LEVEL_COLLAPSE", "KEYSPACE_LEVEL_SHED",
        "def keyspace_update", "def keyspace_newkey_gate",
        "def admission_retry_after",
    ):
        check(marker in pltext, f"runtime/pipeline.py declares {marker!r}")
    check(
        "keyspace:" in open(os.path.join(ROOT, "pyproject.toml")).read(),
        "pyproject registers the keyspace marker",
    )
    check(
        "measure_churn_soak"
        in open(os.path.join(
            ROOT, "opentelemetry_demo_tpu", "runtime", "frontdoorbench.py"
        )).read(),
        "frontdoorbench.py grows the churn-soak gate",
    )
    check(
        "churn_ok" in open(os.path.join(ROOT, "bench.py")).read(),
        "bench.py lifts the churn_ok verdict",
    )
    ks_tests = os.path.join(ROOT, "tests", "test_keyspace.py")
    check(os.path.exists(ks_tests), "tests/test_keyspace.py exists")
    if os.path.exists(ks_tests):
        kttext = open(ks_tests).read()
        for marker in (
            "test_saturated_intern_many_dense_and_bit_stable",
            "test_all_overflow_flush_roundtrips_the_frame_format",
            "test_retire_recycles_ids_behind_a_generation_bump",
            "test_two_edge_hysteresis_one_rung_per_hold",
            "test_throttle_rung_isolates_tenants",
            "test_shed_rung_answers_429_through_the_python_door",
            "test_evict_folds_zeroes_and_retires_idle_keys",
            "test_fleet_merge_refuses_generation_drift",
            "test_replication_delta_refused_across_generations",
            "test_checkpoint_roundtrips_generation_and_tombstones",
            "test_evicted_key_answers_from_history",
            "test_overflow_bucket_answers_are_labeled",
        ):
            check(marker in kttext, f"keyspace suite pins {marker}")

    # no imports from the read-only reference tree
    bad = []
    for dirpath, dirnames, filenames in os.walk(ROOT):
        dirnames[:] = [d for d in dirnames if d not in (".git", "__pycache__", "build")]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            if os.path.samefile(path, __file__):
                continue  # this checker necessarily names the pattern
            text = open(path, errors="replace").read()
            if "/root/reference" in text:
                # Citations in docstrings/comments are expected; an
                # import or open() against the tree is not.
                for line in text.splitlines():
                    s = line.strip()
                    if s.startswith(("#", '"', "'")) or "reference" not in s:
                        continue
                    if ("import" in s or "open(" in s) and "/root/reference" in s:
                        bad.append(os.path.join(dirpath, fname))
    check(not bad, f"no code imports/reads /root/reference {bad or ''}")

    print(("\nSANITY OK" if not FAILS else f"\n{len(FAILS)} FAILURES"))
    return 1 if FAILS else 0


if __name__ == "__main__":
    sys.exit(main())
