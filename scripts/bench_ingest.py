"""Host-ingest throughput: protobuf OTLP bytes → pipeline columns.

The device side does tens of millions of spans/sec (bench.py); this
measures the other half of the ≥200k spans/sec budget (SURVEY.md §7
hard part (a)) — wire decode + attribute hashing + interning — for
three engines over the same bytes:

- pure-Python record path (no compiler needed),
- the serial native path (one C++ decode + tensorize per request — the
  r5 architecture, kept as the BEFORE number),
- the parallel ingest engine (runtime.ingest_pool: batched decode,
  pooled buffers, coalesced tensorize) swept over ``--workers``.

Methodology lives in ``runtime.ingestbench`` (shared with bench.py's
``host_ingest_*`` artifact fields), so CI and operators run the SAME
numbers: ``make ingestbench`` is this script with the default sweep.

Run: python scripts/bench_ingest.py [--workers 1,2,4]   (CPU only)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from opentelemetry_demo_tpu.runtime import ingestbench, native  # noqa: E402


def _print_fat_scaling():
    fat = ingestbench.measure_fat_payload_scaling()
    if fat:
        legs = "  ".join(
            f"{t}thr={fat[t]/1e6:.2f}M/s"
            for t in sorted(k for k in fat if k != "scaling")
        )
        print(f"one fat payload:      {legs}  scaling={fat['scaling']}x")


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workers", default="1,2,4",
        help="comma-separated decode-pool worker counts to sweep",
    )
    parser.add_argument(
        "--raw", action="store_true",
        help="raw two-pass scanner microbench only: pass-1 scan vs "
             "pass-2 extract vs whole-call throughput per thread "
             "(`make decodebench`) — attributes a decode regression "
             "without running the full pool",
    )
    args = parser.parse_args()
    workers = [int(w) for w in args.workers.split(",") if w.strip()]

    if args.raw:
        raw = ingestbench.measure_raw()
        if raw is None:
            print(f"native unavailable: {native.load_error()}")
            return
        print(
            f"pass-1 scan:          {raw['scan_spans_per_sec']/1e6:8.2f} M spans/s"
            f"  ({raw['scan_bytes_per_sec']/1e6:7.1f} MB/s)"
        )
        print(
            f"pass-2 extract:       {raw['extract_spans_per_sec']/1e6:8.2f} M spans/s"
        )
        print(
            f"decode_many (1 thr):  {raw['decode_spans_per_sec']/1e6:8.2f} M spans/s"
        )
        _print_fat_scaling()
        return

    payloads = ingestbench.make_payloads()  # built once, shared by all
    py = ingestbench.measure_python(payloads=payloads)
    print(f"python-records:        {py/1e3:10.1f} k spans/s")
    nat = ingestbench.measure_native(payloads=payloads)
    if nat is None:
        print(f"native unavailable: {native.load_error()}")
        return
    print(f"native-serial:         {nat/1e3:10.1f} k spans/s  (r5 path)")
    for w in workers:
        got = ingestbench.measure_pooled_detail(
            workers=w, payloads=payloads
        )
        rate = got["spans_per_sec"]
        share = got["phase_share"]
        phases = " ".join(
            f"{name}={share.get(name, 0.0):.0%}"
            for name in ("decode", "verify", "tensorize", "submit")
        )
        split = got.get("decode_split") or {}
        split_s = (
            f"  decode: scan={split.get('scan', 0.0):.0%}"
            f" extract={split.get('extract', 0.0):.0%}"
            if split else ""
        )
        print(
            f"pool workers={w}:        {rate/1e3:10.1f} k spans/s"
            f"  ({rate/nat:4.2f}x serial)  [{phases}]{split_s}"
        )
    _print_fat_scaling()


if __name__ == "__main__":
    main()
