"""Host-ingest throughput: protobuf OTLP bytes → pipeline columns.

The device side does tens of millions of spans/sec (bench.py); this
measures the other half of the ≥200k spans/sec budget (SURVEY.md §7
hard part (a)) — wire decode + attribute hashing + interning — for the
pure-Python record path vs the native C++ columnar path. Methodology
lives in ``runtime.ingestbench`` (shared with bench.py's artifact
field).

Run: python scripts/bench_ingest.py   (CPU only, no TPU needed)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from opentelemetry_demo_tpu.runtime import ingestbench, native  # noqa: E402


def main():
    payloads = ingestbench.make_payloads()  # built once, shared by both
    py = ingestbench.measure_python(payloads=payloads)
    print(f"python-records: {py/1e3:10.1f} k spans/s")
    nat = ingestbench.measure_native(payloads=payloads)
    if nat is None:
        print(f"native unavailable: {native.load_error()}")
    else:
        print(f"native-columns: {nat/1e3:10.1f} k spans/s")


if __name__ == "__main__":
    main()
