"""Host-ingest throughput: protobuf OTLP bytes → pipeline columns.

The device side does millions of spans/sec (bench.py); this measures
the other half of the ≥200k spans/sec budget (SURVEY.md §7 hard part
(a)) — wire decode + attribute hashing + interning — for the pure-
Python record path vs the native C++ columnar path.

Run: python scripts/bench_ingest.py   (CPU only, no TPU needed)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from opentelemetry_demo_tpu.runtime import native, wire
from opentelemetry_demo_tpu.runtime.otlp import (
    MONITORED_ATTR_KEYS,
    decode_export_request,
)
from opentelemetry_demo_tpu.runtime.tensorize import SpanTensorizer


def make_payloads(n_requests=64, spans_per_request=128, seed=0):
    rng = np.random.default_rng(seed)
    services = [
        "frontend", "checkout", "cart", "payment", "currency",
        "product-catalog", "shipping", "ad", "recommendation", "quote",
    ]

    def anyval(s):
        return wire.encode_len(1, s.encode())

    def kv(k, v):
        return wire.encode_len(1, k.encode()) + wire.encode_len(2, anyval(v))

    payloads = []
    for _ in range(n_requests):
        svc = services[int(rng.integers(0, len(services)))]
        spans = b""
        for _ in range(spans_per_request):
            start = int(rng.integers(10**18, 2 * 10**18))
            span = (
                wire.encode_len(1, bytes(rng.integers(0, 256, 16, dtype=np.uint8)))
                + wire.encode_len(5, b"oteldemo.rpc/Call")
                + wire.encode_fixed64(7, start)
                + wire.encode_fixed64(8, start + int(rng.integers(10**5, 10**9)))
                + wire.encode_len(9, kv("app.product.id", f"P-{int(rng.integers(0, 100))}"))
                + wire.encode_len(9, kv("rpc.system", "grpc"))
            )
            if rng.random() < 0.02:
                span += wire.encode_len(15, wire.encode_int(3, 2))
            spans += wire.encode_len(2, span)
        resource = wire.encode_len(1, kv("service.name", svc))
        rs = wire.encode_len(1, resource) + wire.encode_len(2, spans)
        payloads.append(wire.encode_len(1, rs))
    return payloads


def bench(label, fn, payloads, n_spans, repeat=5):
    fn(payloads[0])  # warmup
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        for p in payloads:
            fn(p)
        best = min(best, time.perf_counter() - t0)
    rate = n_spans / best
    print(f"{label:>14}: {rate/1e3:10.1f} k spans/s  ({best*1e3:.1f} ms/pass)")
    return rate


def main():
    payloads = make_payloads()
    n_spans = 64 * 128

    tz = SpanTensorizer(num_services=32)
    bench(
        "python-records",
        lambda p: tz.columns_from_records(decode_export_request(p)),
        payloads,
        n_spans,
    )
    if native.available():
        tz2 = SpanTensorizer(num_services=32)
        bench(
            "native-columns",
            lambda p: tz2.columns_from_columnar(
                native.decode_otlp(p, MONITORED_ATTR_KEYS)
            ),
            payloads,
            n_spans,
        )
    else:
        print(f"native unavailable: {native.load_error()}")


if __name__ == "__main__":
    main()
