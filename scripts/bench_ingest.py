"""Host-ingest throughput: protobuf OTLP bytes → pipeline columns.

The device side does tens of millions of spans/sec (bench.py); this
measures the other half of the ≥200k spans/sec budget (SURVEY.md §7
hard part (a)) — wire decode + attribute hashing + interning — for
three engines over the same bytes:

- pure-Python record path (no compiler needed),
- the serial native path (one C++ decode + tensorize per request — the
  r5 architecture, kept as the BEFORE number),
- the parallel ingest engine (runtime.ingest_pool: batched decode,
  pooled buffers, coalesced tensorize) swept over ``--workers``.

Methodology lives in ``runtime.ingestbench`` (shared with bench.py's
``host_ingest_*`` artifact fields), so CI and operators run the SAME
numbers: ``make ingestbench`` is this script with the default sweep.

Run: python scripts/bench_ingest.py [--workers 1,2,4]   (CPU only)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from opentelemetry_demo_tpu.runtime import ingestbench, native  # noqa: E402


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workers", default="1,2,4",
        help="comma-separated decode-pool worker counts to sweep",
    )
    args = parser.parse_args()
    workers = [int(w) for w in args.workers.split(",") if w.strip()]

    payloads = ingestbench.make_payloads()  # built once, shared by all
    py = ingestbench.measure_python(payloads=payloads)
    print(f"python-records:        {py/1e3:10.1f} k spans/s")
    nat = ingestbench.measure_native(payloads=payloads)
    if nat is None:
        print(f"native unavailable: {native.load_error()}")
        return
    print(f"native-serial:         {nat/1e3:10.1f} k spans/s  (r5 path)")
    for w in workers:
        got = ingestbench.measure_pooled_detail(
            workers=w, payloads=payloads
        )
        rate = got["spans_per_sec"]
        share = got["phase_share"]
        phases = " ".join(
            f"{name}={share.get(name, 0.0):.0%}"
            for name in ("decode", "verify", "tensorize", "submit")
        )
        print(
            f"pool workers={w}:        {rate/1e3:10.1f} k spans/s"
            f"  ({rate/nat:4.2f}x serial)  [{phases}]"
        )


if __name__ == "__main__":
    main()
