"""Serve the standalone shop stack — the ``make start`` entry point.

One process = the reference's ``docker compose up`` for this framework:
HTTP gateway at :8080 (Envoy-route analogue: /api/*, /images/*,
/feature flag editor, /otlp-http ingest, /metrics), the in-proc
telemetry backend (collector → trace/metric/log stores), and the TPU
anomaly-detector pipeline subscribed to the span stream. Optional
in-proc load (``--users``), or ``--load-only`` to drive a remote
gateway the way the reference's load-generator container drives Envoy
(/root/reference/docker-compose.yml:646-668).

Examples:
    python scripts/serve_shop.py --port 8080 --users 5
    python scripts/serve_shop.py --load-only --target http://host:8080
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from opentelemetry_demo_tpu.models import AnomalyDetector, DetectorConfig
from opentelemetry_demo_tpu.runtime.pipeline import DetectorPipeline
from opentelemetry_demo_tpu.services.gateway import ShopGateway
from opentelemetry_demo_tpu.services.http_load import (
    BrowserLoadGenerator,
    HttpLoadGenerator,
    browser_traffic_enabled,
)
from opentelemetry_demo_tpu.services.shop import Shop, ShopConfig
from opentelemetry_demo_tpu.telemetry.metrics import export_report
from opentelemetry_demo_tpu.utils.flag_ui import FlagEditorUI


def serve(args) -> None:
    broker = None
    kafka_bootstrap = None
    if args.kafka == "auto":
        # Boot the in-repo broker beside the shop: one process fewer
        # than the compose topology, same wire path (checkout still
        # publishes over a real socket).
        from opentelemetry_demo_tpu.runtime.kafka_broker import KafkaBroker

        broker = KafkaBroker(host="127.0.0.1", port=args.kafka_port)
        broker.start()
        kafka_bootstrap = f"127.0.0.1:{broker.port}"
        print(f"kafka broker on {kafka_bootstrap}", flush=True)
    elif args.kafka:
        kafka_bootstrap = args.kafka

    if args.minimal and kafka_bootstrap:
        parser_error = "--minimal drops the async tier; it conflicts with --kafka"
        raise SystemExit(parser_error)
    shop = Shop(ShopConfig(
        users=0, seed=args.seed, kafka_bootstrap=kafka_bootstrap,
        minimal=args.minimal,
    ))

    pipeline = None
    span_exporter = None
    metrics_exporter = None
    logs_exporter = None
    if args.otlp_endpoint:
        # Compose topology: the detector runs in its OWN process (the
        # anomaly-detector container); this process exports spans and
        # scraped metrics to it over OTLP/HTTP, the otelcol exporter
        # pattern (otelcol-config.yml:85-92, docker-compose.yml:226-256).
        from opentelemetry_demo_tpu.runtime.otlp_export import (
            OtlpHttpLogsExporter,
            OtlpHttpSpanExporter,
        )
        from opentelemetry_demo_tpu.runtime.otlp_metrics import (
            OtlpHttpMetricsExporter,
        )

        span_exporter = OtlpHttpSpanExporter(args.otlp_endpoint)
        metrics_exporter = OtlpHttpMetricsExporter(args.otlp_endpoint)
        # Third signal (otelcol-config.yml:128-131): shop logs cross to
        # the sidecar's /v1/logs so a cross-process deployment carries
        # all three signals, not two.
        logs_exporter = OtlpHttpLogsExporter(args.otlp_endpoint)
        shop.collector.log_exporters.append(logs_exporter)
        exporters_by_signal = (
            ("traces", span_exporter),
            ("metrics", metrics_exporter),
            ("logs", logs_exporter),
        )

        def export_metrics_and_stats(now, jobs):
            metrics_exporter(now, jobs)
            # Sender-queue visibility (anomaly_export_dropped_total /
            # anomaly_export_queue_depth) on the SCRAPE cadence — not
            # the span-flush path, which goes quiet exactly when the
            # queues are most interesting (idle shop, or span export
            # held back by admission backpressure): the drop-oldest
            # path lands in the shop's own scraped registry, so a
            # saturated sidecar shows on the anomaly dashboard.
            for signal, exporter in exporters_by_signal:
                exporter.publish_stats(shop.metrics, signal=signal)

        shop.collector.metrics_exporters.append(export_metrics_and_stats)
        on_spans = span_exporter
    else:
        # Single-process mode: in-proc detector pipeline.
        detector = AnomalyDetector(DetectorConfig(num_services=32))

        def on_report(t, report, flagged):
            export_report(
                shop.metrics,
                pipeline.tensorizer.service_names,
                report,
                flagged,
            )

        pipeline = DetectorPipeline(
            detector, flags=shop.flags, on_report=on_report, batch_size=args.batch
        )

        def on_spans(t, spans):
            pipeline.submit(spans)
            pipeline.pump(t)

    gw = ShopGateway(shop, host=args.host, port=args.port, on_spans=on_spans)
    if not args.minimal:
        # Minimal profile drops flagd-UI (the reference's minimal
        # compose keeps flagd itself — OFREP evaluation stays served).
        gw.feature_ui = FlagEditorUI(shop.flags)
    gw.start()
    print(f"shop gateway on http://{args.host}:{gw.port}  "
          + ("(minimal profile; metrics at /metrics)" if args.minimal else
             "(flag editor at /feature, metrics at /metrics)"), flush=True)

    grpc_edge = None
    if args.grpc_port >= 0:
        # The reference's business services ARE gRPC servers; the edge
        # serves their whole oteldemo surface beside the HTTP gateway,
        # sharing the gateway's lock (one single-writer shop graph).
        from opentelemetry_demo_tpu.services.grpc_edge import GrpcShopEdge

        grpc_edge = GrpcShopEdge(
            shop, host=args.host, port=args.grpc_port, lock=gw._lock
        )
        grpc_edge.start()
        # Single-entry gRPC (the reference's /flagservice/ Envoy route):
        # h2c connections hitting the HTTP port splice to this edge.
        # Dial the edge on the address it actually BOUND — loopback only
        # when it listens on a wildcard.
        splice_host = (
            "127.0.0.1" if args.host in ("0.0.0.0", "::", "") else args.host
        )
        gw.grpc_target = (splice_host, grpc_edge.port)
        print(f"gRPC edge on {args.host}:{grpc_edge.port} "
              f"(also tunnelled through :{gw.port})", flush=True)

    # Loadgen control plane at /loadgen (the Locust web UI behind the
    # edge, envoy.tmpl.yaml:46): --users is the autostart default
    # (.env LOCUST_USERS/LOCUST_AUTOSTART), runtime-editable after.
    from opentelemetry_demo_tpu.services.load_control import LoadControl

    load = LoadControl(f"http://127.0.0.1:{gw.port}", seed=args.seed)
    gw.loadgen_ui = load
    browser_users = 1 if browser_traffic_enabled() else 0
    if args.users > 0 or browser_users:
        load.set_users(args.users, browser_users=browser_users or None)
        print(f"in-proc load: {args.users} users"
              + (", 1 browser user" if browser_users else ""), flush=True)
    print(f"loadgen control at http://{args.host}:{gw.port}/loadgen",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    load.stop()
    if grpc_edge is not None:
        grpc_edge.stop()
    gw.stop()
    # Push the collector's unflushed span/log tail to the exporters
    # before draining them — batches land on the pump timer, and the
    # last window before shutdown has no later pump to flush it.
    shop.collector.force_flush(scrape=False)
    if pipeline is not None:
        pipeline.drain()
    for exporter in (span_exporter, metrics_exporter, logs_exporter):
        if exporter is not None:
            exporter.flush()
            exporter.close()
    if hasattr(shop.bus, "close"):
        shop.bus.close()
    if broker is not None:
        broker.stop()


def load_only(args) -> None:
    load = HttpLoadGenerator(args.target, users=args.users, seed=args.seed)
    load.start()
    print(f"load: {args.users} users → {args.target}", flush=True)
    browser_load = None
    if browser_traffic_enabled():
        browser_load = BrowserLoadGenerator(
            args.target, users=1, seed=args.seed
        )
        browser_load.start()
        print("browser load: 1 user", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    load.stop()
    if browser_load is not None:
        browser_load.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=int(os.getenv("SHOP_PORT", "8080")))
    parser.add_argument("--users", type=int, default=int(os.getenv("SHOP_USERS", "0")))
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--batch", type=int, default=512)
    parser.add_argument("--load-only", action="store_true")
    parser.add_argument(
        "--minimal", action="store_true",
        default=os.getenv("SHOP_MINIMAL", "") not in ("", "0", "false"),
        help="minimal profile (docker-compose.minimal.yml analogue): "
        "drops accounting, fraud-detection, the orders bus and the "
        "flag-editor UI; flagd evaluation (OFREP) stays",
    )
    parser.add_argument("--target", default="http://127.0.0.1:8080")
    parser.add_argument(
        "--grpc-port", type=int,
        default=int(os.getenv("SHOP_GRPC_PORT", "-1")),
        help="serve the oteldemo gRPC surface on this port "
        "(0 = ephemeral, -1 = disabled)",
    )
    parser.add_argument(
        "--kafka", default=os.getenv("KAFKA_ADDR", ""),
        help="orders over a real TCP broker: 'auto' boots the in-repo "
        "KafkaBroker beside the shop, 'host:port' points at an external "
        "one (the compose overlay sets KAFKA_ADDR); empty = in-proc bus "
        "(the minimal-compose analogue, which also drops kafka)",
    )
    parser.add_argument(
        "--kafka-port", type=int, default=int(os.getenv("KAFKA_PORT", "0")),
        help="listen port for --kafka auto (0 = ephemeral)",
    )
    parser.add_argument(
        "--otlp-endpoint",
        default=os.getenv("OTEL_EXPORTER_OTLP_ENDPOINT", ""),
        help="export spans+metrics to an external anomaly-detector "
        "daemon instead of running one in-process; http(s)://host:4318 "
        "for OTLP/HTTP, grpc://host:4317 for OTLP/gRPC (the collector "
        "exporter default)",
    )
    args = parser.parse_args()
    if args.load_only:
        load_only(args)
    else:
        serve(args)


if __name__ == "__main__":
    main()
