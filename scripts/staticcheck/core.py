"""Framework: source model, pragma handling, pass registry, runner.

Passes are plain functions ``(repo) -> list[Violation]`` registered in
:data:`PASSES`. The framework owns everything cross-cutting: file
loading/caching, AST parse, parent links (for "am I inside a ``with``
holding the dispatch lock" questions), the ``# staticcheck: ok[id]``
suppression pragma (reason REQUIRED), and the unused/unknown-pragma
errors. Keeping the framework dumb and the passes declarative is what
lets tests run a single pass against a seeded-bad fixture tree.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

PRAGMA_RE = re.compile(
    r"#\s*staticcheck:\s*ok\[([a-z0-9_-]+)\]\s*(.*?)\s*$"
)

# Directories never scanned (tests manipulate env/state deliberately;
# caches and VCS metadata are noise; the checker itself necessarily
# names the patterns it hunts — same self-exemption sanitycheck takes).
SKIP_DIRS = {
    "__pycache__", ".git", "build", "tests", "tracetesting",
    "staticcheck",
}


@dataclass(frozen=True)
class Violation:
    pass_id: str
    path: str          # repo-relative, slash-separated
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


@dataclass
class Pragma:
    pass_id: str
    reason: str
    line: int
    used: bool = False


class SourceFile:
    """One parsed module: text, lines, AST with parent links, pragmas."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a violation by run_repo
            self.parse_error = f"syntax error: {e}"
        self._parents: dict[ast.AST, ast.AST] = {}
        if self.tree is not None:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        self._comments: dict[int, str] | None = None
        self._pragmas: dict[int, Pragma] | None = None

    @property
    def pragmas(self) -> dict[int, Pragma]:
        """line -> suppression pragma, harvested from REAL comments
        (the tokenizer map) — a pragma-shaped string literal is data,
        not a suppression, the same '#-inside-a-string' rule the
        justification scan applies."""
        if self._pragmas is None:
            self._pragmas = {}
            for ln, comment in sorted(self.comments.items()):
                m = PRAGMA_RE.search(comment)
                if m:
                    self._pragmas[ln] = Pragma(m.group(1), m.group(2), ln)
        return self._pragmas

    @property
    def comments(self) -> dict[int, str]:
        """line -> comment text, from the tokenizer — unlike a ``'#' in
        line`` scan this cannot be fooled by a ``#`` inside a string
        literal. Empty on files the tokenizer rejects (those already
        surface a parse-error violation)."""
        if self._comments is None:
            self._comments = {}
            try:
                readline = io.StringIO(self.text).readline
                for tok in tokenize.generate_tokens(readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenizeError, SyntaxError,
                    IndentationError, ValueError):
                self._comments = {}
        return self._comments

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def inside_with_matching(self, node: ast.AST, needle: str) -> bool:
        """True when ``node`` sits inside a ``with`` statement whose
        context expression source mentions ``needle`` (e.g. the
        dispatch lock). Lexical, not dynamic — which is the point: the
        contract is "the read is WRITTEN under the lock"."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    if needle in ast.unparse(item.context_expr):
                        return True
        return False

    def segment(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.text, node) or ""
        except Exception:  # pragma: no cover - malformed positions
            return ""


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


class ImportMap:
    """Alias → canonical dotted name, per module.

    Resolves ``import numpy as np`` / ``from numpy import frombuffer
    as fb`` so a pass can ask "does this call reach
    ``numpy.frombuffer``?" regardless of spelling — the whole reason
    these checks moved off grep."""

    def __init__(self, tree: ast.Module):
        self.aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def resolve_call(self, func: ast.AST) -> str | None:
        """Canonical dotted target of a call's func expression."""
        name = dotted(func)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        base = self.aliases.get(head)
        if base is None:
            return name
        return f"{base}.{rest}" if rest else base


class Repo:
    """Scanned tree + cached parsed sources.

    ``package`` is the main source package (detected: a top-level
    directory with an ``__init__.py`` and a ``runtime/`` or ``utils/``
    subdirectory), so fixtures in tests can use any package name and
    the passes still find their anchor modules (``utils/config.py``,
    ``telemetry/metrics.py``, ``runtime/frame.py``, …)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._cache: dict[str, SourceFile] = {}
        self.package = self._detect_package()

    def _detect_package(self) -> str | None:
        candidates = []
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            return None
        for name in entries:
            path = os.path.join(self.root, name)
            if name in SKIP_DIRS or not os.path.isdir(path):
                continue
            if not os.path.exists(os.path.join(path, "__init__.py")):
                continue
            if os.path.isdir(os.path.join(path, "runtime")) or os.path.isdir(
                os.path.join(path, "utils")
            ):
                candidates.append(name)
        return candidates[0] if candidates else None

    # -- file iteration -------------------------------------------------

    def iter_py(self, *subpaths: str) -> list[str]:
        """Repo-relative paths of .py files under the given subpaths
        (default: package + scripts + top-level .py files), skipping
        tests/caches."""
        roots = list(subpaths)
        if not roots:
            roots = [p for p in (self.package, "scripts") if p]
            roots += [
                f for f in ("bench.py", "__graft_entry__.py")
                if os.path.exists(os.path.join(self.root, f))
            ]
        out: list[str] = []
        for sub in roots:
            absolute = os.path.join(self.root, sub)
            if os.path.isfile(absolute) and sub.endswith(".py"):
                out.append(sub.replace(os.sep, "/"))
                continue
            for dirpath, dirnames, filenames in os.walk(absolute):
                dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fname), self.root
                        )
                        out.append(rel.replace(os.sep, "/"))
        return sorted(set(out))

    def source(self, relpath: str) -> SourceFile | None:
        relpath = relpath.replace(os.sep, "/")
        if relpath not in self._cache:
            absolute = os.path.join(self.root, relpath)
            if not os.path.exists(absolute):
                return None
            self._cache[relpath] = SourceFile(self.root, relpath)
        return self._cache[relpath]

    def pkg_path(self, *parts: str) -> str | None:
        """Repo-relative path of a module inside the package."""
        if self.package is None:
            return None
        return "/".join((self.package,) + parts)

    def read_text(self, relpath: str) -> str | None:
        absolute = os.path.join(self.root, relpath)
        if not os.path.exists(absolute):
            return None
        with open(absolute, encoding="utf-8", errors="replace") as f:
            return f.read()


# -- pass registry -----------------------------------------------------

# pass-id -> (callable, one-line description). Populated by
# register_passes() below to keep import order simple.
PASSES: dict = {}


def _load_passes() -> None:
    if PASSES:
        return
    from .passes import (
        concurrency,
        donation,
        eviction_lock,
        exception_status,
        frame_monopoly,
        knobs,
        metric_surface,
        provenance_vocabulary,
        trace_discipline,
    )

    for mod in (
        donation, knobs, metric_surface, trace_discipline,
        frame_monopoly, concurrency, exception_status,
        provenance_vocabulary, eviction_lock,
    ):
        PASSES[mod.PASS_ID] = (mod.run, mod.DESCRIPTION)


def run_repo(
    root: str,
    select: list[str] | None = None,
) -> tuple[list[Violation], list[Violation], int]:
    """Run passes against a tree.

    Returns ``(violations, pragma_errors, suppressed_count)``:
    ``violations`` are unsuppressed findings; ``pragma_errors`` are
    misused pragmas (missing reason / unknown id / suppressing
    nothing) and are never themselves suppressible.
    """
    _load_passes()
    repo = Repo(root)
    chosen = select or list(PASSES)
    unknown = [p for p in chosen if p not in PASSES]
    if unknown:
        raise SystemExit(
            f"unknown pass id(s) {unknown}; known: {sorted(PASSES)}"
        )
    raw: list[Violation] = []
    for pass_id in chosen:
        fn, _desc = PASSES[pass_id]
        raw.extend(fn(repo))
    # Parse failures in scanned files surface once, unsuppressible.
    pragma_errors: list[Violation] = []
    for rel in repo.iter_py():
        src = repo.source(rel)
        if src is not None and src.parse_error:
            pragma_errors.append(
                Violation("framework", rel, 1, src.parse_error)
            )

    violations: list[Violation] = []
    suppressed = 0
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.pass_id)):
        src = repo.source(v.path)
        pragma = src.pragmas.get(v.line) if src is not None else None
        if pragma is not None and pragma.pass_id == v.pass_id:
            pragma.used = True
            if pragma.reason:
                suppressed += 1
                continue
            # Reason missing: the violation stands AND the pragma is
            # flagged — an unexplained suppression documents nothing.
        violations.append(v)
    # Pragma hygiene across every scanned file (selected passes only:
    # a fixture run for one pass must not trip over pragmas aimed at
    # another).
    for rel in repo.iter_py():
        src = repo.source(rel)
        if src is None:
            continue
        for pragma in src.pragmas.values():
            if pragma.pass_id not in PASSES:
                pragma_errors.append(Violation(
                    "pragma", rel, pragma.line,
                    f"pragma names unknown pass id {pragma.pass_id!r}",
                ))
                continue
            if pragma.pass_id not in chosen:
                continue
            if not pragma.reason:
                pragma_errors.append(Violation(
                    "pragma", rel, pragma.line,
                    f"suppression ok[{pragma.pass_id}] carries no reason "
                    "(a pragma must say WHY the finding is fine)",
                ))
            elif not pragma.used:
                pragma_errors.append(Violation(
                    "pragma", rel, pragma.line,
                    f"suppression ok[{pragma.pass_id}] suppresses nothing "
                    "(stale pragma — the code it excused is gone)",
                ))
    return violations, pragma_errors, suppressed
