"""concurrency: thread lifecycle ownership + no blocking under the lock.

Two rules, both born from real incidents in this repo's history (the
ack-resync storm flake, the width-ladder warmup threads outliving a
test daemon):

1. **Every thread has an owner.** A ``threading.Thread`` must either
   be daemonized (``daemon=True`` — the process's exit is its owner)
   or be joined by the code that spawned it: a function-local thread
   joins in its enclosing function; a thread stored on ``self`` joins
   somewhere in its class (the stop/close/shutdown path). A
   non-daemon, never-joined thread keeps a dead component's work alive
   and starves whatever runs next.

2. **No blocking call while holding the dispatch lock.** The dispatch
   lock serializes detector-state advancement; every receiver thread
   and the pump contend on it. A ``time.sleep``/socket op/``.join``/
   ``.result``/``.wait`` inside ``with ..._dispatch_lock`` turns one
   slow peer into a stalled ingest path. Snapshot under the lock,
   block outside it — the discipline replication/checkpoint/warmup all
   follow.
"""

from __future__ import annotations

import ast

from ..core import ImportMap, Repo, SourceFile, Violation, dotted

PASS_ID = "concurrency"
DESCRIPTION = (
    "threads daemonized or joined by their owner; no blocking calls "
    "inside `with ..._dispatch_lock`"
)

LOCK_NEEDLE = "_dispatch_lock"

# Dotted-call prefixes considered blocking inside the dispatch lock.
BLOCKING_PREFIXES = (
    "time.sleep", "socket.", "subprocess.", "requests.",
    "urllib.request.",
)
# Method names considered blocking when invoked on anything inside the
# locked region (join/result/wait are the synchronization verbs; a
# str.join would be `", ".join(...)` whose receiver is a Constant —
# excluded below).
BLOCKING_METHODS = {"join", "result", "wait", "acquire", "recv", "accept"}


def _thread_spawn(node: ast.Call, imap: ImportMap) -> bool:
    target = imap.resolve_call(node.func)
    return target in ("threading.Thread", "threading.Timer")


def _is_daemon(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and bool(kw.value.value)
    return False


def _has_thread_join(scope: ast.AST | None) -> bool:
    """True when the scope contains a ``.join()`` call that could be a
    thread join — i.e. NOT a string join (Constant receiver like
    ``", ".join(...)``) and not ``os.path.join``. Without this
    distinction one log-formatting str.join anywhere in a class would
    vacuously satisfy the ownership rule for every thread in it."""
    if scope is None:
        return False
    for n in ast.walk(scope):
        if not (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
        ):
            continue
        recv = n.func.value
        if isinstance(recv, ast.Constant):
            continue  # ", ".join(...) — a string join
        if dotted(recv) in ("os.path", "posixpath", "ntpath"):
            continue
        return True
    return False


def _joined_nearby(src: SourceFile, node: ast.Call) -> bool:
    """Heuristic ownership check: a plausible thread `.join()` in the
    enclosing function, or (for `self.x = Thread(...)`) anywhere in
    the class — the stop/close path that owns the thread."""
    return _has_thread_join(
        src.enclosing_function(node)
    ) or _has_thread_join(src.enclosing_class(node))


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    scan = repo.iter_py(repo.package) if repo.package else []
    scan += repo.iter_py("scripts")
    for rel in sorted(set(scan)):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        imap = ImportMap(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            # Rule 1: thread ownership.
            if _thread_spawn(node, imap):
                if not _is_daemon(node) and not _joined_nearby(src, node):
                    out.append(Violation(
                        PASS_ID, rel, node.lineno,
                        "non-daemon Thread with no join in its owner "
                        "(enclosing function/class): daemonize it, or "
                        "join it from the stop/close path that owns it",
                    ))
                continue
            # Rule 2: blocking call under the dispatch lock.
            if not src.inside_with_matching(node, LOCK_NEEDLE):
                continue
            target = imap.resolve_call(node.func) or ""
            blocking = any(
                target == p or target.startswith(p)
                for p in BLOCKING_PREFIXES
            )
            if not blocking and isinstance(node.func, ast.Attribute):
                recv = node.func.value
                if (
                    node.func.attr in BLOCKING_METHODS
                    and not isinstance(recv, ast.Constant)
                    and dotted(recv) != "os.path"
                ):
                    blocking = True
            if blocking:
                out.append(Violation(
                    PASS_ID, rel, node.lineno,
                    f"blocking call `{src.segment(node.func)}()` while "
                    f"holding {LOCK_NEEDLE}: every receiver thread and "
                    "the pump contend on this lock — copy under the "
                    "lock, block outside it",
                ))
    return out
