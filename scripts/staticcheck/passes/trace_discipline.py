"""trace-discipline: one span/phase name table, dashboards in sync.

The self-telemetry vocabulary (``runtime/selftrace.py``: ``SPAN_*``
span names, ``PHASE_*`` phase labels) is what Jaeger searches, the
``anomaly_phase_seconds{phase=}`` histogram series and the Grafana
panels are written against — the metric-surface story, replayed for
spans. Drift modes this pass closes (mirroring metric-surface):

1. **Stray literal.** A span/phase recorded with an inline string
   (``trace.span("detector.rogue", ...)`` /
   ``self._phase("decode2", ...)``) bypasses the table: it can typo
   silently, mint an undashboarded histogram series, and fork the
   Jaeger vocabulary. Every call to a span/phase construction site
   (``span`` / ``_phase`` / ``phase_observe`` / ``_observe_phase``)
   under the detector's ``runtime/`` package (outside selftrace.py
   itself) must reference a ``selftrace`` constant. Scoped to
   ``runtime/`` deliberately: the SHOP SIMULATION's services emit
   route-named spans (``services/base.py span()``) — that vocabulary
   is the workload under test, unbounded by design, and none of this
   pass's business.

2. **Orphan.** A ``SPAN_*``/``PHASE_*`` constant nothing references is
   a dead vocabulary entry — the tracer and its consumers have forked.

3. **Dangling dashboard label.** A dashboard Query whose ``matchers``
   pin a ``phase=`` value that no ``PHASE_*`` constant declares graphs
   nothing, forever.
"""

from __future__ import annotations

import ast

from ..core import Repo, Violation, dotted

PASS_ID = "trace-discipline"
DESCRIPTION = (
    "span/phase names come from runtime/selftrace.py constants; "
    "no stray literals, no orphans, dashboard phase labels resolve"
)

SELFTRACE_REL = ("runtime", "selftrace.py")
DASHBOARDS_REL = ("telemetry", "dashboards.py")
# Call names that CONSTRUCT a span or phase sample (first positional
# arg is the name/label). ``span`` is BatchTrace's recorder; ``_phase``
# is the ingest pool's ledger; ``phase_observe``/``_observe_phase``
# the histogram hook (callable attr or daemon method); ``flush_segment``
# takes a dict keyed by phase labels — only its literal-keyed dict
# displays are checkable and checked.
CONSTRUCTORS = {"span", "_phase", "phase_observe", "_observe_phase"}
PREFIXES = ("SPAN_", "PHASE_")


def load_constants(repo: Repo) -> dict[str, str]:
    """SPAN_*/PHASE_* name → string value from runtime/selftrace.py."""
    rel = repo.pkg_path(*SELFTRACE_REL)
    src = repo.source(rel) if rel else None
    consts: dict[str, str] = {}
    if src is None or src.tree is None:
        return consts
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith(PREFIXES):
                    consts[t.id] = node.value.value
    return consts


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    if repo.package is None:
        return out
    consts = load_constants(repo)
    if not consts:
        return out  # no vocabulary declared — nothing to police
    selftrace_rel = repo.pkg_path(*SELFTRACE_REL)
    referenced: set[str] = set()

    runtime_prefix = f"{repo.package}/runtime/"
    for rel in repo.iter_py(repo.package):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        for node in ast.walk(src.tree):
            # Constant references anywhere (incl. selftrace.py's own
            # SPAN_FOR_PHASE projection) count against the orphan rule.
            if isinstance(node, ast.Attribute) and node.attr in consts:
                referenced.add(node.attr)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in consts:
                referenced.add(node.id)
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in CONSTRUCTORS
                and node.args
            ):
                continue
            if rel == selftrace_rel or not rel.startswith(runtime_prefix):
                # selftrace.py builds from locals; outside runtime/
                # the shop simulation's route-named spans are the
                # workload, not detector self-telemetry.
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append(Violation(
                    PASS_ID, rel, node.lineno,
                    f"span/phase name {arg.value!r} constructed from a "
                    "string literal — names must come from the "
                    "runtime/selftrace.py constant table (a typo here "
                    "forks the Jaeger/histogram vocabulary silently)",
                ))
            else:
                name = dotted(arg)
                if name is not None:
                    referenced.add(name.split(".")[-1])

    # Orphans: a vocabulary entry nothing references.
    src = repo.source(selftrace_rel) if selftrace_rel else None
    const_line: dict[str, int] = {}
    if src is not None and src.tree is not None:
        for node in src.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        const_line[t.id] = node.lineno
    for cname in consts:
        if cname not in referenced:
            out.append(Violation(
                PASS_ID, selftrace_rel, const_line.get(cname, 1),
                f"{cname} ({consts[cname]!r}) is never referenced by "
                "any span/phase construction site — a dead vocabulary "
                "entry",
            ))

    # Dashboard phase labels must resolve against the table.
    phase_values = {
        v for k, v in consts.items() if k.startswith("PHASE_")
    }
    dash_rel = repo.pkg_path(*DASHBOARDS_REL)
    dash_src = repo.source(dash_rel) if dash_rel else None
    if dash_src is not None and dash_src.tree is not None:
        for node in ast.walk(dash_src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Query"
            ):
                continue
            for kw in node.keywords:
                if kw.arg != "matchers" or not isinstance(
                    kw.value, ast.Dict
                ):
                    continue
                for key, val in zip(kw.value.keys, kw.value.values):
                    if (
                        isinstance(key, ast.Constant)
                        and key.value == "phase"
                        and isinstance(val, ast.Constant)
                        and val.value not in phase_values
                    ):
                        out.append(Violation(
                            PASS_ID, dash_rel, node.lineno,
                            f"dashboard panel pins phase={val.value!r} "
                            "but no runtime/selftrace.py PHASE_* "
                            "constant declares it — the panel would "
                            "graph nothing, forever",
                        ))
    return out
