"""exception-status: no silent swallows; one status taxonomy.

The runtime's error-handling contract, two halves:

1. **Exceptions.** No bare ``except:`` anywhere in the package (it
   eats ``KeyboardInterrupt``/``SystemExit`` and wedges shutdown). In
   ``runtime/``, a broad handler (``except Exception`` /
   ``BaseException``) must carry a justification — a comment on the
   except clause or immediately inside the handler explaining WHY
   catching everything is right there (the repo's ``# noqa: BLE001 —
   reason`` convention, or a staticcheck pragma). An unexplained broad
   catch is where real faults go to disappear; ~50 existing sites all
   carry their reasons, and this pass keeps it that way.

2. **Status taxonomy.** The HTTP/gRPC surfaces answer ONLY from the
   registered status sets (deploy/README's fault matrix is written
   against them): HTTP {200, 204, 400, 404, 405, 411, 413, 415, 429,
   500, 503} and gRPC {OK, INVALID_ARGUMENT, NOT_FOUND,
   RESOURCE_EXHAUSTED, UNAVAILABLE, INTERNAL, UNIMPLEMENTED}. A
   handler inventing a new code (or typoing one — 419, ``EXHAUSTED``)
   silently breaks every client retry policy written against the
   documented set. Checked over literal ``send_response``/
   ``send_error`` arguments, literal ``status =`` assignments in the
   server modules, and ``StatusCode.X`` attribute references.
"""

from __future__ import annotations

import ast

import re

from ..core import PRAGMA_RE, Repo, SourceFile, Violation

# Content-free comment markers that do NOT count as a written reason:
# a justification must say WHY, not merely wave off another linter.
_DIRECTIVE_RE = re.compile(
    r"noqa(:\s*[A-Z0-9, ]+)?"
    r"|type:\s*ignore(\[[^\]]*\])?"
    r"|pragma:\s*no\s*cover"
    r"|(?i:todo|fixme|xxx)\b[:\s]*"
)

PASS_ID = "exception-status"
DESCRIPTION = (
    "no bare except; broad excepts in runtime/ carry reasons; "
    "HTTP/gRPC handlers answer only from the registered status sets"
)

HTTP_TAXONOMY = {200, 204, 400, 404, 405, 411, 413, 415, 429, 500, 503}
GRPC_TAXONOMY = {
    "OK", "INVALID_ARGUMENT", "NOT_FOUND", "RESOURCE_EXHAUSTED",
    "UNAVAILABLE", "INTERNAL", "UNIMPLEMENTED", "DEADLINE_EXCEEDED",
}
# Server modules whose integer status literals are HTTP answer codes.
HTTP_SERVER_MODULES = (
    "runtime/otlp.py", "runtime/query.py", "telemetry/metrics.py",
)
BROAD = {"Exception", "BaseException"}


def _has_justification(src: SourceFile, handler: ast.ExceptHandler) -> bool:
    """A comment on the except line, between it and the first
    statement, or on the first statement's line counts as the reason
    (the repo's `# noqa: BLE001 — why` convention lives there).

    Real comments only (tokenizer, so a ``#`` inside a string literal
    doesn't count); a ``staticcheck: ok[...]`` pragma is NOT a
    free-text reason — the violation must still be emitted so the
    suppression machinery consumes it (marks it used, enforces its
    reason) instead of reporting the pragma as stale — and neither is
    a content-free lint marker (bare ``# noqa``, ``# type: ignore``,
    ``# TODO``): some explanatory text must remain after stripping
    those."""
    first = handler.body[0].lineno if handler.body else handler.lineno
    for ln in range(handler.lineno, min(first, len(src.lines)) + 1):
        comment = src.comments.get(ln)
        if not comment:
            continue
        text = PRAGMA_RE.sub("", comment)
        text = _DIRECTIVE_RE.sub("", text)
        if re.search(r"\w", text):
            return True
    return False


def _status_ints(node: ast.AST) -> list[tuple[int, int]]:
    """(value, line) integer literals inside a status expression —
    resolves the `503 if degraded else 200` conditional shape."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool):
            out.append((sub.value, getattr(sub, "lineno", 0)))
    return out


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    if repo.package is None:
        return out
    runtime_prefix = f"{repo.package}/runtime/"
    http_modules = {f"{repo.package}/{m}" for m in HTTP_SERVER_MODULES}
    for rel in repo.iter_py(repo.package):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        in_runtime = rel.startswith(runtime_prefix)
        for node in ast.walk(src.tree):
            # -- exceptions --------------------------------------------
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    out.append(Violation(
                        PASS_ID, rel, node.lineno,
                        "bare `except:` swallows KeyboardInterrupt/"
                        "SystemExit — catch Exception (with a reason) "
                        "or the specific type",
                    ))
                    continue
                if not in_runtime:
                    continue
                names = [
                    n.id for n in ast.walk(node.type)
                    if isinstance(n, ast.Name)
                ]
                if not any(n in BROAD for n in names):
                    continue
                if not _has_justification(src, node):
                    out.append(Violation(
                        PASS_ID, rel, node.lineno,
                        "broad `except Exception` with no stated reason: "
                        "narrow it, or justify the catch-all in a "
                        "comment on the clause (`# noqa: BLE001 — why`)",
                    ))
                continue
            # -- status taxonomy ---------------------------------------
            if rel in http_modules and isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("send_response", "send_error") and \
                    node.args:
                for value, line in _status_ints(node.args[0]):
                    if value not in HTTP_TAXONOMY:
                        out.append(Violation(
                            PASS_ID, rel, line or node.lineno,
                            f"HTTP status {value} is outside the "
                            f"registered taxonomy {sorted(HTTP_TAXONOMY)} "
                            "— the fault matrix and client retry "
                            "policies are written against that set",
                        ))
            elif rel in http_modules and isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "status":
                        for value, line in _status_ints(node.value):
                            if 100 <= value <= 599 and \
                                    value not in HTTP_TAXONOMY:
                                out.append(Violation(
                                    PASS_ID, rel, line or node.lineno,
                                    f"HTTP status {value} assigned but "
                                    "outside the registered taxonomy "
                                    f"{sorted(HTTP_TAXONOMY)}",
                                ))
            elif in_runtime and isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "StatusCode" and \
                    node.attr.isupper():
                if node.attr not in GRPC_TAXONOMY:
                    out.append(Violation(
                        PASS_ID, rel, node.lineno,
                        f"gRPC StatusCode.{node.attr} is outside the "
                        f"registered taxonomy {sorted(GRPC_TAXONOMY)}",
                    ))
    return out
