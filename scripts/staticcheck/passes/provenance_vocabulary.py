"""provenance-vocabulary: one head/reason table, every surface in sync.

The evidence vocabulary (``runtime/provenance.py``: ``HEAD_*`` head
kinds, ``REASON_*`` signal names) is what an evidence bundle's
``heads``/``signals`` fields carry, what ``/query/explain`` consumers
filter on, and what any Grafana panel pinning a ``head=``/``signal=``
label graphs — the trace-discipline story, replayed for verdicts.
Drift modes this pass closes:

1. **Unknown literal.** A dict display under ``runtime/`` whose
   ``head``/``heads`` entry names a head kind no ``HEAD_*`` constant
   declares (or whose ``signal``/``signals`` entry names a signal no
   ``REASON_*`` constant declares) mints a vocabulary fork: the
   bundle self-describes with a word nothing downstream understands,
   and a replica/history answer can never be joined against it.
   Literals carrying a DECLARED value pass — the fence is the
   vocabulary, not the spelling.

2. **Orphan.** A ``HEAD_*``/``REASON_*`` constant nothing references
   (the ``HEAD_FOR_REASON`` projection counts, like trace-discipline's
   ``SPAN_FOR_PHASE``) is a dead vocabulary entry.

3. **Dangling dashboard label.** A dashboard Query whose ``matchers``
   pin ``head=``/``signal=`` to a value the table does not declare
   graphs nothing, forever.
"""

from __future__ import annotations

import ast

from ..core import Repo, Violation

PASS_ID = "provenance-vocabulary"
DESCRIPTION = (
    "evidence head/signal names come from runtime/provenance.py "
    "constants; no unknown literals, no orphans, dashboard labels "
    "resolve"
)

PROVENANCE_REL = ("runtime", "provenance.py")
DASHBOARDS_REL = ("telemetry", "dashboards.py")
PREFIXES = ("HEAD_", "REASON_")
# The no-signal fallback pipeline.py stamps on exemplar entries when a
# flag carried no per-signal evidence — deliberate, and not a head.
EXTRA_SIGNALS = {"flag"}
# Dict keys that claim membership in each half of the vocabulary.
HEAD_KEYS = {"head", "heads"}
SIGNAL_KEYS = {"signal", "signals"}


def load_constants(repo: Repo) -> dict[str, str]:
    """HEAD_*/REASON_* name → string value from runtime/provenance.py."""
    rel = repo.pkg_path(*PROVENANCE_REL)
    src = repo.source(rel) if rel else None
    consts: dict[str, str] = {}
    if src is None or src.tree is None:
        return consts
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith(PREFIXES):
                    consts[t.id] = node.value.value
    return consts


def _literal_strings(node: ast.AST):
    """(value, lineno) for a string constant or a list/tuple/set
    display of string constants — the only literal shapes a
    head/signal entry legitimately takes."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node.lineno
    elif isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                yield elt.value, elt.lineno


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    if repo.package is None:
        return out
    consts = load_constants(repo)
    if not consts:
        return out  # no vocabulary declared — nothing to police
    head_values = {v for k, v in consts.items() if k.startswith("HEAD_")}
    signal_values = {
        v for k, v in consts.items() if k.startswith("REASON_")
    } | EXTRA_SIGNALS
    provenance_rel = repo.pkg_path(*PROVENANCE_REL)
    referenced: set[str] = set()

    runtime_prefix = f"{repo.package}/runtime/"
    for rel in repo.iter_py(repo.package):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        for node in ast.walk(src.tree):
            # Constant references anywhere (incl. provenance.py's own
            # HEAD_FOR_REASON projection) count against the orphan rule.
            if isinstance(node, ast.Attribute) and node.attr in consts:
                referenced.add(node.attr)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in consts:
                referenced.add(node.id)
            if not isinstance(node, ast.Dict):
                continue
            if rel == provenance_rel or not rel.startswith(runtime_prefix):
                # provenance.py IS the table; outside runtime/ nothing
                # constructs evidence bundles.
                continue
            for key, val in zip(node.keys, node.values):
                if not (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                ):
                    continue
                if key.value in HEAD_KEYS:
                    allowed, half = head_values, "HEAD_*"
                elif key.value in SIGNAL_KEYS:
                    allowed, half = signal_values, "REASON_*"
                else:
                    continue
                for text, lineno in _literal_strings(val):
                    if text not in allowed:
                        out.append(Violation(
                            PASS_ID, rel, lineno,
                            f"{key.value!r} entry names {text!r} but no "
                            f"runtime/provenance.py {half} constant "
                            "declares it — an evidence-vocabulary fork "
                            "nothing downstream can join against",
                        ))

    # Orphans: a vocabulary entry nothing references.
    src = repo.source(provenance_rel) if provenance_rel else None
    const_line: dict[str, int] = {}
    if src is not None and src.tree is not None:
        for node in src.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        const_line[t.id] = node.lineno
    for cname in consts:
        if cname not in referenced:
            out.append(Violation(
                PASS_ID, provenance_rel, const_line.get(cname, 1),
                f"{cname} ({consts[cname]!r}) is never referenced — a "
                "dead vocabulary entry (wire it into HEAD_FOR_REASON "
                "or a construction site, or delete it)",
            ))

    # Dashboard head/signal labels must resolve against the table.
    dash_rel = repo.pkg_path(*DASHBOARDS_REL)
    dash_src = repo.source(dash_rel) if dash_rel else None
    if dash_src is not None and dash_src.tree is not None:
        for node in ast.walk(dash_src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Query"
            ):
                continue
            for kw in node.keywords:
                if kw.arg != "matchers" or not isinstance(
                    kw.value, ast.Dict
                ):
                    continue
                for key, val in zip(kw.value.keys, kw.value.values):
                    if not (
                        isinstance(key, ast.Constant)
                        and isinstance(val, ast.Constant)
                    ):
                        continue
                    if key.value == "head" and val.value not in head_values:
                        bad_half = "HEAD_*"
                    elif (
                        key.value == "signal"
                        and val.value not in signal_values
                    ):
                        bad_half = "REASON_*"
                    else:
                        continue
                    out.append(Violation(
                        PASS_ID, dash_rel, node.lineno,
                        f"dashboard panel pins {key.value}="
                        f"{val.value!r} but no runtime/provenance.py "
                        f"{bad_half} constant declares it — the panel "
                        "would graph nothing, forever",
                    ))
    return out
