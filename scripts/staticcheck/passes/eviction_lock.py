"""eviction-lock: intern-id retirement happens under the dispatch lock.

The keyspace evictor's safety contract (runtime/keyspace.py →
``SpanTensorizer.retire_services``): the moment a retirement publishes
its snapshot, a freed id is assignable to a brand-new service on the
very next flush — and that flush scatters into whatever the old
occupant's sketch/head rows still hold. The ONLY thing that makes the
recycle safe is ordering: fold + zero the rows, then retire, all
inside one ``with pipeline._dispatch_lock`` critical section so no
dispatch can interleave between the zero and the republish.

This pass pins the lock half of that contract lexically: every call
to ``.retire_services(...)`` anywhere in the package must sit inside a
``with`` statement whose context expression mentions the dispatch
lock. (The fold-before-retire ordering is behavioral and lives in
tests/test_keyspace.py; lexical lock scope is what an analyzer can
prove and what a refactor is most likely to silently drop.)
"""

from __future__ import annotations

import ast

from ..core import Repo, Violation

PASS_ID = "eviction-lock"
DESCRIPTION = (
    "`.retire_services(...)` only inside `with ..._dispatch_lock` "
    "(id recycling must not interleave with dispatch)"
)

LOCK_NEEDLE = "_dispatch_lock"
RETIRE_METHOD = "retire_services"


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    scan = repo.iter_py(repo.package) if repo.package else []
    for rel in sorted(set(scan)):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == RETIRE_METHOD
            ):
                continue
            # The definition site's own intern-lock body never calls
            # itself; any OTHER call — whatever the receiver is named
            # (tz, self.tensorizer, pipeline.tensorizer) — needs the
            # dispatch lock around it.
            if src.inside_with_matching(node, LOCK_NEEDLE):
                continue
            out.append(Violation(
                PASS_ID, rel, node.lineno,
                f"`{src.segment(node.func)}(...)` outside "
                f"`with ...{LOCK_NEEDLE}`: a freed id is assignable on "
                "the next flush the instant the snapshot republishes — "
                "fold + zero the rows and retire inside ONE dispatch-"
                "lock critical section",
            ))
    return out
