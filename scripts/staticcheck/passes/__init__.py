# Pass modules. Each exposes PASS_ID, DESCRIPTION and run(repo).
