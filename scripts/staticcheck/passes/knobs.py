"""knob-discipline: every env knob lives in ONE ``*_KNOBS`` registry.

The repo's config contract (``utils/config.py``): a knob family is ONE
literal dict — env name → (type, default, meaning) — consumed by the
daemon, the compose overlay, the k8s generator and the checkers, so
the knob set can never drift between surfaces. This pass enforces it
structurally:

1. **No stray reads.** Every ``os.environ``/``os.getenv`` read outside
   ``utils/config.py`` must name a registered knob (string literal
   resolvable against the union of all ``*_KNOBS`` registries). Env
   *writes* (``environ[k] = v`` / ``setdefault``) and whole-environment
   passthrough (``dict(os.environ)`` / ``os.environ.copy()`` for
   subprocess spawning) are fine — the contract is about configuration
   reads. A read whose key is not a literal (helper indirection) is
   checked at the helper's call sites instead: a function whose
   parameter flows into an environ read is an *env accessor*, and each
   of its call sites must pass a registered literal.

2. **Deployed registries are threaded.** Registries named in
   ``config.DEPLOYED_KNOB_REGISTRIES`` must have every knob present in
   ``runtime/daemon.py`` (a string constant in its AST — the consuming
   subscript), in ``deploy/docker-compose.anomaly.yml``, and the k8s
   generator must reference the registry object itself (it consumes
   the dict, so per-knob greps there would be redundant). Harness
   registries (faultwire chaos knobs, bench/shop scaffolding) only
   legitimize reads — a chaos proxy has no business in the fleet
   compose file.

3. **No dead knobs.** Every registered knob must be read somewhere
   outside ``utils/config.py`` (as a string constant in a scanned
   module) — a knob nobody consumes is documentation rot wearing a
   registry entry.
"""

from __future__ import annotations

import ast
import re

from ..core import ImportMap, Repo, SourceFile, Violation, dotted

PASS_ID = "knob-discipline"
DESCRIPTION = (
    "os.environ reads must resolve to a *_KNOBS registry entry; "
    "deployed registries threaded through daemon/compose/k8s; "
    "no dead knobs"
)

CONFIG_REL = ("utils", "config.py")
DAEMON_REL = ("runtime", "daemon.py")
K8S_REL = ("utils", "k8s.py")
COMPOSE_REL = "deploy/docker-compose.anomaly.yml"


def load_registries(src: SourceFile) -> tuple[dict[str, dict], tuple]:
    """(registries, deployed_names) from utils/config.py's AST."""
    registries: dict[str, dict] = {}
    deployed: tuple = ()
    if src.tree is None:
        return registries, deployed
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id.endswith("_KNOBS"):
                try:
                    registries[t.id] = ast.literal_eval(value)
                except ValueError:
                    continue  # non-literal registry: config's own tests
            elif t.id == "DEPLOYED_KNOB_REGISTRIES":
                try:
                    deployed = tuple(ast.literal_eval(value))
                except ValueError:
                    pass
    return registries, deployed


def compose_defines(compose_text: str, knob: str) -> bool:
    """True when the compose file DEFINES the knob: an env entry
    ``- KNOB=...`` / ``KNOB: ...`` / bare ``- KNOB`` passthrough on a
    non-comment line. A raw substring test would be fooled by prefix
    knobs (``ANOMALY_CHECKPOINT`` matching inside
    ``ANOMALY_CHECKPOINT_INTERVAL_S``) and by mentions in comments —
    exactly the silent-drift this pass exists to prevent."""
    pattern = re.compile(
        rf"^\s*-?\s*[\"']?{re.escape(knob)}[\"']?\s*([=:]|$)"
    )
    for line in compose_text.splitlines():
        code = line.split("#", 1)[0]
        if pattern.match(code):
            return True
    return False


def _env_read_key(node: ast.Call, imap: ImportMap) -> tuple[bool, ast.AST | None]:
    """(is_env_read, key_node) for a call; key_node None = no args."""
    target = imap.resolve_call(node.func)
    if target in ("os.getenv", "os.environ.get"):
        return True, (node.args[0] if node.args else None)
    return False, None


def _is_environ_expr(node: ast.AST, imap: ImportMap) -> bool:
    name = dotted(node)
    if name is None:
        return False
    head = name.split(".")[0]
    resolved = imap.aliases.get(head, head)
    full = ".".join([resolved] + name.split(".")[1:])
    return full in ("os.environ", "environ")


def _collect_accessors(src: SourceFile, imap: ImportMap) -> dict[str, int]:
    """Function name → param index whose value flows into an environ
    read key (the helper-indirection case: ``def env_int(name, ...):
    ... os.environ.get(name)``)."""
    accessors: dict[str, int] = {}
    if src.tree is None:
        return accessors
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in fn.args.args]
        for node in ast.walk(fn):
            key = None
            if isinstance(node, ast.Call):
                is_read, key = _env_read_key(node, imap)
                if not is_read:
                    continue
            elif isinstance(node, ast.Subscript) and _is_environ_expr(
                node.value, imap
            ):
                key = node.slice
            else:
                continue
            if isinstance(key, ast.Name) and key.id in params:
                accessors[fn.name] = params.index(key.id)
    return accessors


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    config_rel = repo.pkg_path(*CONFIG_REL)
    config_src = repo.source(config_rel) if config_rel else None
    registries: dict[str, dict] = {}
    deployed: tuple = ()
    if config_src is not None:
        registries, deployed = load_registries(config_src)
    known = {k for reg in registries.values() for k in reg}

    # Env accessors declared in config.py (env_str/env_int/...): their
    # call sites elsewhere must pass registered literals.
    accessor_params: dict[str, int] = {}
    if config_src is not None and config_src.tree is not None:
        accessor_params = _collect_accessors(
            config_src, ImportMap(config_src.tree)
        )

    scanned: list[str] = []
    for rel in repo.iter_py():
        if config_rel is not None and rel == config_rel:
            continue  # the registry module is the one legitimate home
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        scanned.append(rel)
        imap = ImportMap(src.tree)
        local_accessors = _collect_accessors(src, imap)

        def check_key(key: ast.AST | None, line: int, how: str) -> None:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                if key.value not in known:
                    out.append(Violation(
                        PASS_ID, rel, line,
                        f"{how} reads env {key.value!r} which is not in "
                        "any utils/config.py *_KNOBS registry — register "
                        "it (one literal dict per knob family) or read "
                        "it through a registered family",
                    ))
            else:
                out.append(Violation(
                    PASS_ID, rel, line,
                    f"{how} reads a non-literal env key — unresolvable "
                    "against the knob registries; thread the literal "
                    "name through, or use a config.py accessor",
                ))

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                is_read, key = _env_read_key(node, imap)
                if is_read:
                    fn = src.enclosing_function(node)
                    if (
                        fn is not None
                        and isinstance(key, ast.Name)
                        and fn.name in local_accessors
                    ):
                        continue  # the accessor body; call sites checked
                    check_key(key, node.lineno, "call")
                    continue
                # Accessor call sites (config.env_int("NAME", ...) or a
                # locally defined helper).
                target = imap.resolve_call(node.func)
                base = target.split(".")[-1] if target else None
                idx = accessor_params.get(base) if base else None
                if idx is None and base in local_accessors:
                    idx = local_accessors[base]
                if idx is not None and len(node.args) > idx:
                    check_key(
                        node.args[idx], node.lineno, f"{base}() call"
                    )
            elif isinstance(node, ast.Subscript) and _is_environ_expr(
                node.value, imap
            ):
                if isinstance(node.ctx, ast.Load):
                    fn = src.enclosing_function(node)
                    if (
                        fn is not None
                        and isinstance(node.slice, ast.Name)
                        and fn.name in local_accessors
                    ):
                        continue
                    check_key(node.slice, node.lineno, "subscript")
            elif isinstance(node, ast.Compare) and any(
                _is_environ_expr(c, imap) for c in node.comparators
            ):
                left = node.left
                check_key(left, node.lineno, "membership test")

    # -- threading + dead-knob checks ---------------------------------
    if config_src is None:
        return out
    daemon_rel = repo.pkg_path(*DAEMON_REL)
    daemon_src = repo.source(daemon_rel) if daemon_rel else None
    daemon_consts: set[str] = set()
    if daemon_src is not None and daemon_src.tree is not None:
        daemon_consts = {
            n.value for n in ast.walk(daemon_src.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
    compose_text = repo.read_text(COMPOSE_REL)
    k8s_rel = repo.pkg_path(*K8S_REL)
    k8s_src = repo.source(k8s_rel) if k8s_rel else None
    k8s_names: set[str] = set()
    if k8s_src is not None and k8s_src.tree is not None:
        k8s_names = {
            n.id for n in ast.walk(k8s_src.tree) if isinstance(n, ast.Name)
        }
        # An `from .config import X_KNOBS` counts too: the import IS
        # the registry reference the check demands (vs copied strings).
        k8s_names |= set(ImportMap(k8s_src.tree).aliases)

    cfg_line = {  # registry name -> declaration line, for messages
        t.id: node.lineno
        for node in (config_src.tree.body if config_src.tree else [])
        if isinstance(node, (ast.Assign, ast.AnnAssign))
        for t in (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        if isinstance(t, ast.Name)
    }
    for reg_name in deployed:
        reg = registries.get(reg_name)
        line = cfg_line.get(reg_name, 1)
        if reg is None:
            out.append(Violation(
                PASS_ID, config_rel, line,
                f"DEPLOYED_KNOB_REGISTRIES names {reg_name} but no such "
                "registry is declared",
            ))
            continue
        if k8s_src is not None and reg_name not in k8s_names:
            out.append(Violation(
                PASS_ID, k8s_rel, 1,
                f"k8s generator does not consume the {reg_name} registry "
                "(it must import the dict, not copy its strings)",
            ))
        for knob in reg:
            if daemon_src is not None and knob not in daemon_consts:
                out.append(Violation(
                    PASS_ID, config_rel, line,
                    f"{reg_name}[{knob!r}] is not threaded through "
                    "runtime/daemon.py (no consuming reference)",
                ))
            if compose_text is not None and not compose_defines(
                compose_text, knob
            ):
                out.append(Violation(
                    PASS_ID, config_rel, line,
                    f"{reg_name}[{knob!r}] is not threaded through "
                    f"{COMPOSE_REL}",
                ))
    # Dead knobs: registered but consumed nowhere.
    consumed: set[str] = set()
    for rel in scanned:
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        consumed |= {
            n.value for n in ast.walk(src.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
    for reg_name, reg in registries.items():
        line = cfg_line.get(reg_name, 1)
        for knob in reg:
            if knob not in consumed:
                out.append(Violation(
                    PASS_ID, config_rel, line,
                    f"{reg_name}[{knob!r}] is dead: no module outside "
                    "utils/config.py ever names it",
                ))
    return out
