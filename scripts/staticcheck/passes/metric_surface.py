"""metric-surface: one metric name table, dashboards/docs in sync.

The ``anomaly_*`` / ``app_anomaly_*`` Prometheus family is the
operator surface: Grafana panels (``telemetry/dashboards.py``) and the
ops docs (``deploy/README.md`` / ``README.md``) are written against
the names ``telemetry/metrics.py`` declares. Three drift modes, each
historically reachable by one careless edit:

1. **Stray literal.** A metric constructed with an inline string
   (``registry.gauge_set("app_anomaly_...", ...)``) bypasses the name
   table — it can typo silently and no dashboard/doc check ever sees
   it. Every anomaly-family construction site must reference a
   ``metrics.py`` constant. (External vocabularies — ``container_*``,
   ``otelcol_*``, spanmetrics — are other systems' names and exempt.)

2. **Dangling panel.** A dashboard Query naming an anomaly-family
   metric that no constant declares graphs nothing, forever
   (histogram ``_bucket``/``_sum``/``_count`` suffixes are resolved to
   their base constant first).

3. **Orphan.** A constant no code ever constructs, or one missing
   from the ops docs (``deploy/README.md`` or ``README.md``), is a
   dead or invisible metric — either way the surface and its
   documentation have forked.
"""

from __future__ import annotations

import ast

from ..core import Repo, Violation, dotted

PASS_ID = "metric-surface"
DESCRIPTION = (
    "anomaly metric names come from telemetry/metrics.py constants; "
    "dashboards and deploy docs reference only declared names"
)

METRICS_REL = ("telemetry", "metrics.py")
DASHBOARDS_REL = ("telemetry", "dashboards.py")
FAMILY_PREFIXES = ("anomaly_", "app_anomaly_")
CONSTRUCTORS = {
    "counter_add", "gauge_set", "histogram_observe", "describe",
}
HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _family(name: str) -> bool:
    return name.startswith(FAMILY_PREFIXES)


def _strip_histo(name: str) -> str:
    for suf in HISTO_SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def load_constants(repo: Repo) -> dict[str, str]:
    """UPPER_NAME -> metric string from telemetry/metrics.py."""
    rel = repo.pkg_path(*METRICS_REL)
    src = repo.source(rel) if rel else None
    consts: dict[str, str] = {}
    if src is None or src.tree is None:
        return consts
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.isupper():
                    consts[t.id] = node.value.value
    return consts


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    if repo.package is None:
        return out
    consts = load_constants(repo)
    values = set(consts.values())
    metrics_rel = repo.pkg_path(*METRICS_REL)

    # 1) construction sites across the package. A constant counts as
    #    "constructed" when its value appears as a registry-call
    #    literal OR its NAME is referenced anywhere outside metrics.py
    #    (constants also flow through helpers like the daemon's
    #    _export_counter_delta, where the call site isn't a registry
    #    method).
    used_values: set[str] = set()
    referenced_names: set[str] = set()
    for rel in repo.iter_py(repo.package):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        if rel != metrics_rel:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Attribute) and node.attr in consts:
                    referenced_names.add(node.attr)
                elif isinstance(node, ast.Name) and node.id in consts:
                    referenced_names.add(node.id)
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in CONSTRUCTORS
                and node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if _family(arg.value) and rel != metrics_rel:
                    out.append(Violation(
                        PASS_ID, rel, node.lineno,
                        f"metric {arg.value!r} constructed from a string "
                        "literal — anomaly-family names must come from "
                        "the telemetry/metrics.py constant table (typos "
                        "here are invisible to every other check)",
                    ))
                used_values.add(arg.value)
            else:
                name = dotted(arg)
                if name is not None:
                    const = consts.get(name.split(".")[-1])
                    if const is not None:
                        used_values.add(const)

    # 2) dashboard references.
    dash_rel = repo.pkg_path(*DASHBOARDS_REL)
    dash_src = repo.source(dash_rel) if dash_rel else None
    if dash_src is not None and dash_src.tree is not None:
        for node in ast.walk(dash_src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Query"
                and node.args
            ):
                continue
            kind = (
                node.args[0].value
                if isinstance(node.args[0], ast.Constant) else None
            )
            if kind not in ("rate", "quantile", "instant"):
                continue  # traces/logs/sketch target other datasources
            metric = None
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                metric = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "metric" and isinstance(kw.value, ast.Constant):
                    metric = kw.value.value
            if not isinstance(metric, str) or not _family(metric):
                continue
            base = _strip_histo(metric)
            if base not in values and metric not in values:
                out.append(Violation(
                    PASS_ID, dash_rel, node.lineno,
                    f"dashboard panel queries {metric!r} but no "
                    "telemetry/metrics.py constant declares it — the "
                    "panel would graph nothing, forever",
                ))
            else:
                used_values.add(base if base in values else metric)

    # 3) orphans: every anomaly-family constant must be constructed
    #    somewhere and documented in the ops docs.
    docs = (
        (repo.read_text("deploy/README.md") or "")
        + (repo.read_text("README.md") or "")
    )
    metrics_src = repo.source(metrics_rel) if metrics_rel else None
    const_line: dict[str, int] = {}
    if metrics_src is not None and metrics_src.tree is not None:
        for node in metrics_src.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        const_line[t.id] = node.lineno
    for cname, value in consts.items():
        if not _family(value):
            continue
        line = const_line.get(cname, 1)
        if value not in used_values and cname not in referenced_names:
            out.append(Violation(
                PASS_ID, metrics_rel, line,
                f"{cname} ({value!r}) is never constructed by any "
                "registry call — a dead metric name",
            ))
        if docs and value not in docs:
            out.append(Violation(
                PASS_ID, metrics_rel, line,
                f"{cname} ({value!r}) is not documented in "
                "deploy/README.md or README.md — operators cannot "
                "discover it",
            ))
    return out
