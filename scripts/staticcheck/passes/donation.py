"""donation-race: detector state touched outside the dispatch lock.

Live dispatch DONATES the detector's device buffers: the jitted step
deletes its input arrays Python-side the moment it dispatches
(``jax.jit(..., donate_argnums=...)``), so any other thread reading —
or swapping — ``detector.state`` concurrently races "Array has been
deleted". The repo's rule (previously a memory note, now enforced):
every access to a ``detector.state`` chain outside the model package
happens inside ``with <pipeline>._dispatch_lock``, the same lock
``DetectorPipeline.pump`` holds for the dispatch itself. That covers
reads (snapshot helpers: replication, checkpoint, benches) AND writes
(promotion hydration) — an unlocked swap can be clobbered by a
dispatcher mid-flight just as easily as an unlocked read can touch a
deleted buffer.

Accesses that are provably single-threaded (boot-time hydration before
any dispatcher thread exists) carry the pragma with the proof as the
reason.

Scope: the package outside ``models/`` (the detector/head classes own
their ``self.state``; the pipeline serializes them) plus ``scripts/``
and ``bench.py``. The lock context is recognized lexically: any
enclosing ``with`` whose context expression mentions ``dispatch_lock``
(the pipeline attribute, or a ``dispatch_lock`` parameter a helper
like ``checkpoint.save`` threads through).
"""

from __future__ import annotations

import ast

from ..core import Repo, Violation, dotted

PASS_ID = "donation-race"
DESCRIPTION = (
    "detector.state read/written outside `with ..._dispatch_lock` "
    "(donated device buffers: races 'Array has been deleted')"
)

LOCK_NEEDLE = "dispatch_lock"


def _is_detector_state(node: ast.Attribute) -> bool:
    """True for ``<...>.detector.state`` / ``detector.state`` chains
    (and their ``._asdict()`` snapshot reads, which hang off the same
    Attribute node)."""
    if node.attr != "state":
        return False
    base = dotted(node.value)
    return base is not None and (
        base == "detector" or base.endswith(".detector")
    )


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    scan: list[str] = []
    if repo.package:
        scan += [
            p for p in repo.iter_py(repo.package)
            if not p.startswith(f"{repo.package}/models/")
        ]
    scan += repo.iter_py("scripts")
    for extra in ("bench.py",):
        if repo.source(extra) is not None:
            scan.append(extra)
    for rel in sorted(set(scan)):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Attribute) and _is_detector_state(node)
            ):
                continue
            if src.inside_with_matching(node, LOCK_NEEDLE):
                continue
            kind = (
                "written" if isinstance(node.ctx, ast.Store) else "read"
            )
            out.append(Violation(
                PASS_ID, rel, node.lineno,
                f"`{src.segment(node) or 'detector.state'}` {kind} outside "
                f"`with ...{LOCK_NEEDLE}`: live dispatch donates these "
                "buffers — snapshot/swap under the pipeline's dispatch "
                "lock (or prove single-threadedness in a pragma reason)",
            ))
    return out
