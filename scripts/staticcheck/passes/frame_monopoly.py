"""frame-monopoly: runtime/frame.py owns every state byte layout.

PR 5's refactor put ALL state byte layouts — ingest scratch→pipeline,
replication payloads, checkpoint files — into ONE checksummed,
versioned columnar frame (``runtime/frame.py``). The contract is only
worth anything if no later PR can quietly fork it, so raw
byte-(de)serialization primitives are fenced to the layout owners:

- ``numpy.savez``/``savez_compressed``/``load`` (the pre-frame npz
  containers) and ``numpy.frombuffer``/``fromfile`` (raw
  reinterpretation of state bytes) are allowed ONLY in ``frame.py``
  (which owns the legacy-v0 migration shim) and ``tensorize.py``
  (whose documented record-join frombuffer is a hash input, not a
  wire layout).

- ``struct.pack``/``unpack``/``Struct`` are additionally allowed in
  the declared PROTOCOL CODECS — modules that implement byte layouts
  owned by EXTERNAL protocols (Kafka wire format, protobuf varints,
  HTTP/2 frames, the replication envelope header, faultwire's fault
  plans). Those are not state layouts; forcing them through frame.py
  would be category error. The list is closed: a new codec module is
  a deliberate, reviewed addition here, not a drive-by.

Detection is by import resolution, not text: ``from numpy import
frombuffer as fb`` or ``import struct as s`` cannot dodge it — which
is exactly why this pass replaces scripts/sanitycheck.py's old grep
pins (sanitycheck now delegates to this pass, so the two can never
disagree).
"""

from __future__ import annotations

import ast

from ..core import ImportMap, Repo, Violation

PASS_ID = "frame-monopoly"
DESCRIPTION = (
    "np.savez/np.load/np.frombuffer/struct.pack outside frame.py/"
    "tensorize.py (+ declared protocol codecs for struct), resolved "
    "through imports"
)

# Layout owners (repo-relative under the package).
FRAME_OWNERS = ("runtime/frame.py", "runtime/tensorize.py")
# External-protocol codecs: struct use here encodes SOMEONE ELSE'S
# wire format, not detector state.
PROTOCOL_CODECS = (
    "runtime/wire.py",        # length-prefixed frame transport
    "runtime/kafka_wire.py",  # Kafka protocol encoding
    "runtime/structpb.py",    # protobuf wire primitives
    "runtime/replication.py", # session envelope header (state INSIDE is frames)
    "runtime/history.py",     # segment-log record headers (state INSIDE is frames)
    "runtime/faultwire.py",   # chaos proxy fault plans
    "runtime/otlp_metrics.py",# OTLP fixed64/double fields
    "services/grpc_edge.py",  # HTTP/2 frame codec
)

NUMPY_FENCED = {
    "numpy.savez", "numpy.savez_compressed", "numpy.load",
    "numpy.frombuffer", "numpy.fromfile",
}
STRUCT_FENCED = {
    "struct.pack", "struct.unpack", "struct.pack_into",
    "struct.unpack_from", "struct.Struct", "struct.calcsize",
}


def run(repo: Repo) -> list[Violation]:
    out: list[Violation] = []
    if repo.package is None:
        return out
    owners = {f"{repo.package}/{p}" for p in FRAME_OWNERS}
    codecs = {f"{repo.package}/{p}" for p in PROTOCOL_CODECS}
    for rel in repo.iter_py(repo.package):
        src = repo.source(rel)
        if src is None or src.tree is None:
            continue
        imap = ImportMap(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            target = imap.resolve_call(node.func)
            if target is None:
                continue
            if target in NUMPY_FENCED and rel not in owners:
                out.append(Violation(
                    PASS_ID, rel, node.lineno,
                    f"{target}() outside runtime/frame.py|tensorize.py: "
                    "state byte layouts have ONE owner — encode/decode "
                    "through runtime.frame instead of minting a layout",
                ))
            elif (
                target in STRUCT_FENCED
                and rel not in owners
                and rel not in codecs
            ):
                out.append(Violation(
                    PASS_ID, rel, node.lineno,
                    f"{target}() outside the layout owners and declared "
                    "protocol codecs: a new byte layout goes through "
                    "runtime.frame; a new external-protocol codec is a "
                    "deliberate addition to PROTOCOL_CODECS in this pass",
                ))
    return out
