"""CLI: ``python -m scripts.staticcheck [--root R] [--select a,b] [--list]``."""

from __future__ import annotations

import argparse
import sys
import time

from .core import PASSES, _load_passes, run_repo


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="staticcheck",
        description="Repo-specific AST invariant analysis (see "
                    "scripts/staticcheck/__init__.py for the contract).",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root to analyze (default: cwd)",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated pass ids (default: all)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list passes and exit",
    )
    args = parser.parse_args(argv)
    _load_passes()
    if args.list:
        width = max(len(p) for p in PASSES)
        for pass_id, (_fn, desc) in PASSES.items():
            print(f"{pass_id:<{width}}  {desc}")
        return 0
    select = [p for p in args.select.split(",") if p] or None
    t0 = time.monotonic()
    violations, pragma_errors, suppressed = run_repo(args.root, select)
    for v in violations + pragma_errors:
        print(v.render())
    n = len(violations) + len(pragma_errors)
    took = time.monotonic() - t0
    print(
        f"staticcheck: {n} violation(s), {suppressed} suppressed "
        f"(with reasons), {len(PASSES if select is None else select)} "
        f"pass(es), {took:.2f}s"
    )
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
