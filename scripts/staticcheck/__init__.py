"""Repo-specific AST static analysis: the invariants checker.

The stack carries five hard-won cross-cutting contracts — frame.py's
byte-layout monopoly, the ``*_KNOBS`` registry threading, the
dispatch-lock donation discipline, the error-lane shed exclusion and
the ``anomaly_*`` metric/dashboard surface. ``scripts/sanitycheck.py``
pins some of them with greps, but a grep is defeated by an aliased
import, helper indirection or a renamed variable. This package checks
them on the AST instead (import resolution, lexical lock context,
literal tracing), so the contracts survive refactors — the way the
reference demo's ``internal/tools`` lint pins gate its Makefile
``check`` target.

Run:

    python -m scripts.staticcheck            # all passes, repo root
    python -m scripts.staticcheck --list     # pass table
    python -m scripts.staticcheck --select donation-race,frame-monopoly

Every violation prints ``path:line: [pass-id] message``. A violation
that is deliberate is suppressed IN PLACE with a pragma that must
carry a reason::

    detector.state = hydrate()  # staticcheck: ok[donation-race] boot-time, no dispatcher yet

A pragma without a reason, with an unknown pass id, or suppressing
nothing is itself an error — suppressions are documentation, not
escape hatches. ``make staticcheck`` (folded into ``make check``) must
run clean; tests/test_staticcheck.py proves each pass trips on a
seeded-bad fixture and stays silent on its clean twin.

No jax/numpy imports anywhere in this package: the whole run is pure
``ast`` + file IO and completes in well under ten seconds.
"""

from .core import PASSES, Repo, Violation, run_repo  # noqa: F401
