# Makes scripts/ importable so `python -m scripts.staticcheck` (and the
# sanitycheck delegation into its passes) work from the repo root.
