"""Run the in-repo Kafka broker standalone — the compose topology's
kafka container (/root/reference/docker-compose.yml kafka service) as
its own OS process.

The reference consumes an Apache Kafka image; this repo's broker is the
from-scratch wire-subset server in ``runtime.kafka_broker`` (Produce
v0/v3, Fetch v0/v4 with v2 RecordBatch headers, consumer-group offset
storage). Point ``serve_shop --kafka host:port`` and the detector
daemon's ``KAFKA_ADDR`` at it for the full three-process orders
topology: shop (producer + accounting/fraud groups) and daemon
(anomaly-detector group) on one broker.

Usage: python scripts/serve_kafka.py [--host 0.0.0.0] [--port 9092]
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from opentelemetry_demo_tpu.runtime.kafka_broker import KafkaBroker  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument(
        "--port", type=int, default=int(os.getenv("KAFKA_PORT", "9092")),
        help="listen port (0 = ephemeral, printed at boot)",
    )
    args = parser.parse_args()

    broker = KafkaBroker(host=args.host, port=args.port)
    broker.start()
    print(f"kafka broker on {args.host}:{broker.port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    broker.stop()


if __name__ == "__main__":
    main()
