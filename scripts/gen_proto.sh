#!/usr/bin/env bash
# Generate Python stubs from proto/demo.proto (the analogue of the
# reference's docker-gen-proto.sh / ide-gen-proto.sh codegen step).
# Stubs land in build/proto_gen/ and are NOT sources: the runtime
# decodes by field number via runtime/wire.py; the stubs exist for
# interop testing (tests/test_proto_contract.py) and downstream users.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=build/proto_gen
mkdir -p "$OUT"
protoc --python_out="$OUT" proto/demo.proto
echo "generated: $OUT/proto/demo_pb2.py"
