"""Detection-lag benchmark CLI: the second north-star metric.

Thin argument front-end over the shared methodology in
``opentelemetry_demo_tpu.runtime.lagbench`` (also what ``bench.py``
embeds in the driver artifact). Prints one JSON line:

    {"metric": "detection_lag_p99", "value": N, "unit": "ms",
     "vs_baseline": <100ms-baseline ratio>, ...}

Usage: python scripts/bench_lag.py [--rate 200000] [--seconds 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from opentelemetry_demo_tpu.runtime.lagbench import (  # noqa: E402
    BASELINE_LAG_MS,
    measure_lag,
)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rate", type=float, default=2_000.0,
                        help="spans/sec to sustain (default models the "
                        "default Locust profile; 200000 = stress config)")
    parser.add_argument("--seconds", type=float, default=8.0)
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--harvest-interval", type=float, default=0.0,
                        help="report readback cadence, s (0 = every batch)")
    parser.add_argument("--harvest-async", action="store_true",
                        help="fetch reports on a background thread")
    args = parser.parse_args()

    stats = measure_lag(
        rate=args.rate,
        seconds=args.seconds,
        batch=args.batch,
        harvest_interval_s=args.harvest_interval,
        harvest_async=args.harvest_async,
    )
    out = {
        "metric": "detection_lag_p99",
        "value": stats["p99_ms"],
        "unit": "ms",
        "vs_baseline": round(BASELINE_LAG_MS / max(stats["p99_ms"], 1e-9), 3),
        "rate_spans_per_sec": stats["rate"],
        "batches": stats["batches"],
        "spans": stats["spans"],
        "reports_skipped": stats["reports_skipped"],
    }
    # Paired-probe fields (see lagbench): net = lag − concurrent RTT,
    # the locally-attached-chip number on tunneled topologies.
    for key in ("p99_net_ms", "p50_net_ms", "rtt_p50_ms", "rtt_p99_ms",
                "rtt_pairs"):
        if key in stats:
            out[key] = stats[key]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
