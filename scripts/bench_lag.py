"""Detection-lag benchmark: the second north-star metric.

BASELINE north star: <100 ms p99 detection lag under the default Locust
profile (SURVEY.md §6) — the time from a span batch's submission to its
report being harvested on host. This drives the REAL DetectorPipeline
(async single-in-flight dispatch, donated state) at a configurable
span rate on whatever device jax finds, and prints one JSON line:

    {"metric": "detection_lag_p99", "value": N, "unit": "ms",
     "vs_baseline": <100ms-baseline ratio>}

Usage: python scripts/bench_lag.py [--rate 200000] [--seconds 8]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from opentelemetry_demo_tpu.models import AnomalyDetector, DetectorConfig
from opentelemetry_demo_tpu.runtime.pipeline import DetectorPipeline
from opentelemetry_demo_tpu.runtime.tensorize import SpanColumns

BASELINE_LAG_MS = 100.0


def make_columns(rng, rows: int) -> SpanColumns:
    return SpanColumns(
        svc=rng.integers(0, 20, size=rows).astype(np.int32),
        lat_us=rng.gamma(4.0, 250.0, size=rows).astype(np.float32),
        is_error=(rng.random(rows) < 0.02).astype(np.float32),
        trace_key=rng.integers(0, 2**63, size=rows, dtype=np.uint64),
        attr_crc=rng.zipf(1.5, size=rows).astype(np.uint64),
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    # Defaults model the north star's own config: "<100 ms p99 detection
    # lag, default Locust profile" — the default profile is 5 users with
    # 1-10 s waits (~10^2-10^3 spans/s), NOT the 200k/s throughput
    # config. Pass --rate 200000 --harvest-async to measure the stress
    # config (there, on a tunneled session, dispatch sustains the full
    # rate and lag is readback-cadence-bound).
    parser.add_argument("--rate", type=float, default=2_000.0,
                        help="spans/sec to sustain")
    parser.add_argument("--seconds", type=float, default=8.0)
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--harvest-interval", type=float, default=0.0,
                        help="report readback cadence, s (0 = every batch)")
    parser.add_argument("--harvest-async", action="store_true",
                        help="fetch reports on a background thread")
    args = parser.parse_args()

    detector = AnomalyDetector(DetectorConfig())
    pipe = DetectorPipeline(
        detector, batch_size=args.batch,
        harvest_interval_s=args.harvest_interval,
        harvest_async=args.harvest_async,
    )
    rng = np.random.default_rng(0)

    # Pre-build chunks so generation cost stays off the timed path.
    chunk_rows = args.batch
    chunks = [make_columns(rng, chunk_rows) for _ in range(16)]
    interval = chunk_rows / args.rate

    # Warmup: compile the step before the paced loop; scrub it from
    # every reported stat (not just the lag samples).
    pipe.submit_columns(chunks[0])
    pipe.pump(time.monotonic())
    pipe.drain()
    pipe.stats.lag_ms.clear()
    base_batches = pipe.stats.batches
    base_spans = pipe.stats.spans
    base_skipped = pipe.stats.reports_skipped

    end = time.monotonic() + args.seconds
    next_at = time.monotonic()
    i = 0
    while time.monotonic() < end:
        now = time.monotonic()
        if now < next_at:
            time.sleep(min(next_at - now, interval))
            continue
        next_at += interval
        pipe.submit_columns(chunks[i % len(chunks)])
        pipe.pump(time.monotonic())
        i += 1
    pipe.drain()

    p99 = pipe.stats.lag_p99_ms()
    print(json.dumps({
        "metric": "detection_lag_p99",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_LAG_MS / max(p99, 1e-9), 3),
        "rate_spans_per_sec": args.rate,
        "batches": pipe.stats.batches - base_batches,
        "spans": pipe.stats.spans - base_spans,
        "reports_skipped": pipe.stats.reports_skipped - base_skipped,
    }))


if __name__ == "__main__":
    main()
