"""Isolate why chained+donated steps are slower than repeated static calls."""
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import DetectorConfig, detector_init, detector_step
from bench import BASELINE_SPANS_PER_SEC, make_batch_pool

config = DetectorConfig()
B = 2048
rng = np.random.default_rng(0)
pool = make_batch_pool(config, B, 4, rng)
dt = jnp.float32(B / BASELINE_SPANS_PER_SEC)
rot = jnp.asarray([False, False, False])
rot_t = jnp.asarray([True, False, False])
iters = 300


def run(name, donate, chain, vary_mask, fetch_report=False):
    step = jax.jit(
        partial(detector_step, config), donate_argnums=0 if donate else ()
    )
    state = detector_init(config)
    state, rep = step(state, *pool[0], dt, rot)
    jax.block_until_ready(state)
    s = state
    t0 = time.perf_counter()
    for i in range(iters):
        mask = rot_t if (vary_mask and i % 7 == 0) else rot
        out, rep = step(s if chain else state, *pool[i % 4], dt, mask)
        if chain:
            s = out
        if fetch_report:
            np.asarray(rep.flags)
    jax.block_until_ready(out)
    per = (time.perf_counter() - t0) / iters
    print(f"{name:45s} {per*1e6:9.1f} us/step")


run("no-donate, no-chain, fixed mask", False, False, False)
run("no-donate, chain, fixed mask", False, True, False)
run("donate, chain, fixed mask", True, True, False)
run("donate, chain, varying mask", True, True, True)
run("donate, chain, vary mask, fetch flags", True, True, True, True)
