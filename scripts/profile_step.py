"""Component-level timing of detector_step to locate fixed per-step cost."""
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import DetectorConfig, detector_init, detector_step
from opentelemetry_demo_tpu.ops import cms, ewma, hll
from bench import BASELINE_SPANS_PER_SEC, make_batch_pool

config = DetectorConfig()
B = 2048
rng = np.random.default_rng(0)
pool = make_batch_pool(config, B, 4, rng)
state = detector_init(config)


def timeit(name, fn, *args, iters=200):
    jfn = jax.jit(fn)
    out = jfn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:30s} {dt*1e6:9.1f} us")
    return dt


svc, lat_us, is_error, trace_hi, trace_lo, attr_hi, attr_lo, valid = pool[0]
dt = jnp.float32(B / BASELINE_SPANS_PER_SEC)
rot = jnp.asarray([False, False, False])
rot_t = jnp.asarray([True, False, False])

# Full step
step = partial(detector_step, config)
timeit("full step (no rotate)", step, state, *pool[0], dt, rot)
timeit("full step (rotate w0)", step, state, *pool[0], dt, rot_t)

# Components
hll_bank = state.hll_bank
cms_bank = state.cms_bank


def f_hll(bank, th, tl, s, v):
    bucket, rank = hll.hll_indices(th, tl, p=config.hll_p)
    upd = jax.vmap(hll.hll_update, in_axes=(0, None, None, None, None))
    return bank.at[:, 0].set(upd(bank[:, 0], s, bucket, rank, v))


def f_cms(bank, ah, al, v):
    cidx = cms.cms_indices(ah, al, config.cms_depth, config.cms_width)
    upd = jax.vmap(cms.cms_update, in_axes=(0, None, None, None))
    return bank.at[:, 0].set(upd(bank[:, 0], cidx, None, v))


def f_est(bank):
    return hll.hll_estimate(bank[:, 0])


def f_rot(bank, mask):
    rolled = jnp.stack([jnp.zeros_like(bank[:, 0]), bank[:, 0]], axis=1)
    m = mask.reshape((-1,) + (1,) * (bank.ndim - 1))
    return jnp.where(m, rolled, bank)


def f_seg(lat, s, v):
    return ewma.segment_stats(jnp.log1p(lat), s, config.num_services, valid=v)


def f_cmsq(bank, ah, al):
    cidx = cms.cms_indices(ah, al, config.cms_depth, config.cms_width)
    return jax.vmap(cms.cms_query, in_axes=(0, None))(bank[:, 0], cidx)


timeit("hll scatter-max (3 win)", f_hll, hll_bank, trace_hi, trace_lo, svc, valid)
timeit("cms scatter-add (3 win)", f_cms, cms_bank, attr_hi, attr_lo, valid)
timeit("hll estimate (3 win)", f_est, hll_bank)
timeit("rotate hll bank", f_rot, hll_bank, rot_t)
timeit("segment stats", f_seg, lat_us, svc, valid)
timeit("cms query (3 win)", f_cmsq, cms_bank, attr_hi, attr_lo)
