"""Component-level timing of detector_step to locate fixed per-step cost.

SLOPE timing with a device→host fetch terminating every region — the
only honest method on this repo's tunneled topology, where
``block_until_ready`` can return before device compute completes (the
r3 bisection found a 14 ms CMS gather this way; the old
block_until_ready version of this script reported every component as
~100 µs of dispatch cost). Variants chain a donated state so XLA cannot
dead-code-eliminate the part under test — note the r3 lesson: a variant
whose CMS delta is unused gets the whole histogram sort DCE'd and reads
8 ms too fast.

Usage: python scripts/profile_step.py [B]   (default 524288; real TPU)
"""

import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import (
    DetectorConfig,
    detector_init,
    detector_step,
)
from opentelemetry_demo_tpu.ops import cms, fused
from bench import BASELINE_SPANS_PER_SEC, make_batch_pool

config = DetectorConfig()
B = int(sys.argv[1]) if len(sys.argv) > 1 else 524288
rng = np.random.default_rng(0)
pool = make_batch_pool(config, B, 2, rng)
dt = jnp.float32(B / BASELINE_SPANS_PER_SEC)
mask = jnp.asarray([False] * len(config.windows_s))


def slope(name, fn, iters=20):
    """Per-step seconds of a state-chained fn(state, *batch, dt, mask)."""
    jfn = jax.jit(fn, donate_argnums=0)

    def region(k, st):
        t0 = time.perf_counter()
        for i in range(k):
            st, _ = jfn(st, *pool[i % 2], dt, mask)
        _ = float(np.asarray(st.step_idx))  # fetch forces the chain
        return time.perf_counter() - t0, st

    st = detector_init(config)
    _, st = region(3, st)
    t1, st = region(iters, st)
    t2, st = region(3 * iters, st)
    per = (t2 - t1) / (2 * iters)
    print(f"{name:34s} {per*1e3:8.2f} ms   {B/per/1e6:7.1f}M spans/s")
    return per


full = slope("full step", partial(detector_step, config))


def make_delta(use_cms: bool):
    """Delta-only step variant; ``use_cms=False`` leaves the CMS delta
    unused so XLA DCE's its histogram sort — the gap between the two
    variants IS the sort's cost. ONE body builds both so they cannot
    silently measure different computations.

    impl is FORCED to "xla": a pallas_call is opaque to XLA, so
    dropping the cms output would NOT eliminate the CMS work inside
    the fused kernel and the subtraction would read ~0. (The dense
    kernel has no sort to isolate anyway — this decomposition is a
    property of the xla path.)
    """

    def fn(st, svc, lat_us, is_error, hi, lo, ahi, alo, valid, dt, mask):
        log_lat = jnp.log1p(jnp.maximum(lat_us, 0.0))
        cidx = cms.cms_indices(ahi, alo, config.cms_depth, config.cms_width)
        d = fused.sketch_batch_delta(
            svc.astype(jnp.int32), log_lat, is_error, hi, lo, cidx, valid,
            num_services=config.num_services, hll_p=config.hll_p,
            cms_width=config.cms_width, impl="xla",
        )
        st = st._replace(
            hll_bank=st.hll_bank.at[:, 0].set(
                jnp.maximum(st.hll_bank[:, 0], d.hll[None])
            ),
            obs_batches=st.obs_batches + d.stats[0],
            step_idx=st.step_idx + 1,
        )
        if use_cms:
            st = st._replace(
                cms_bank=st.cms_bank.at[:, 0].set(
                    st.cms_bank[:, 0] + d.cms[None]
                )
            )
        return st, None

    return fn


delta = slope("delta xla (hll+cms+stats, used)", make_delta(use_cms=True))
no_cms = slope("delta xla w/o cms hist (DCE'd)", make_delta(use_cms=False))
print(f"{'-> cms histogram sort (xla)':34s} {(delta - no_cms)*1e3:8.2f} ms")
print(f"{'-> rest (queries/heads/report)':34s} {(full - delta)*1e3:8.2f} ms")
