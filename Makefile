# Build/run surface — the analogue of the reference's Makefile
# (/root/reference/Makefile:100-285: start/stop, run-tests,
# run-tracetesting, generate-protobuf, check). JAX on CPU is forced for
# local targets; bench runs on whatever accelerator jax.devices() finds.

PY      := python
CPU_ENV := env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu

.PHONY: start start-minimal start-kafka start-load test tracetest kafka-interop bench overloadbench ingestbench decodebench spinebench frontdoorbench replbench fleetbench autoscalebench replaybench mitigbench shadowbench querybench explainbench gen-k8s gen-proto gen-dashboards build-native staticcheck check clean

start:          ## serve the shop stack (gateway :8080 + detector + 5 users)
	$(CPU_ENV) $(PY) scripts/serve_shop.py --users 5

start-minimal:  ## reduced profile (reference make start-minimal): no async tier, no flag-editor UI
	$(CPU_ENV) $(PY) scripts/serve_shop.py --users 5 --minimal

start-kafka:    ## shop with the async tier over a REAL broker socket
	$(CPU_ENV) $(PY) scripts/serve_shop.py --users 5 --kafka auto

start-load:     ## drive a remote gateway (TARGET=http://host:8080)
	$(CPU_ENV) $(PY) scripts/serve_shop.py --load-only --target $(or $(TARGET),http://127.0.0.1:8080) --users 5

test:           ## unit + integration suite (CPU mesh)
	$(CPU_ENV) $(PY) -m pytest tests/ -x -q

tracetest:      ## trace-based suites over a live gateway (SURVEY.md §4)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.tracetest tracetesting

kafka-interop:  ## wire-client suite vs a real broker (KAFKA_ADDR=host:9092; unset = in-repo broker)
	$(CPU_ENV) $(PY) -m pytest tests/test_kafka_interop.py -v

bench:          ## flagship benchmark (ONE json line; real TPU if present)
	$(PY) bench.py

overloadbench:  ## overload saturation driver (ONE json line: bounded queue, zero error-lane shed, brownout, recovery)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.overloadbench

ingestbench:    ## host-ingest engines + decode-pool worker sweep (same methodology as bench.py's host_ingest_*)
	$(CPU_ENV) $(PY) scripts/bench_ingest.py --workers 1,2,4

decodebench:    ## raw two-pass scanner microbench: pass-1 scan vs pass-2 extract per thread + one-fat-payload shard scaling
	$(CPU_ENV) $(PY) scripts/bench_ingest.py --raw

spinebench:     ## end-to-end ingest spine: payload → flagged report, workers × ring-depth sweep (ONE json line)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.spinebench

frontdoorbench: ## native front door vs in-process pool at matched workers + ≥1M-distinct-key cardinality soak (ONE json line)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.frontdoorbench

replbench:      ## hot-standby failover drill (ONE json line: replication lag p99, failover TTD, exact convergence)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.replbench

fleetbench:     ## sharded-fleet reshard drill (ONE json line: SIGKILL a shard under live Kafka+OTLP load, reshard TTD, witness-pinned bit-exact answers, blackholed-shard partial answers, noisy-tenant isolation; folds in the autoscalebench leg)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.replbench --fleet

autoscalebench: ## elastic-fleet live drill alone (ONE json line: ramp to saturation, autoscaler proposes scale-out, SIGKILL a shard mid-resize, automatic adoption TTA, bit-exact witness pin, no oscillation)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.replbench --autoscale

replaybench:    ## history time-travel drill (ONE json line: record an incident, replay the segment log at N× wall clock, pin bit-identical verdicts, range-query p99)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.replaybench

mitigbench:     ## closed-loop auto-mitigation drill (ONE json line: time-to-mitigate per flagd scenario, rollback drill, no-oscillation gate; BENCH_SHADOW=1 folds in the shadow leg)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.mitigbench

shadowbench:    ## counterfactual pre-flight drill alone (ONE json line: shadow-replay bit-identity at ≥10× wall, would-help released vs wrong-flag refused with zero actuator writes, collector keep/drop ratio + exact revert)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.mitigbench --shadow

querybench:     ## live query plane under concurrent ingest (ONE json line: query p99/qps, ingest interference ratio)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.querybench

explainbench:   ## verdict-provenance canary (ONE json line: provenance-on/off ABAB overhead ratio gated ≤1.03, /query/explain p99 under the live-ingest hammer)
	$(CPU_ENV) $(PY) -m opentelemetry_demo_tpu.runtime.spinebench --explain

gen-k8s:        ## regenerate deploy/k8s manifests
	$(PY) -m opentelemetry_demo_tpu.utils.k8s --out deploy/k8s

build-native:   ## C++ ingest + currency kernels
	$(MAKE) -C opentelemetry_demo_tpu/native

staticcheck:    ## AST invariant analysis (scripts/staticcheck; no jax, <10s)
	$(PY) -m scripts.staticcheck

check:          ## fast static sanity (no network, no device)
	$(PY) -m compileall -q opentelemetry_demo_tpu tests scripts bench.py __graft_entry__.py
	$(PY) -m scripts.staticcheck
	SANITYCHECK_SKIP_STATICCHECK=1 $(PY) scripts/sanitycheck.py

gen-proto:      ## regenerate protobuf stubs (build artifact)
	bash scripts/gen_proto.sh

gen-dashboards: ## regenerate deploy/grafana/*.json from telemetry.dashboards
	$(PY) -c "from opentelemetry_demo_tpu.telemetry.dashboards import write_grafana_dashboards as w; print('\n'.join(w('deploy/grafana')))"

clean:
	$(MAKE) -C opentelemetry_demo_tpu/native clean 2>/dev/null || true
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
