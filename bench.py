"""Benchmark: sketch-update throughput of the flagship detector step.

Measures sustained spans/sec through the full single-chip detector
update (HLL + CMS + EWMA heads + heavy-hitter query + window rotation)
on device-resident batches — the BASELINE north-star metric
("≥200,000 spans/sec sketch updates on a single v5e-1").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "spans/sec", "vs_baseline": N}

Methodology — honest under remote/tunneled devices:
``jax.block_until_ready`` can return before device compute completes on
tunneled PJRT topologies (measured here: a matmul chain with a 28 ms
FLOP floor "completing" in 0.1 ms), so any fetch-free timed loop
measures dispatch rate, not throughput. This bench instead times two
state-chained regions of k1 and k2 steps, each terminated by a real
device→host scalar fetch (the chain's final ``step_idx``), and reports
the SLOPE (t2-t1)/(k2-k1) as per-step cost — fixed costs (fetch RTT,
loop overhead) cancel, device compute cannot be hidden. The state is
donated every step and batches live on device; window-rotation masks
cycle at the cadence a real stream at the baseline rate would see.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import (
    DetectorConfig,
    detector_init,
    detector_step,
)
from opentelemetry_demo_tpu.runtime import SpanTensorizer

BASELINE_SPANS_PER_SEC = 200_000.0


def make_batch_pool(config, batch_size, n_pool, rng):
    tz = SpanTensorizer(num_services=config.num_services, batch_size=batch_size)
    pool = []
    for _ in range(n_pool):
        tb = tz.pack_arrays(
            svc=rng.integers(0, 20, size=batch_size),
            lat_us=rng.gamma(4.0, 250.0, size=batch_size).astype(np.float32),
            trace_id=rng.integers(0, 2**63, size=batch_size, dtype=np.uint64),
            is_error=(rng.random(batch_size) < 0.02).astype(np.float32),
            attr_key=rng.zipf(1.5, size=batch_size).astype(np.uint64),
        )
        pool.append(
            tuple(
                jax.device_put(jnp.asarray(x))
                for x in (
                    tb.svc, tb.lat_us, tb.is_error,
                    tb.trace_hi, tb.trace_lo, tb.attr_hi, tb.attr_lo, tb.valid,
                )
            )
        )
    return pool


def main():
    # 512k: the XLA path (auto-selected for large batches; CMS counting
    # via the scatter-free sort+searchsorted histogram) saturates ~20M
    # spans/s from B≈128k on v5e-1; 512k keeps the timed regions long
    # relative to any fixed overheads.
    batch_size = int(os.environ.get("BENCH_BATCH", 524288))
    config = DetectorConfig()
    step = jax.jit(partial(detector_step, config), donate_argnums=0)
    rng = np.random.default_rng(0)

    n_pool = 4
    pool = make_batch_pool(config, batch_size, n_pool, rng)
    dt_host = batch_size / BASELINE_SPANS_PER_SEC
    dt = jnp.float32(dt_host)

    # Rotation cadence as seen by a stream at the baseline rate.
    steps_per_sec = max(int(1.0 / dt_host), 1)
    masks = []
    for i in range(max(steps_per_sec * 60, 240)):
        masks.append(
            (i % steps_per_sec == 0,
             i % (steps_per_sec * 10) == 0,
             i % (steps_per_sec * 60) == 0)
        )
    uniq = {m: jnp.asarray(m) for m in set(masks)}
    mask_seq = [uniq[m] for m in masks]

    state = detector_init(config)
    # Warmup / compile, then a real fetch so the whole run measures in
    # the same (synchronized) tunnel regime.
    state, report = step(state, *pool[0], dt, mask_seq[1])
    _ = int(np.asarray(state.step_idx))

    def region(k: int, state):
        t0 = time.perf_counter()
        for i in range(k):
            state, _report = step(
                state, *pool[i % n_pool], dt, mask_seq[i % len(mask_seq)]
            )
        _ = int(np.asarray(state.step_idx))  # fetch forces the chain
        return time.perf_counter() - t0, state

    # Calibrate k from a probe SLOPE (two probe lengths) so the fixed
    # fetch RTT — which dominates short regions on tunneled topologies —
    # doesn't inflate the estimate and undersize the timed regions.
    # k1 is bounded ([8, 2000]) so a probe spike can neither hang the
    # bench for hours nor shrink the regions to pure RTT jitter.
    ta, state = region(4, state)
    tb, state = region(12, state)
    per_step_est = max((tb - ta) / 8, 1e-5)
    k1 = min(max(int(2.0 / per_step_est), 8), 2000)

    # Accept a measurement only when the inter-region signal dwarfs
    # RTT jitter (≥0.5 s of extra device work); otherwise grow the
    # regions and retry.
    per_step = 0.0
    signal = 0.0
    for _attempt in range(4):
        k2 = 3 * k1
        t1, state = region(k1, state)
        t2, state = region(k2, state)
        per_step = (t2 - t1) / (k2 - k1)
        signal = t2 - t1
        if per_step > 0 and signal >= 0.5:
            break
        k1 = min(k1 * 4, 20_000)
    if per_step <= 0 or signal < 0.5:
        raise RuntimeError(
            f"slope {per_step!r} with only {signal:.3f}s of inter-region "
            "signal after retries — timing noise exceeded the signal; "
            "refusing to report"
        )

    spans_per_sec = batch_size / per_step
    print(
        json.dumps(
            {
                "metric": "sketch_update_throughput_single_chip",
                "value": round(spans_per_sec, 1),
                "unit": "spans/sec",
                "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
