"""Benchmark: BOTH north stars of the flagship detector.

1. **Throughput** — sustained spans/sec through the full single-chip
   detector update (HLL + CMS + EWMA heads + heavy-hitter query +
   window rotation) on device-resident batches (BASELINE:
   "≥200,000 spans/sec sketch updates on a single v5e-1").
2. **Detection lag** — p99 of submit→report-harvest time through the
   REAL DetectorPipeline at the default-Locust-profile rate (BASELINE:
   "<100 ms p99 detection lag"), with the measured device→host fetch
   RTT reported beside it: on a tunneled CI topology every harvest pays
   one RTT, so ``lag_p99_ms − fetch_rtt_ms`` approximates what a
   locally attached v5e would show.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "spans/sec", "vs_baseline": N,
     "lag_p99_ms": N, "lag_vs_baseline": N, "fetch_rtt_ms": N, ...}

Methodology — honest under remote/tunneled devices:
``jax.block_until_ready`` can return before device compute completes on
tunneled PJRT topologies (measured here: a matmul chain with a 28 ms
FLOP floor "completing" in 0.1 ms), so any fetch-free timed loop
measures dispatch rate, not throughput. This bench instead times two
state-chained regions of k1 and k2 steps, each terminated by a real
device→host scalar fetch (the chain's final ``step_idx``), and reports
the SLOPE (t2-t1)/(k2-k1) as per-step cost — fixed costs (fetch RTT,
loop overhead) cancel, device compute cannot be hidden. The state is
donated every step and batches live on device; window-rotation masks
cycle at the cadence a real stream at the baseline rate would see.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import (
    DetectorConfig,
    detector_init,
    detector_step,
)
from opentelemetry_demo_tpu.runtime import SpanTensorizer

BASELINE_SPANS_PER_SEC = 200_000.0
BASELINE_LAG_MS = 100.0
# Host-ingest SLO (the r6 parallel-ingest tentpole): the r5 serial
# native path measured 2.26M spans/s on this CI topology — 53× under
# the device rate it feeds. The pooled engine must clear 3× that.
R5_HOST_INGEST_SPANS_PER_SEC = 2_260_000.0
HOST_INGEST_TARGET = 3.0 * R5_HOST_INGEST_SPANS_PER_SEC


def make_batch_pool(config, batch_size, n_pool, rng):
    tz = SpanTensorizer(num_services=config.num_services, batch_size=batch_size)
    pool = []
    for _ in range(n_pool):
        tb = tz.pack_arrays(
            svc=rng.integers(0, 20, size=batch_size),
            lat_us=rng.gamma(4.0, 250.0, size=batch_size).astype(np.float32),
            trace_id=rng.integers(0, 2**63, size=batch_size, dtype=np.uint64),
            is_error=(rng.random(batch_size) < 0.02).astype(np.float32),
            attr_key=rng.zipf(1.5, size=batch_size).astype(np.uint64),
        )
        pool.append(
            tuple(
                jax.device_put(jnp.asarray(x))
                for x in (
                    tb.svc, tb.lat_us, tb.is_error,
                    tb.trace_hi, tb.trace_lo, tb.attr_hi, tb.attr_lo, tb.valid,
                )
            )
        )
    return pool


def measure_throughput(
    config: DetectorConfig,
    batch_size: int,
    rng,
    min_signal_s: float = 0.5,
    target_region_s: float = 2.0,
) -> float:
    """Slope-timed spans/sec through the full detector step.

    Times two state-chained regions of k1/k2 steps, each terminated by a
    real device→host scalar fetch, and reports (t2-t1)/(k2-k1) — fixed
    costs (fetch RTT, loop overhead) cancel, device compute cannot be
    hidden (the only honest timing on tunneled PJRT topologies, where
    block_until_ready can return early).
    """
    step = jax.jit(partial(detector_step, config), donate_argnums=0)
    n_pool = 4
    pool = make_batch_pool(config, batch_size, n_pool, rng)
    dt_host = batch_size / BASELINE_SPANS_PER_SEC
    dt = jnp.float32(dt_host)

    # Rotation cadence as seen by a stream at the baseline rate.
    steps_per_sec = max(int(1.0 / dt_host), 1)
    masks = []
    for i in range(max(steps_per_sec * 60, 240)):
        masks.append(
            (i % steps_per_sec == 0,
             i % (steps_per_sec * 10) == 0,
             i % (steps_per_sec * 60) == 0)
        )
    uniq = {m: jnp.asarray(m) for m in set(masks)}
    mask_seq = [uniq[m] for m in masks]

    state = detector_init(config)
    # Warmup / compile, then a real fetch so the whole run measures in
    # the same (synchronized) tunnel regime.
    state, _report = step(state, *pool[0], dt, mask_seq[1])
    _ = int(np.asarray(state.step_idx))

    def region(k: int, state):
        t0 = time.perf_counter()
        for i in range(k):
            state, _report = step(
                state, *pool[i % n_pool], dt, mask_seq[i % len(mask_seq)]
            )
        _ = int(np.asarray(state.step_idx))  # fetch forces the chain
        return time.perf_counter() - t0, state

    # Calibrate k from a probe SLOPE (two probe lengths) so the fixed
    # fetch RTT — which dominates short regions on tunneled topologies —
    # doesn't inflate the estimate and undersize the timed regions.
    # k1 is bounded ([8, 2000]) so a probe spike can neither hang the
    # bench for hours nor shrink the regions to pure RTT jitter.
    ta, state = region(4, state)
    tb, state = region(12, state)
    per_step_est = max((tb - ta) / 8, 1e-5)
    k1 = min(max(int(target_region_s / per_step_est), 8), 2000)

    # Accept a measurement only when the inter-region signal dwarfs
    # RTT jitter; otherwise grow the regions and retry.
    per_step = 0.0
    signal = 0.0
    for _attempt in range(4):
        k2 = 3 * k1
        t1, state = region(k1, state)
        t2, state = region(k2, state)
        per_step = (t2 - t1) / (k2 - k1)
        signal = t2 - t1
        if per_step > 0 and signal >= min_signal_s:
            break
        k1 = min(k1 * 4, 20_000)
    if per_step <= 0 or signal < min_signal_s:
        raise RuntimeError(
            f"slope {per_step!r} with only {signal:.3f}s of inter-region "
            "signal after retries — timing noise exceeded the signal; "
            "refusing to report"
        )
    return batch_size / per_step


def measure_impl_matrix(rng) -> dict[str, float]:
    """impl × batch-size crossover matrix (BASELINE config #4 audit).

    The dense Pallas kernel's per-span cost is a fixed sweep of all
    sketch cell tiles per batch tile — flat in B — so it owns the
    small-batch low-latency regime; the XLA path's O(1)-per-span
    scatter-free formulation wins throughput at large B. The matrix in
    the artifact makes the auto-select crossover auditable instead of
    asserted. Looser signal floor (0.3 s) than the headline number —
    these are regime comparisons, not the record.
    """
    if jax.default_backend() != "tpu":
        return {}
    out: dict[str, float] = {}
    # Both impls at both sides of the reference-geometry ~24k crossover
    # (the r5 calibration table above fused.expected_rates): 16384 is
    # the dense kernel's last winning point, 65536 deep in the xla
    # path's MXU-histogram regime. Compiles dominate the cost, so the
    # sweep stays at 8 entries.
    for impl in ("pallas", "xla"):
        for batch in (2048, 16384, 65536, 524288):
            config = DetectorConfig(sketch_impl=impl)
            try:
                rate = measure_throughput(
                    config, batch, rng, min_signal_s=0.3, target_region_s=0.8
                )
            except (RuntimeError, ValueError):
                out[f"{impl}@{batch}"] = float("nan")
                continue
            out[f"{impl}@{batch}"] = round(rate, 1)
    return out


def main():
    # 2M: the XLA path (auto-selected for large batches; CMS counting
    # via the transposed-int8 MXU histogram, cms.cms_update_hist)
    # plateaus ~123M spans/s at B=2M single-chip (r5: 105M@512k with
    # this function's tight floors — a loose-floor sweep sampled 97M
    # there, within the tunnel's run-to-run variance — then 115M@1M,
    # 123M@2M, flat to 8M; the r4 f32 engine's 2^24 key cap that
    # blocked >4M-key batches is gone with int32 accumulation).
    batch_size = int(os.environ.get("BENCH_BATCH", 2097152))
    rng = np.random.default_rng(0)
    spans_per_sec = measure_throughput(DetectorConfig(), batch_size, rng)

    # ---- impl × batch crossover (config #4 audit) --------------------
    matrix = {}
    if os.environ.get("BENCH_MATRIX", "1") != "0":
        matrix = measure_impl_matrix(rng)

    # ---- host ingest (SURVEY §7 hard part (a)) -----------------------
    # The other half of the ≥200k/s budget: OTLP bytes → columns on the
    # HOST. Serial = the r5 path (one decode+tensorize per request, one
    # thread) kept as the BEFORE number; the headline is the parallel
    # ingest engine (runtime.ingest_pool: batched decode_many, pooled
    # buffers, coalesced tensorize, N workers) with its worker-count
    # scaling curve. None/{} when the .so can't build here.
    ingest_serial = None
    ingest_rate = None
    ingest_scaling: dict[str, float] = {}
    ingest_detail: dict = {}
    if os.environ.get("BENCH_INGEST", "1") != "0":
        from opentelemetry_demo_tpu.runtime import ingestbench

        try:
            payloads = ingestbench.make_payloads()
            ingest_serial = ingestbench.measure_native(
                repeat=3, payloads=payloads
            )
            ingest_scaling = ingestbench.measure_scaling(
                workers_list=(1, 2, 3, 4), payloads=payloads,
                detail=ingest_detail,
            )
            if ingest_scaling:
                ingest_rate = max(ingest_scaling.values())
        except Exception:  # noqa: BLE001 — artifact field is optional
            ingest_serial = ingest_rate = None
            ingest_scaling = {}
            ingest_detail = {}

    # ---- end-to-end ingest spine (payload → flagged report) ----------
    # The number ROADMAP item 1 is gated on: sustained spans/s from raw
    # OTLP bytes through decode pool → admission → device-put spine →
    # donated one-pass step → harvested report. The SLO below checks it
    # against min(host ingest, kernel): ≥90% means the transfer and
    # host glue are genuinely hidden behind the slower of the two
    # endpoints, not just fast in isolation. {} on failure — additive.
    e2e = {}
    if os.environ.get("BENCH_SPINE", "1") != "0":
        from opentelemetry_demo_tpu.runtime import spinebench

        try:
            e2e = spinebench.measure_e2e(
                seconds=float(os.environ.get("BENCH_SPINE_SECONDS", "6.0"))
            ) or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            e2e = {}

    # ---- native front door + million-key soak (r19) ------------------
    # The zero-Python door measured against the in-process pool at
    # matched workers (the r19 tentpole gate), plus the scale-of-keys
    # soak: ≥1M distinct (tenant×service) keys through ingest→sketch→
    # query with RSS-per-million-keys reported. Heavy: trim with
    # BENCH_FRONTDOOR_KEYS or skip with BENCH_FRONTDOOR=0. {} on
    # failure — additive artifact fields.
    frontdoor = {}
    frontdoor_soak = {}
    churn_soak = {}
    if os.environ.get("BENCH_FRONTDOOR", "1") != "0":
        from opentelemetry_demo_tpu.runtime import frontdoorbench

        try:
            frontdoor = frontdoorbench.measure_frontdoor_vs_pool(
                seconds=float(
                    os.environ.get("BENCH_FRONTDOOR_SECONDS", "4.0")
                ),
            ) or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            frontdoor = {}
        try:
            frontdoor_soak = frontdoorbench.measure_million_key_soak(
                target_keys=int(
                    os.environ.get("BENCH_FRONTDOOR_KEYS", "1048576")
                ),
            ) or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            frontdoor_soak = {}
        try:
            churn_soak = frontdoorbench.measure_churn_soak(
                waves=int(os.environ.get("BENCH_CHURN_WAVES", "8")),
            ) or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            churn_soak = {}

    # ---- self-telemetry overhead (the ISSUE 10 canary) ---------------
    # Tracer-on vs tracer-off spinebench A/B with the full production
    # wiring (sampled batch traces + phase histograms): the detector
    # watching itself must cost ≤3% of the path it watches, proven per
    # run, not asserted. {} on failure — additive fields.
    selftrace_ab = {}
    if os.environ.get("BENCH_SELFTRACE", "1") != "0":
        from opentelemetry_demo_tpu.runtime import spinebench

        try:
            selftrace_ab = spinebench.measure_selftrace_overhead() or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            selftrace_ab = {}

    # ---- verdict provenance overhead (the ISSUE 18 canary) -----------
    # Provenance-on vs provenance-off spinebench A/B: the per-report
    # trajectory ring (the only provenance work on the hot path —
    # bundle assembly fires only on flags) must cost ≤3% of spine
    # throughput, same discipline as the selftrace gate above.
    # {} on failure — additive fields.
    explain_ab = {}
    if os.environ.get("BENCH_EXPLAIN", "1") != "0":
        from opentelemetry_demo_tpu.runtime import spinebench

        try:
            explain_ab = spinebench.measure_explain_overhead() or {}
        except Exception:  # noqa: BLE001 — artifact field is optional
            explain_ab = {}

    # ---- history replay (the time-travel tentpole) -------------------
    # Record a synthetic incident into the on-disk segment log, then
    # re-feed the recorded frames through a FRESH real pipeline under
    # virtual-time injection: replay_speedup is recorded-seconds per
    # wall-second (gated >= the ANOMALY_HISTORY_REPLAY_RATE target,
    # 10x on CI), and the replayed flag verdicts must equal the
    # recording run's bit-for-bit. history_range_query_p99_ms prices
    # the read path over the just-written ladder. {} on failure.
    replay = {}
    if os.environ.get("BENCH_REPLAY", "1") != "0":
        from opentelemetry_demo_tpu.runtime.replaybench import (
            measure_replay,
        )

        try:
            replay = measure_replay()
        except Exception:  # noqa: BLE001 — artifact field is optional
            replay = {}

    # ---- hot-standby failover (the replication tentpole) -------------
    # Real replication link, real kill: failover_ttd_s is the blind
    # window a primary host loss costs (watchdog fire → promoted), and
    # replication_lag_p99_ms bounds how stale the standby's mirror can
    # be. CPU-friendly small geometry — the protocol, not the kernels,
    # is under test. None on failure (additive artifact fields).
    repl = {}
    if os.environ.get("BENCH_REPL", "1") != "0":
        from opentelemetry_demo_tpu.runtime.replbench import (
            measure_failover,
        )

        try:
            repl = measure_failover()
        except Exception:  # noqa: BLE001 — artifact field is optional
            repl = {}

    # ---- sharded-fleet reshard drill (the fleet tentpole) ------------
    # Kill one of three shards under deterministic load beside an
    # UNKILLED witness fleet: shard_reshard_ttd_s is kill → a survivor
    # answering the victim's keys from its adopted replicated frame,
    # fleet_ok gates the witness-pinned bit-exactness, the blackholed-
    # shard labeled-partial answer, and the noisy-tenant quota
    # isolation. (The live SIGKILL-a-daemon leg runs under `make
    # fleetbench`; the in-proc leg here keeps the flagship line fast.)
    fleet_drill = {}
    if os.environ.get("BENCH_FLEET", "1") != "0":
        from opentelemetry_demo_tpu.runtime.replbench import (
            measure_reshard,
        )

        try:
            fleet_drill = measure_reshard()
        except Exception:  # noqa: BLE001 — artifact field is optional
            fleet_drill = {}

    # ---- elastic-fleet autoscale drill (the elastic tentpole) --------
    # Two REAL daemon shards wired as an adoptive pair, autoscaler on
    # the heir: ramp OTLP load until admission saturates and a
    # scale-out is proposed, SIGKILL the victim mid-resize, and watch
    # the heir adopt its keyspace with zero operator action.
    # autoscale_tta_s is SIGKILL → adoption applied; autoscale_ok
    # gates the whole contract (real-saturation proposal, automatic
    # adoption, bit-exact witness pin, no oscillation over the quiet
    # window). Slow (two daemon boots) — gate off with
    # BENCH_AUTOSCALE=0. {} on failure — additive artifact fields.
    autoscale_drill = {}
    if os.environ.get("BENCH_AUTOSCALE", "1") != "0":
        from opentelemetry_demo_tpu.runtime.replbench import (
            measure_adoption,
        )

        try:
            autoscale_drill = measure_adoption()
        except Exception:  # noqa: BLE001 — artifact field is optional
            autoscale_drill = {}

    # ---- live query plane (the read-path tentpole) -------------------
    # Real HTTP query service hammered beside live ingest in one
    # process: query_p99_ms is the dashboard-refresh cost over live
    # sketches, query_qps the sustained read rate, ingest_ratio the
    # "reads don't degrade the write path" guard (the ingest/lag SLOs
    # above stay gated independently). {} on failure — additive fields.
    queryq = {}
    if os.environ.get("BENCH_QUERY", "1") != "0":
        from opentelemetry_demo_tpu.runtime.querybench import (
            measure_query,
        )

        try:
            queryq = measure_query()
        except Exception:  # noqa: BLE001 — artifact field is optional
            queryq = {}

    # ---- north star #2: detection lag through the real pipeline ------
    fetch_rtt_ms = measure_fetch_rtt()
    lag = measure_lag(rng)

    # ---- detection quality: per-fault TTD + false-positive rate ------
    # Detector math is backend-independent; a CPU subprocess avoids
    # paying the tunneled-TPU fetch RTT ~1900 times (one per stepped
    # report) for numbers that would come out identical.
    quality = {}
    if os.environ.get("BENCH_QUALITY", "1") != "0":
        quality = measure_quality_subprocess()

    # ---- closed-loop auto-mitigation (the remediation tentpole) ------
    # Time-to-mitigate beside time-to-detect: the controller flips the
    # scenario's mitigation flag through the live store, the injector
    # (reading the same store) heals, and the controller VERIFIES the
    # recovery with its own heads — per scenario, with the rollback
    # drill (a mitigation that doesn't heal rolls back on deadline)
    # and the no-oscillation gate (zero flag writes over a long clean
    # run). Same CPU-subprocess methodology as quality. {} on failure.
    mitig = {}
    if os.environ.get("BENCH_MITIG", "1") != "0":
        mitig = measure_mitigation_subprocess()

    # ---- stress config (BASELINE #4: 10× the Locust profile) ---------
    # Same methodology at 10× the rate with the async harvester (the
    # stress deployment shape); paired-RTT fields ride along.
    stress = {}
    if os.environ.get("BENCH_LAG_STRESS", "1") != "0":
        from opentelemetry_demo_tpu.runtime.lagbench import (
            measure_lag as run_lag,
        )

        stress = run_lag(
            rate=20_000.0, seconds=8.0, batch=1024, harvest_async=True,
            # Adaptive width: under readback-RTT-bound harvest the
            # controller widens batches until dispatch rate ≤ harvest
            # rate — bounding the report skip rate the stress gate
            # checks (r4 shipped 0.5 here; the gate wants <0.1).
            adaptive=True,
        )

    # ---- SLO verdicts (BASELINE.md:20-21) ----------------------------
    # Explicit pass/fail so a reader never reconstructs the argument:
    # throughput against the 200k/s star; lag on the NET basis (each
    # sample's paired tunnel RTT subtracted — the locally-attached-chip
    # number; the gross p99 sits on a ~130 ms topology floor this
    # environment cannot remove, see lag_note); stress skip rate gated
    # <0.1 (reports the operator actually sees under 10× load).
    lag_net = lag.get("p99_net_ms")
    stress_skip = stress.get("skip_rate")
    # e2e verdict basis (the ISSUE's gate): min(host ingest, kernel AT
    # THE MATCHED geometry/batch) — the spine bench measures its own
    # device-only reference so the ratio compares like with like; the
    # default-geometry headline kernel is the fallback basis.
    e2e_rate = e2e.get("spans_per_sec")
    e2e_kernel = e2e.get("kernel_spans_per_sec") or spans_per_sec
    # Ingest basis at the e2e run's OWN worker count (the sweep's max
    # may be a deeper pool than the e2e configured — holding the e2e
    # to a 4-worker ingest rate it never had would fail the gate for
    # the wrong reason); fall back to the sweep max.
    e2e_ingest = (
        ingest_scaling.get(str(e2e.get("workers"))) or ingest_rate
        if ingest_scaling else ingest_rate
    )
    e2e_bound = (
        min(e2e_ingest, e2e_kernel) if e2e_ingest else None
    )
    # Decode's share of pooled flush wall time at the 2-worker
    # geometry (the r15 decode-wall attribution; phase_share keys are
    # the TOP-level partition — scan/extract ride decode_split).
    decode_share_2w = (
        (ingest_detail.get("2") or {}).get("phase_share", {}).get("decode")
        if ingest_detail else None
    )
    slo = {
        "north_star_throughput_ok": bool(
            spans_per_sec >= BASELINE_SPANS_PER_SEC
        ),
        "north_star_lag_ok": (
            bool(lag_net < BASELINE_LAG_MS) if lag_net is not None else None
        ),
        "north_star_lag_basis": "net_of_paired_rtt",
        "stress_skip_rate_ok": (
            bool(stress_skip < 0.1) if stress_skip is not None else None
        ),
        # Host-ingest verdict: the pooled engine must sustain ≥3× the
        # r5 serial rate on the same CI topology (6.78M spans/s). Same
        # hardware-eligibility rule as decode_wall_ok: a 1-core box
        # cannot run a worker POOL against anything, so the verdict is
        # None (unmeasurable), not a fake regression (BENCH_r06 read
        # as a failure for exactly this reason).
        "host_ingest_ok": (
            bool(ingest_rate >= HOST_INGEST_TARGET)
            if ingest_rate is not None and (os.cpu_count() or 1) >= 2
            else None
        ),
        # Decode-wall verdict (r15): decode's share of pooled flush
        # wall time at the 2-worker CI geometry must sit ≤0.70 — the
        # two-pass scanner's intra-call sharding spreads extraction
        # over spare cores, so decode stops being the one serialized
        # envelope. The lever IS a second core: on a single-core
        # runner no thread can shard anything and the gate reports
        # None (unmeasurable), not a fake pass/fail.
        "decode_wall_ok": (
            bool(decode_share_2w <= 0.70)
            if decode_share_2w is not None and (os.cpu_count() or 1) >= 2
            else None
        ),
        # End-to-end spine verdict: payload→report throughput must
        # reach ≥90% of min(host ingest, kernel) — transfer + host
        # glue hidden behind the slower endpoint, proven not asserted.
        # Null-when-ineligible (decode_wall_ok's rule): the e2e spine
        # needs pool workers + pump + "device" step overlapping, which
        # one core cannot express.
        "e2e_ok": (
            bool(e2e_rate >= 0.9 * e2e_bound)
            if e2e_rate is not None and e2e_bound is not None
            and (os.cpu_count() or 1) >= 2
            else None
        ),
        # Self-telemetry verdict: the batch-lifecycle tracer + phase
        # histograms must cost ≤3% of e2e spine throughput.
        "selftrace_overhead_ok": (
            bool(selftrace_ab["ratio"] <= 1.03)
            if selftrace_ab.get("ratio") is not None else None
        ),
        # Provenance verdict: the evidence plane's per-report ring must
        # cost ≤3% of spine throughput (bundle assembly is flag-rare).
        "explain_overhead_ok": (
            bool(explain_ab["ratio"] <= 1.03)
            if explain_ab.get("ratio") is not None else None
        ),
        # Time-travel verdict: replaying a recorded segment log through
        # the real pipeline must run ≥10× wall clock with verdicts
        # bit-identical to the recording run.
        "replay_ok": replay.get("replay_ok"),
        # Auto-mitigation verdict: ≥3 scenarios with verified recovery,
        # the rollback drill restoring the exact prior flag state, and
        # ZERO flag writes over the long clean run (no oscillation).
        "mitigation_ok": mitig.get("mitigation_ok"),
        # Counterfactual pre-flight verdicts (r17; ride the mitigbench
        # subprocess when BENCH_SHADOW=1): shadow_ok = bit-identical
        # shadow replay at ≥ the rate target AND would-help released
        # within 2× the ungated TTM AND the wrong-flag refusal drill
        # holding (below). preflight_refusal_ok = the refusal drill
        # alone — a mitigation that would NOT help is refused BEFORE
        # any actuator write: zero flag-store mutations, budget token
        # refunded, flight-recorder evidence (ring event + dump file).
        "shadow_ok": mitig.get("shadow_ok"),
        "preflight_refusal_ok": mitig.get("preflight_refusal_ok"),
        # Front-door verdict (r19): OTLP/HTTP spans/s through the
        # native acceptor must meet the in-process pool at matched
        # workers — the framing provably free relative to decode. On a
        # 1-core box the bench's OWN load generator timeshares the
        # serving core, so the verdict is None by the same eligibility
        # rule as decode_wall_ok.
        "frontdoor_ok": (
            bool(
                frontdoor["frontdoor_spans_per_sec"]
                >= frontdoor["pool_spans_per_sec"]
            )
            if frontdoor.get("pool_spans_per_sec")
            and (os.cpu_count() or 1) >= 2
            else None
        ),
        # Million-key soak verdict: bounded intern count, read-back
        # identity, drift refusal at scale, zero corrupt frames —
        # computed inside the soak itself (frontdoorbench).
        "frontdoor_soak_ok": frontdoor_soak.get("soak_ok"),
        # Bounded-memory verdict (r20): RSS per million distinct keys
        # under SOAK_RSS_CEILING_MB_PER_MILLION (the old append-only
        # table's measured ~935 MB/M leak is the fail line). None when
        # RSS is unmeasurable or BENCH_FRONTDOOR_KEYS trimmed the run
        # below the normalization floor.
        "soak_rss_ok": frontdoor_soak.get("soak_rss_ok"),
        # Churn-soak verdict (r20 tentpole gate): ≥3× key budget of
        # distinct keys with churn through a keyspace-enabled pipeline
        # — evictions recycling ids under generation bumps, live-key
        # ids bit-stable, evicted keys answering from history labeled
        # source:"evicted", generation-drifted fleet merge refused,
        # zero corrupt frames, steady-state RSS slope ≈ 0.
        "churn_ok": churn_soak.get("churn_ok"),
    }

    print(
        json.dumps(
            {
                "metric": "sketch_update_throughput_single_chip",
                "value": round(spans_per_sec, 1),
                "unit": "spans/sec",
                "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
                "lag_p99_ms": lag["p99_ms"],
                "lag_vs_baseline": round(
                    BASELINE_LAG_MS / max(lag["p99_ms"], 1e-9), 3
                ),
                "lag_p99_net_ms": lag.get("p99_net_ms"),
                "lag_p50_net_ms": lag.get("p50_net_ms"),
                "lag_net_vs_baseline": (
                    round(BASELINE_LAG_MS / max(lag["p99_net_ms"], 1e-9), 3)
                    if lag.get("p99_net_ms") is not None
                    else None
                ),
                "lag_rtt_p50_ms": lag.get("rtt_p50_ms"),
                "lag_rtt_p99_ms": lag.get("rtt_p99_ms"),
                "lag_rtt_pairs": lag.get("rtt_pairs"),
                "lag_rate_spans_per_sec": lag["rate"],
                "lag_batches": lag["batches"],
                "lag_stress_p99_ms": stress.get("p99_ms"),
                "lag_stress_p99_net_ms": stress.get("p99_net_ms"),
                "lag_stress_rate_spans_per_sec": stress.get("rate"),
                "lag_stress_batches": stress.get("batches"),
                "lag_stress_reports_skipped": stress.get("reports_skipped"),
                "lag_stress_skip_rate": stress.get("skip_rate"),
                "lag_stress_final_batch_width": stress.get("final_batch_width"),
                **slo,
                "ttd_s": {
                    name: entry.get("ttd_s")
                    for name, entry in (quality.get("ttd") or {}).items()
                },
                "fp_rate": quality.get("fp_rate"),
                "paymentFailure_ttd_by_rate": quality.get(
                    "paymentFailure_ttd_by_rate"
                ),
                "detection_quality": quality or None,
                "fetch_rtt_ms": fetch_rtt_ms,
                "host_ingest_spans_per_sec": (
                    round(ingest_rate, 1) if ingest_rate else None
                ),
                "host_ingest_serial_spans_per_sec": (
                    round(ingest_serial, 1) if ingest_serial else None
                ),
                "host_ingest_scaling": ingest_scaling or None,
                "host_ingest_vs_r5": (
                    round(ingest_rate / R5_HOST_INGEST_SPANS_PER_SEC, 3)
                    if ingest_rate else None
                ),
                "host_ingest_phase_share": (
                    ingest_detail.get(
                        max(
                            ingest_scaling,
                            key=lambda k: ingest_scaling[k],
                        ),
                        {},
                    ).get("phase_share")
                    if ingest_scaling else None
                ),
                "host_ingest_decode_share": decode_share_2w,
                "host_ingest_decode_split": (
                    (ingest_detail.get("2") or {}).get("decode_split")
                    if ingest_detail else None
                ),
                "e2e_spans_per_sec": (
                    round(e2e_rate, 1) if e2e_rate else None
                ),
                "e2e_vs_kernel": (
                    round(e2e_rate / e2e_kernel, 3) if e2e_rate else None
                ),
                "e2e_kernel_spans_per_sec": e2e.get("kernel_spans_per_sec"),
                "e2e_vs_host_ingest": (
                    round(e2e_rate / ingest_rate, 3)
                    if e2e_rate and ingest_rate else None
                ),
                "e2e_overlap_ratio": e2e.get("overlap_ratio"),
                "e2e_phase_share": e2e.get("phase_share"),
                "e2e_note": (
                    "payload->flagged-report through decode pool + "
                    "admission + device-put spine + donated one-pass "
                    "step; e2e_ok gates >=90% of min(host ingest, "
                    "kernel at the spine bench's own geometry/batch). "
                    "On CPU-only topologies the host threads contend "
                    "with the 'device' step for the same cores, so "
                    "the gate is meaningful only with a real "
                    "accelerator"
                ) if e2e else None,
                "frontdoor_spans_per_sec": frontdoor.get(
                    "frontdoor_spans_per_sec"
                ),
                "frontdoor_pool_spans_per_sec": frontdoor.get(
                    "pool_spans_per_sec"
                ),
                "frontdoor_vs_pool": frontdoor.get("frontdoor_vs_pool"),
                "frontdoor_soak_keys": frontdoor_soak.get("distinct_keys"),
                "frontdoor_soak_rss_per_million_keys_mb": (
                    frontdoor_soak.get("rss_per_million_keys_mb")
                ),
                "frontdoor_soak_keys_per_sec": frontdoor_soak.get(
                    "keys_per_sec"
                ),
                "frontdoor_soak_overflow_keys": frontdoor_soak.get(
                    "overflow_keys"
                ),
                "churn_soak_evictions": churn_soak.get("evictions"),
                "churn_soak_generation": churn_soak.get("generation"),
                "churn_soak_distinct_streamed": churn_soak.get(
                    "distinct_streamed"
                ),
                "churn_soak_rss_slope_mb": churn_soak.get("rss_slope_mb"),
                "selftrace_overhead_ratio": selftrace_ab.get("ratio"),
                "selftrace_spans_per_sec_on": selftrace_ab.get(
                    "spans_per_sec_on"
                ),
                "selftrace_traces_exported": selftrace_ab.get(
                    "traces_exported"
                ),
                "explain_overhead_ratio": explain_ab.get("ratio"),
                "explain_spans_per_sec_on": explain_ab.get(
                    "spans_per_sec_on"
                ),
                "query_p99_ms": queryq.get("query_p99_ms"),
                "query_p50_ms": queryq.get("query_p50_ms"),
                "query_qps": queryq.get("query_qps"),
                "query_ingest_ratio": queryq.get("ingest_ratio"),
                "explain_p99_ms": queryq.get("explain_p99_ms"),
                "replay_speedup": replay.get("replay_speedup"),
                "replay_verdicts_identical": replay.get(
                    "replay_verdicts_identical"
                ),
                "replay_batches": replay.get("replay_batches"),
                "history_range_query_p99_ms": replay.get(
                    "history_range_query_p99_ms"
                ),
                "history_range_query_p50_ms": replay.get(
                    "history_range_query_p50_ms"
                ),
                "time_to_mitigate_s": mitig.get("time_to_mitigate_s"),
                "mitigation_rollback_exercised": (
                    mitig.get("rollback_drill", {}).get("rolled_back")
                    if mitig else None
                ),
                "mitigation_no_oscillation": (
                    mitig.get("no_oscillation", {}).get("ok")
                    if mitig else None
                ),
                "mitigation_detail": mitig or None,
                "preflight_verdict_s": mitig.get("preflight_verdict_s"),
                "preflight_ttm_ratio": mitig.get("preflight_ttm_ratio"),
                "shadow_identical": mitig.get("shadow_identical"),
                "shadow_speedup": mitig.get("shadow_speedup"),
                "collector_keep_ratio": mitig.get("collector_keep_ratio"),
                "collector_storage_reduction": mitig.get(
                    "collector_storage_reduction"
                ),
                "failover_ttd_s": repl.get("failover_ttd_s"),
                "replication_lag_p99_ms": repl.get(
                    "replication_lag_p99_ms"
                ),
                "failover_converged_exact": repl.get("converged_exact"),
                "shard_reshard_ttd_s": fleet_drill.get(
                    "shard_reshard_ttd_s"
                ),
                "fleet_ok": fleet_drill.get("fleet_ok"),
                "fleet_reshard_bitexact": fleet_drill.get(
                    "reshard_bitexact"
                ),
                "fleet_partial_answer_ok": fleet_drill.get(
                    "partial_answer_ok"
                ),
                "fleet_noisy_tenant_isolated": fleet_drill.get(
                    "noisy_tenant_isolated"
                ),
                "autoscale_tta_s": autoscale_drill.get(
                    "autoscale_tta_s"
                ),
                "autoscale_ok": autoscale_drill.get("autoscale_ok"),
                "autoscale_adoption_bitexact": autoscale_drill.get(
                    "adoption_bitexact"
                ),
                "sketch_impl_matrix": matrix,
                "lag_note": (
                    "gross p99 is submit-to-harvest through the real "
                    "pipeline; every harvest's device-to-host fetch pays "
                    "one tunnel round trip on this topology, so each lag "
                    "sample is PAIRED with a 1-scalar fetch probe that "
                    "rides the tunnel CONCURRENTLY with that harvest's "
                    "report fetch (same congestion window) — p99_net is "
                    "the p99 of elementwise lag minus paired RTT, the "
                    "locally-attached-chip number; rtt_p50/p99 bound the "
                    "topology floor and jitter the gross number sits on"
                ),
            }
        )
    )


def _measure_module_subprocess(module: str, timeout_s: float) -> dict:
    """Run a bench module in a pristine CPU interpreter; {} on failure
    (these fields are additive — a broken CPU leg must not sink the
    throughput/lag artifact)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # one tunnel holder at a time
    here = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", module],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=timeout_s,
        )
        if proc.returncode != 0:
            print(proc.stderr[-2000:], file=sys.stderr)
            return {}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, json.JSONDecodeError, IndexError):
        return {}


def measure_quality_subprocess(timeout_s: float = 900.0) -> dict:
    """Detection-quality scenarios (runtime.qualbench) on CPU: the
    per-step report fetches must not pay the tunneled-TPU RTT."""
    return _measure_module_subprocess(
        "opentelemetry_demo_tpu.runtime.qualbench", timeout_s
    )


def measure_mitigation_subprocess(timeout_s: float = 1500.0) -> dict:
    """Closed-loop mitigation drill (runtime.mitigbench) on CPU: the
    same stepped-report methodology as qualbench, plus the remediation
    controller acting through a live flag store. With BENCH_SHADOW=1
    (default) the subprocess folds in the counterfactual pre-flight
    leg — shadow bit-identity, both verdict directions, the collector
    keep/drop measurement — hence the wider timeout."""
    return _measure_module_subprocess(
        "opentelemetry_demo_tpu.runtime.mitigbench", timeout_s
    )


def measure_fetch_rtt() -> float:
    """Median ms of a 1-scalar device→host fetch (the harvest's floor).

    block_until_ready can return early on tunneled PJRT topologies, so
    the only honest synchronization is the fetch itself — which is
    exactly what the pipeline's harvest pays per report. Each sample
    fetches a FRESH device value (jax.Array caches its host copy after
    the first conversion, so re-fetching the same array times a dict
    lookup, not the wire).
    """
    base = jnp.zeros((), jnp.int32)
    bump = jax.jit(lambda s, i: s + i)
    samples = []
    for i in range(7):
        fresh = bump(base, i)
        t0 = time.perf_counter()
        _ = int(np.asarray(fresh))
        samples.append((time.perf_counter() - t0) * 1000.0)
    samples.sort()
    return round(samples[len(samples) // 2], 3)


def measure_lag(rng):
    """p99 submit→harvest lag via the shared methodology
    (runtime.lagbench — also the scripts/bench_lag.py engine)."""
    del rng  # lagbench owns its seeding
    from opentelemetry_demo_tpu.runtime.lagbench import measure_lag as run

    return run(
        rate=float(os.environ.get("BENCH_LAG_RATE", 2_000.0)),
        seconds=float(os.environ.get("BENCH_LAG_SECONDS", 12.0)),
    )


if __name__ == "__main__":
    main()
