"""Benchmark: sketch-update throughput of the flagship detector step.

Measures sustained spans/sec through the full single-chip detector update
(HLL + CMS + EWMA heads + heavy-hitter query + window rotation) on
device-resident batches — the BASELINE north-star metric
("≥200,000 spans/sec sketch updates on a single v5e-1").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "spans/sec", "vs_baseline": N}

Methodology: a pool of pre-tensorized batches lives on device (host
ingest is benchmarked separately; the north star isolates sketch-update
throughput), the state buffer is donated every step, window-rotation
masks cycle at the cadence a real 200k spans/s stream would see, and
nothing syncs to host inside the timed loop. Reported number is
spans/sec over the whole timed region including rotations.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from opentelemetry_demo_tpu.models import (
    DetectorConfig,
    detector_init,
    detector_step,
)
from opentelemetry_demo_tpu.runtime import SpanTensorizer

BASELINE_SPANS_PER_SEC = 200_000.0


def make_batch_pool(config, batch_size, n_pool, rng):
    tz = SpanTensorizer(num_services=config.num_services, batch_size=batch_size)
    pool = []
    for _ in range(n_pool):
        tb = tz.pack_arrays(
            svc=rng.integers(0, 20, size=batch_size),
            lat_us=rng.gamma(4.0, 250.0, size=batch_size).astype(np.float32),
            trace_id=rng.integers(0, 2**63, size=batch_size, dtype=np.uint64),
            is_error=(rng.random(batch_size) < 0.02).astype(np.float32),
            attr_key=rng.zipf(1.5, size=batch_size).astype(np.uint64),
        )
        pool.append(
            tuple(
                jax.device_put(jnp.asarray(x))
                for x in (
                    tb.svc, tb.lat_us, tb.is_error,
                    tb.trace_hi, tb.trace_lo, tb.attr_hi, tb.attr_lo, tb.valid,
                )
            )
        )
    return pool


def main():
    # Throughput scales ~linearly with batch (2048→10.9M, 8192→86M,
    # 32768→359M, 65536→713M spans/s on v5e-1) — the fused kernel's
    # batch-grid tiling (ops/fused.py) keeps VMEM bounded at any B.
    # 65536 is the practical peak (131072 trips a residual scoped-VMEM
    # edge). Overridable for sweeps.
    batch_size = int(os.environ.get("BENCH_BATCH", 65536))
    config = DetectorConfig()
    step = jax.jit(partial(detector_step, config), donate_argnums=0)
    rng = np.random.default_rng(0)

    n_pool = 8
    pool = make_batch_pool(config, batch_size, n_pool, rng)
    # dt stays a Python-derived constant end to end: fetching even one
    # device scalar to host (e.g. float(dt)) degrades axon tunnel
    # dispatch ~20x for the rest of the process with no recovery
    # (measured directly: 68us/step before a single float(dt), then
    # 1.3-3ms/step on every later fetch-free loop), so the timed loop
    # and everything before it must be fetch-free.
    dt_host = batch_size / BASELINE_SPANS_PER_SEC
    dt = jnp.float32(dt_host)

    # Rotation cadence as seen by a stream at the baseline rate: the 1s
    # window rotates every ~1s/dt steps, the 10s/60s windows at 1/10 and
    # 1/60 of that.
    steps_per_sec = max(int(1.0 / dt_host), 1)
    masks = []
    for i in range(steps_per_sec * 60):
        masks.append(
            (i % steps_per_sec == 0,
             i % (steps_per_sec * 10) == 0,
             i % (steps_per_sec * 60) == 0)
        )
    uniq = {m: jnp.asarray(m) for m in set(masks)}
    mask_seq = [uniq[m] for m in masks]

    state = detector_init(config)
    # Warmup / compile.
    state, report = step(state, *pool[0], dt, mask_seq[1])
    jax.block_until_ready(state)

    # Calibrate to a ~4s timed region.
    t0 = time.perf_counter()
    probe = 50
    for i in range(probe):
        state, report = step(state, *pool[i % n_pool], dt, mask_seq[i % len(mask_seq)])
    jax.block_until_ready(state)
    per_step = (time.perf_counter() - t0) / probe
    iters = max(int(4.0 / per_step), 200)

    t0 = time.perf_counter()
    for i in range(iters):
        state, report = step(state, *pool[i % n_pool], dt, mask_seq[i % len(mask_seq)])
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    spans_per_sec = batch_size * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "sketch_update_throughput_single_chip",
                "value": round(spans_per_sec, 1),
                "unit": "spans/sec",
                "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
