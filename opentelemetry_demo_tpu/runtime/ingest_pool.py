"""Parallel host-ingest engine: decode pool + pooled buffers + coalesce.

The device side of the detector sustains ~119M spans/s (bench.py); the
r5 host-ingest path topped out at ~2.26M spans/s — 53× below the rate
it must feed (SURVEY.md §7 hard part (a)). Profiling put the gap almost
entirely on the HOST glue around the native decoder, not in it: the C
scan runs ~7M spans/s single-threaded on the CI box, but every request
paid one ctypes round trip, eight fresh ``np.empty`` output arrays,
eight ``.copy()`` slices, an intern pass, and one pipeline-lock
acquisition — all serial on the receiver thread. This module removes
each of those per-REQUEST costs by making them per-FLUSH:

- **Sharded decode pool** — N worker threads pull raw payloads off one
  bounded queue. ``ctypes.CDLL`` drops the GIL for the duration of the
  native call (runtime/native.py module doc), so workers decode in
  true parallel and scale with cores.
- **Coalesced batch decode** — each worker drains up to
  ``coalesce_max`` queued requests and decodes them with ONE
  ``native.decode_otlp_many`` call: one GIL round trip amortized over
  the whole batch. Per-payload verdicts ride back in ``payload_rows``,
  so a malformed request still answers 400 for exactly that request
  while its batchmates proceed.
- **Pooled zero-copy output buffers with ticketed release** — decode
  writes into a :class:`ScratchPool` freelist of column arrays sized
  by high-watermark: steady-state decode performs zero numpy
  allocations. The flush hands the pipeline VIEWS into the scratch —
  no per-flush copy-out at all (the r7 frame round trip copied every
  row once per flush; the spine removes it). Safety is the ticket: a
  scratch whose views escaped is PARKED, re-entering the freelist only
  once no pipeline reference to its memory remains, and its
  decode-time CRC manifest (``frame.span_column_crcs``) is re-checked
  at recycle — a buffer scribbled while rows were live surfaces as
  ``anomaly_frame_corrupt_total{hop="ingest"}`` + quarantine evidence
  instead of silently feeding the sketches another request's rows
  (tests/test_ingest_pool.py + tests/test_frame.py pin this).
- **One tensorize + one merge per flush** — a single intern pass over
  the batch-wide service list and a single
  ``SpanColumns``/``submit_columns`` call per flush, so the pipeline
  lock and the interner are touched once per thousands of spans, not
  once per request.

Overload semantics are PRESERVED: admission control still lives in
``pipeline.submit_columns`` (shed/brownout/429 watermarks fire exactly
as before — the pool sits in front of the same gate), and the pool's
own queue is bounded — a full queue raises
:class:`IngestPoolSaturated`, which the receivers answer as the same
retryable 429/``RESOURCE_EXHAUSTED`` they use for pipeline saturation.
No unbounded buffer ever forms ahead of the pool. Receivers resolve a
request's ticket only AFTER its rows hit ``submit_columns``, so a 200
still means "enqueued", exactly the serial path's contract.

Latency: coalescing is opportunistic, not timed — a worker drains
whatever is queued RIGHT NOW and decodes immediately, so an idle
deployment sees single-request latency (no flush-interval tax) while a
loaded one sees deep batches automatically (the queue fills while
workers are busy — the same self-clocking the reference collector's
batch processor exhibits under load).

The r15 decode-wall rework sharpened the engine on three axes:

- **Two-pass native scanner** (ingest.cc): decode is now a structural
  boundary scan (pass 1 → span index) plus an index-driven column
  extraction (pass 2), reported separately to the
  ``anomaly_phase_seconds{phase=scan|extract}`` histograms.
- **Intra-call sharding**: a flush carrying ≥
  ``ANOMALY_INGEST_SHARD_MIN_BYTES`` of payload splits its pass-2
  extraction across up to ``ANOMALY_INGEST_NATIVE_THREADS`` native OS
  threads at span-record boundaries — mid-payload included, so ONE
  oversized OTLP export spreads over cores instead of serializing on
  whichever worker drained it.
- **Per-worker arena interning** (tensorize.InternArena): each worker
  resolves the flush's service names against worker-local memory; only
  a never-seen name pays one batched reconciliation against the shared
  read-mostly table. Intern ids stay bit-identical to the serial path.

Knob registry: ``utils.config.INGEST_KNOBS`` (workers / coalesce /
max-pending / native-threads / shard-min-bytes), threaded through the
daemon env, the compose overlay and the k8s generator;
scripts/sanitycheck.py pins the correspondence.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from collections import deque
from typing import Callable, NamedTuple, Sequence

from . import frame, native
from .otlp import MONITORED_ATTR_KEYS, decode_export_request
from .selftrace import (
    PHASE_DECODE,
    PHASE_EXTRACT,
    PHASE_SCAN,
    PHASE_SUBMIT,
    PHASE_TENSORIZE,
    PHASE_VERIFY,
)
from .tensorize import InternArena, SpanColumns, SpanRecord, SpanTensorizer

# Phases whose durations PARTITION a flush's wall time. PHASE_SCAN /
# PHASE_EXTRACT are sub-phases INSIDE the decode envelope (the native
# two-pass split) — share computations over TOP_PHASES stay a true
# breakdown while the sub-phases ride the same histograms for
# attribution.
TOP_PHASES = (PHASE_DECODE, PHASE_VERIFY, PHASE_TENSORIZE, PHASE_SUBMIT)


class IngestPoolSaturated(RuntimeError):
    """The bounded request queue ahead of the pool is full — the
    receivers' cue to answer retryable 429/RESOURCE_EXHAUSTED."""


class IngestWorkerError(RuntimeError):
    """A flush failed SERVER-side (e.g. the pipeline sink raised) after
    decode — distinct from a per-payload decode verdict so the
    receivers answer 5xx/INTERNAL for our bugs and 400 only for the
    client's bad bytes (the serial path's 'server bugs must surface,
    not masquerade as a client error' contract)."""


class DecodeTicket:
    """Per-request decode verdict: the receiver blocks on ``result()``
    to answer 400 (malformed) vs 200 (decoded AND enqueued).

    The Event is allocated LAZILY, only when a waiter arrives before
    the verdict: fire-and-forget submitters (the Kafka pump, benches)
    then pay one flag write instead of a kernel-object allocation per
    request. The ``_done``-before-``_event`` publication order below
    makes the lock-free handshake safe under the GIL: whichever of
    {resolver reads ``_event``, waiter re-reads ``_done``} happens
    second sees the other side's write.
    """

    __slots__ = ("_done", "_error", "_event")

    def __init__(self) -> None:
        self._done = False
        self._error: BaseException | None = None
        self._event: threading.Event | None = None

    def _resolve(self, error: BaseException | None = None) -> None:
        self._error = error
        self._done = True  # publish BEFORE checking for a waiter
        ev = self._event
        if ev is not None:
            ev.set()

    def done(self) -> bool:
        """Non-blocking: has the request's flush landed (success or
        error)? Safe from any thread — ``_done`` is published last by
        the resolver. The front-door pump polls this to defer a
        wedged-flush verdict instead of abandoning a borrowed native
        buffer (``runtime/frontdoor.py``)."""
        return self._done

    def result(self, timeout: float = 30.0) -> None:
        """Block until the request's flush lands; re-raise its decode
        error (``ValueError`` for malformed wire data) if any."""
        if not self._done:
            ev = self._event
            if ev is None:
                ev = threading.Event()
                self._event = ev
                if self._done:  # resolver ran before our store landed
                    ev.set()
            if not ev.wait(timeout):
                raise TimeoutError("ingest pool did not resolve the request")
        if self._error is not None:
            raise self._error


class _ParkedScratch(NamedTuple):
    """A ticketed scratch: held OUT of the freelist until no pipeline
    view references its memory, then CRC-verified and recycled."""

    scratch: object  # native.DecodeScratch
    cols: object  # native.ColumnarSpans — the decode views, retained
    crcs: dict  # frame.span_column_crcs manifest from decode time


class ScratchPool:
    """Freelist of :class:`native.DecodeScratch` buffer sets, sized by
    high-watermark: the first few flushes grow the dims, after which
    every acquire is a pop — zero allocator churn on the hot path. At
    most ``keep`` sets are retained (one per worker is enough; an
    occasional burst allocates and is dropped on release).

    **Ticketed release** (the zero-copy ingest spine): a flush that
    handed SCRATCH VIEWS to the pipeline parks its scratch instead of
    releasing it. A parked scratch re-enters the freelist only once no
    outside reference to its column memory remains — checked by
    refcount under the GIL: each retained decode view holds exactly one
    reference to its backing array, and every pipeline slice holds one
    more (numpy collapses ``view.base`` to the owning array), so a
    quiescent lane shows exactly the pool's own references. Before
    recycling, the decode-time CRC manifest is re-verified against the
    scratch memory: a mismatch means something scribbled the buffer
    while rows were still live — the aliasing bug class the old
    frame-copy-out caught per flush — and the scratch is discarded with
    the evidence queued for the owner to count + quarantine. A scratch
    whose views outlive demand simply stays parked; ``acquire`` then
    allocates fresh (visible in ``allocations``) rather than ever
    recycling live memory.
    """

    def __init__(self, keep: int = 4):
        self._free: list = []
        self._lock = threading.Lock()
        self._keep = keep
        self._hw = (0, 0, 0)
        self._parked: list[_ParkedScratch] = []
        self.allocations = 0  # how often acquire had to allocate
        self.tickets_parked = 0  # flushes that handed out scratch views
        self.tickets_recycled = 0  # parked scratches returned to the freelist
        # Scavenged entries whose memory no longer matched the decode
        # manifest: (cols, bad_column_names) for the owner to count and
        # quarantine (detection is at recycle time — after the rows were
        # consumed — so this is an audit trail, not a gate). The deque
        # bounds EVIDENCE retention only; corrupt_total is the honest
        # monotone count (an event storm past the deque bound must not
        # undercount the counter the audit trail exists to feed).
        self.corrupt: deque = deque(maxlen=16)
        self.corrupt_total = 0

    @staticmethod
    def _quiescent(entry: _ParkedScratch) -> bool:
        """True when no reference outside the parked entry can reach
        the scratch memory (CPython refcounts, checked under the GIL).

        Per retained view: the entry's cols tuple plus this frame's
        local are the only holders (refcount 3 incl. the getrefcount
        temp); per backing array: the scratch namedtuple, that one
        view's ``.base`` slot and this frame's local (refcount 4 incl.
        temp) — any pipeline slice of a handed-out view keeps a base
        reference to the backing array and shows up here. Another
        thread mid-read merely elevates a count for one round — the
        check is conservative, never unsafe. Iterates every ARRAY
        field of the ColumnarSpans (the trailing ``services`` string
        list has no ``.dtype``), so a future column can't silently
        escape the quiescence check."""
        for i in range(len(entry.cols)):
            view = entry.cols[i]
            if not hasattr(view, "dtype"):
                continue  # services list, not a column array
            if sys.getrefcount(view) > 3:
                return False
            base = view.base
            if base is not None and sys.getrefcount(base) > 4:
                return False
        return True

    def _scavenge_locked(self) -> None:
        still: list[_ParkedScratch] = []
        for entry in self._parked:
            if not self._quiescent(entry):
                still.append(entry)
                continue
            bad = frame.verify_span_columns(entry.cols, entry.crcs)
            if bad:
                # Scribbled while parked: never recycle the buffer,
                # surface the evidence (drained by the ingest pool
                # into anomaly_frame_corrupt_total{hop="ingest"}).
                self.corrupt_total += 1
                self.corrupt.append((entry.cols, bad))
            else:
                self.tickets_recycled += 1
                if len(self._free) < self._keep:
                    self._free.append(entry.scratch)
        self._parked = still

    def parked(self) -> int:
        with self._lock:
            return len(self._parked)

    def park(self, scratch, cols, crcs: dict) -> None:
        """Ticketed release: hold ``scratch`` until the pipeline drops
        every view into it (see class doc), then verify + recycle."""
        with self._lock:
            self._parked.append(_ParkedScratch(scratch, cols, crcs))
            self.tickets_parked += 1

    def acquire(self, cap: int, svc_cap: int, rs_cap: int):
        with self._lock:
            self._scavenge_locked()
            self._hw = (
                max(self._hw[0], cap),
                max(self._hw[1], svc_cap),
                max(self._hw[2], rs_cap),
            )
            for i, s in enumerate(self._free):
                if s.cap >= cap and s.svc_cap >= svc_cap and s.rs_cap >= rs_cap:
                    return self._free.pop(i)
            hw = self._hw
            self.allocations += 1
        return native.alloc_scratch(*hw)

    def release(self, scratch) -> None:
        with self._lock:
            if len(self._free) < self._keep:
                self._free.append(scratch)


_STOP = object()


class _JobQueue:
    """Bounded MPMC queue with BATCHED consume.

    ``queue.Queue`` costs one lock round trip per ``get`` — 64 of them
    per coalesced flush. ``get_batch`` pops the whole coalesce window
    under ONE lock acquisition, which is where the pool's per-request
    overhead has to live for the flush amortization to mean anything.
    ``put`` blocks up to ``timeout`` for space and then raises
    ``queue.Full`` (the bounded-admission contract).
    """

    def __init__(self, maxsize: int):
        self._d: deque = deque()
        self._max = int(maxsize)
        lock = threading.Lock()
        self._not_empty = threading.Condition(lock)
        self._not_full = threading.Condition(lock)

    def put(self, item, timeout: float) -> None:
        with self._not_full:
            if len(self._d) >= self._max:
                deadline = time.monotonic() + timeout
                while len(self._d) >= self._max:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise queue.Full
                    self._not_full.wait(remaining)
            self._d.append(item)
            self._not_empty.notify()

    def put_unbounded(self, item) -> None:
        """Bypass the bound (shutdown sentinels only)."""
        with self._not_empty:
            self._d.append(item)
            self._not_empty.notify()

    def get_batch(self, max_n: int) -> list:
        with self._not_empty:
            while not self._d:
                self._not_empty.wait()
            n = min(len(self._d), max_n)
            batch = [self._d.popleft() for _ in range(n)]
            self._not_full.notify(n)
            return batch

    def qsize(self) -> int:
        return len(self._d)


class IngestPool:
    """N decode workers between the receivers and the pipeline.

    ``submit(payload)`` (OTLP/HTTP + OTLP/gRPC protobuf bodies) returns
    a :class:`DecodeTicket`; ``submit_records(records)`` (the Kafka
    pump and any already-decoded source) folds record batches into the
    same coalesced flushes. The off switch lives at the call site: the
    daemon simply doesn't construct a pool when
    ``ANOMALY_INGEST_WORKERS=0`` (receivers then keep the serial
    in-thread decode path), so a constructed pool always has ≥1 worker.
    """

    SUBMIT_TIMEOUT_S = 1.0  # bounded wait for queue space before 429

    def __init__(
        self,
        submit_columns: Callable[[SpanColumns], None],
        tensorizer: SpanTensorizer,
        workers: int = 2,
        coalesce_max: int = 64,
        max_pending: int = 512,
        attr_keys: Sequence[str] = MONITORED_ATTR_KEYS,
        phase_observe=None,
        selftrace=None,
        native_threads: int = 2,
        shard_min_bytes: int = native.SHARD_MIN_BYTES_DEFAULT,
    ):
        if workers <= 0:
            raise ValueError("IngestPool needs workers >= 1 (0 = no pool)")
        self.submit_columns = submit_columns
        self.tensorizer = tensorizer
        self.workers = int(workers)
        self.coalesce_max = max(int(coalesce_max), 1)
        # Intra-call sharding (the two-pass scanner's pass 2): a flush
        # carrying >= shard_min_bytes of payload splits its extraction
        # across up to native_threads OS threads at span-record
        # boundaries — one oversized export no longer serializes on
        # one core even when only one pool worker holds it.
        # native_threads <= 1 keeps extraction serial per call.
        self.native_threads = int(native_threads)
        self.shard_min_bytes = int(shard_min_bytes)
        self.attr_keys = tuple(attr_keys)
        # Self-telemetry (runtime.selftrace): ``phase_observe(phase,
        # seconds)`` feeds the promoted anomaly_phase_seconds
        # histograms per flush; ``selftrace.flush_segment`` records the
        # same durations as an ingest segment the next sampled batch
        # trace absorbs. Both optional and both cheap — one callback /
        # one bounded append per FLUSH, never per request.
        self.phase_observe = phase_observe
        self.selftrace = selftrace
        self._q = _JobQueue(max_pending)
        self._scratch = ScratchPool(keep=self.workers + 1)
        # Stats (guarded by _stats_lock; read by the daemon's scrape).
        self._stats_lock = threading.Lock()
        self.submitted = 0
        self.flushes = 0
        self.flushed_spans = 0
        self.coalesced_requests = 0
        self.decode_errors = 0
        self.worker_failures = 0  # server-side flush failures (per flush)
        # Parked-scratch CRC mismatches (lifecycle bugs, memory
        # corruption): counted + quarantined at scavenge time.
        # Exported as anomaly_frame_corrupt_total{hop="ingest"}.
        self.frames_corrupt = 0
        # Per-phase flush wall time (decode / verify / tensorize /
        # submit) — the attribution the spine's win is measured by
        # (ingestbench phase breakdown).
        self.phase_s = {
            PHASE_DECODE: 0.0, PHASE_SCAN: 0.0, PHASE_EXTRACT: 0.0,
            PHASE_VERIFY: 0.0, PHASE_TENSORIZE: 0.0, PHASE_SUBMIT: 0.0,
        }
        self._scratch_corrupt_seen = 0
        self.busy_s = 0.0  # summed across workers
        self._started = time.monotonic()
        # Drain accounting: jobs submitted but not yet fully processed.
        self._inflight = 0
        self._idle = threading.Condition(self._stats_lock)
        self._stop = False
        self._threads: list[threading.Thread] = []
        for i in range(self.workers):
            self._spawn(i)

    def _spawn(self, idx: int) -> None:
        t = threading.Thread(
            target=self._run, name=f"ingest-pool-{idx}", daemon=True
        )
        t.start()
        if idx < len(self._threads):
            self._threads[idx] = t
        else:
            self._threads.append(t)

    # -- producer side -------------------------------------------------

    def submit(self, payload: bytes) -> DecodeTicket:
        """Enqueue one protobuf ExportTraceServiceRequest body.

        Blocks briefly for queue space; a still-full queue raises
        :class:`IngestPoolSaturated` — the bounded-admission contract
        (never an unbounded buffer ahead of the pool).
        """
        ticket = DecodeTicket()
        self._enqueue(("payload", payload, ticket))
        return ticket

    def submit_records(
        self, records: list[SpanRecord]
    ) -> DecodeTicket | None:
        """Enqueue already-decoded records (Kafka pump etc.) for the
        same coalesced tensorize+merge pass. Returns a ticket that
        resolves once the batch's flush reached the pipeline (the
        pump's at-least-once bookkeeping waits on it), or None for an
        empty batch. The ticket's Event is lazy, so fire-and-forget
        callers pay nothing for ignoring it."""
        if not records:
            return None
        ticket = DecodeTicket()
        self._enqueue(("records", records, ticket))
        return ticket

    def _enqueue(self, item) -> None:
        with self._stats_lock:
            self.submitted += 1
            self._inflight += 1
        try:
            self._q.put(item, timeout=self.SUBMIT_TIMEOUT_S)
        except queue.Full:
            with self._stats_lock:
                self.submitted -= 1
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.notify_all()
            raise IngestPoolSaturated(
                f"ingest queue full ({self._q._max} pending requests)"
            ) from None

    def depth(self) -> int:
        return self._q.qsize()

    # -- worker side ---------------------------------------------------

    def _run(self) -> None:
        # Per-worker intern arena: the flush's service names resolve
        # against worker-local memory; only a genuinely new name pays
        # ONE batched reconciliation with the shared tensorizer table.
        # Ids are bit-identical to the serial service_id path.
        arena = InternArena(self.tensorizer)
        while True:
            batch = self._q.get_batch(self.coalesce_max)
            jobs = [b for b in batch if b is not _STOP]
            n_stop = len(batch) - len(jobs)
            # A batched pop can swallow sentinels meant for sibling
            # workers: hand the extras back before exiting.
            for _ in range(n_stop - 1):
                self._q.put_unbounded(_STOP)
            if jobs:
                t0 = time.perf_counter()
                try:
                    self._process(jobs, arena)
                except Exception as e:  # noqa: BLE001 — worker survives
                    # Unexpected (non-decode) failure: resolve every
                    # ticket with a SERVER-fault wrapper so no receiver
                    # hangs and none of them mistakes our bug for a
                    # malformed payload; counted as a worker failure
                    # (per flush), NOT as decode_errors — that counter
                    # means "client sent wire garbage" and must stay
                    # honest for triage.
                    err = IngestWorkerError(f"{type(e).__name__}: {e}")
                    err.__cause__ = e
                    for _kind, _data, ticket in jobs:
                        if ticket is not None and not ticket._done:
                            ticket._resolve(err)
                    with self._stats_lock:
                        self.worker_failures += 1
                finally:
                    dt = time.perf_counter() - t0
                    with self._stats_lock:
                        self.busy_s += dt
                        self._inflight -= len(jobs)
                        if self._inflight == 0:
                            self._idle.notify_all()
            if n_stop:
                return

    def _process(self, batch: list, arena: InternArena | None = None) -> None:
        payload_jobs = [(d, t) for kind, d, t in batch if kind == "payload"]
        record_jobs = [(d, t) for kind, d, t in batch if kind == "records"]
        parts: list[SpanColumns] = []
        errors: dict[int, BaseException] = {}  # job index → decode error
        # Per-flush phase ledger: the same durations feed the lifetime
        # phase_s counters, the anomaly_phase_seconds histograms and
        # (when a tracer rides along) the ingest segment of the next
        # sampled batch trace — one measurement, three consumers.
        seg: dict[str, float] = {}
        if payload_jobs:
            if native.available():
                parts += self._decode_native(payload_jobs, errors, seg, arena)
            else:
                parts += self._decode_python(payload_jobs, errors, seg)
        if record_jobs:
            t0 = time.perf_counter()
            merged: list[SpanRecord] = []
            for records, _t in record_jobs:
                merged.extend(records)
            parts.append(self.tensorizer.columns_from_records(merged))
            self._phase(PHASE_TENSORIZE, time.perf_counter() - t0, seg)
        cols = SpanColumns.concat(parts) if parts else None
        n_rows = cols.rows if cols is not None else 0
        if n_rows:
            t0 = time.perf_counter()
            self.submit_columns(cols)
            self._phase(PHASE_SUBMIT, time.perf_counter() - t0, seg)
        if self.selftrace is not None and seg:
            self.selftrace.flush_segment(seg)
        del parts, cols  # drop the worker's view refs: the rows stay
        # alive exactly as long as the PIPELINE holds them (the ticket
        # discipline the parked-scratch scavenge keys on)
        self._drain_scratch_corruption()
        with self._stats_lock:
            self.flushes += 1
            self.coalesced_requests += len(batch)
            self.flushed_spans += n_rows
            self.decode_errors += len(errors)
        # Tickets resolve AFTER submit_columns: a 200 means the rows
        # are enqueued (the serial path's contract), and error-lane
        # rows can never reorder past their own flush boundary.
        for i, (_payload, ticket) in enumerate(payload_jobs):
            if ticket is not None:
                ticket._resolve(errors.get(i))
        for _records, ticket in record_jobs:
            if ticket is not None:
                ticket._resolve(None)

    def _decode_native(self, payload_jobs, errors, seg, arena=None) -> list[SpanColumns]:
        payloads = [p for p, _t in payload_jobs]
        total = sum(len(p) for p in payloads)
        t0 = time.perf_counter()
        scratch = self._scratch.acquire(
            *native.scratch_dims(total, len(payloads))
        )
        parked = False
        native_phases: dict[str, float] = {}
        try:
            cols, payload_rows = native.decode_otlp_many(
                payloads, self.attr_keys, scratch,
                threads=self.native_threads,
                shard_min_bytes=self.shard_min_bytes,
                phases=native_phases,
            )
            for i, rows in enumerate(payload_rows):
                if rows < 0:
                    errors[i] = ValueError("malformed OTLP payload")
            # Phase sample BEFORE the empty-flush return: an all-
            # malformed flood burns real decode time and the
            # attribution must show it. scan/extract are the native
            # call's own two-pass split — sub-phases of the decode
            # envelope, never added into a share denominator
            # (TOP_PHASES).
            self._phase(PHASE_DECODE, time.perf_counter() - t0, seg)
            self._phase(PHASE_SCAN, native_phases.get("scan", 0.0), seg)
            self._phase(
                PHASE_EXTRACT, native_phases.get("extract", 0.0), seg
            )
            if not cols.duration_us.shape[0]:
                return []
            # Zero-copy hand-off (the ingest spine): the pipeline
            # receives VIEWS into the decode scratch — the per-flush
            # frame-buffer copy-out is gone. Integrity moves from
            # copy-then-verify to ticketed release: the decode views'
            # CRC manifest is taken NOW (frame.span_column_crcs, the
            # same native crc32c the frame trailer used), the scratch
            # is PARKED instead of released, and it re-enters the
            # freelist only once no pipeline view references it — at
            # which point the manifest is re-checked, so a buffer that
            # was scribbled while rows were live still surfaces as
            # anomaly_frame_corrupt_total{hop="ingest"} + quarantine
            # evidence (see ScratchPool). The recycled-early race the
            # old copy guarded against cannot happen: a still-
            # referenced scratch is simply never handed out again.
            t0 = time.perf_counter()
            crcs = frame.span_column_crcs(cols)
            self._phase(PHASE_VERIFY, time.perf_counter() - t0, seg)
            t0 = time.perf_counter()
            out = self.tensorizer.columns_from_columnar(
                cols, copy=False, arena=arena
            )
            self._phase(PHASE_TENSORIZE, time.perf_counter() - t0, seg)
            if cols.duration_us.base is scratch.duration:
                self._scratch.park(scratch, cols, crcs)
                parked = True
            # else: decode grew past the pooled scratch mid-call and
            # returned views into a bigger private buffer (or copies)
            # — plain GC owns that memory; OUR scratch saw no views
            # and goes straight back to the freelist.
            return [out]
        finally:
            if not parked:
                self._scratch.release(scratch)

    def _phase(self, name: str, dt: float, seg: dict | None = None) -> None:
        """Accumulate per-phase flush time (decode / verify /
        tensorize / submit) — how an operator attributes a flush's
        wall time between the native decoder, the integrity manifest,
        the intern/column pass and the pipeline merge. Also fans the
        sample out to the promoted histogram (``phase_observe``) and
        the caller's per-flush segment ledger (``seg``)."""
        with self._stats_lock:
            self.phase_s[name] += dt
        if seg is not None:
            seg[name] = seg.get(name, 0.0) + dt
        if self.phase_observe is not None:
            self.phase_observe(name, dt)

    def _drain_scratch_corruption(self) -> None:
        """Surface parked-scratch CRC mismatches (see ScratchPool):
        count anomaly_frame_corrupt_total{hop="ingest"} and write the
        frame-encoded rows aside as quarantine evidence. Detection is
        at recycle time — after consumption — so this is the audit
        trail for a lifecycle bug, not an admission gate. The COUNT
        comes from the monotone corrupt_total (evidence past the
        bounded deque still counts); the deque holds what forensics
        gets."""
        total = self._scratch.corrupt_total  # int read: GIL-atomic
        delta = total - self._scratch_corrupt_seen
        if delta > 0:
            self._scratch_corrupt_seen = total
            with self._stats_lock:
                self.frames_corrupt += delta
        while True:
            try:
                cols, _bad = self._scratch.corrupt.popleft()
            except IndexError:
                return
            try:
                frame.quarantine(frame.encode_spans(cols), "ingest")
            except Exception:  # noqa: BLE001 — forensics must never
                pass  # compound the fault (same rule as quarantine())

    def _decode_python(self, payload_jobs, errors, seg) -> list[SpanColumns]:
        """No-compiler fallback: per-request wire decode, still ONE
        coalesced tensorize pass per flush."""
        t0 = time.perf_counter()
        merged: list[SpanRecord] = []
        for i, (payload, _t) in enumerate(payload_jobs):
            try:
                merged.extend(decode_export_request(payload))
            except Exception as e:  # noqa: BLE001 — per-request verdict
                errors[i] = e
        self._phase(PHASE_DECODE, time.perf_counter() - t0, seg)
        if not merged:
            return []
        return [self.tensorizer.columns_from_records(merged)]

    # -- lifecycle / supervision --------------------------------------

    def alive(self) -> bool:
        """Supervisor probe: every worker thread is running."""
        return not self._stop and all(t.is_alive() for t in self._threads)

    def restart_workers(self) -> None:
        """Respawn dead workers (the supervisor's restart hook)."""
        if self._stop:
            return
        for i, t in enumerate(self._threads):
            if not t.is_alive():
                self._spawn(i)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted job has been processed."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def close(self) -> None:
        """Flush everything, then stop the workers."""
        self.drain()
        self._stop = True
        for _ in self._threads:
            self._q.put_unbounded(_STOP)
        for t in self._threads:
            t.join(timeout=5.0)

    # -- telemetry -----------------------------------------------------

    def stats(self) -> dict:
        """Point-in-time counters for the daemon's metrics scrape."""
        with self._stats_lock:
            wall = max(time.monotonic() - self._started, 1e-9)
            return {
                "depth": self._q.qsize(),
                "submitted": self.submitted,
                "flushes": self.flushes,
                "flushed_spans": self.flushed_spans,
                "coalesced_requests": self.coalesced_requests,
                "decode_errors": self.decode_errors,
                "worker_failures": self.worker_failures,
                "frames_corrupt": self.frames_corrupt,
                "busy_s": self.busy_s,
                "phase_s": dict(self.phase_s),
                "tickets_parked": self._scratch.tickets_parked,
                "tickets_recycled": self._scratch.tickets_recycled,
                "scratch_parked": self._scratch.parked(),
                "workers": self.workers,
                # Lifetime busy fraction; the daemon exports a windowed
                # delta-based gauge on top of busy_s/wall.
                "utilization": min(self.busy_s / (wall * self.workers), 1.0),
            }
