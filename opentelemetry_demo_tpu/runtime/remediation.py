"""Closed-loop auto-mitigation: the verified remediation controller.

The detector flags the 13 shop failure scenarios in 0.25–1.75 s
(BENCH_r05) — and then a human reads Grafana. This module closes the
loop through PAPER.md's two control seams: every shop service
evaluates its fault flags live from the flagd store (``utils/flags``),
and the pipeline's span stream is sampled by policy. The controller
subscribes to the pipeline's per-service anomaly verdicts (the same
flag reports the query plane serves) and drives two actuators behind
one interface:

- :class:`FlagdActuator` — flips per-scenario mitigation flags (e.g.
  disable ``recommendationCacheFailure``'s cache path, shed
  ``loadGeneratorFloodHomepage`` at the edge) through the flag store's
  ONE atomic write primitive (``flags.atomic_write_doc``; remote mode
  posts to the flag editor's ``/api/*`` surface with bounded timeouts).
  Mitigation = set the fault flag's ``state`` to ``DISABLED`` (every
  service evaluates fault flags with a falsy default, so a disabled
  flag IS the healthy path); revert restores the exact prior
  state/defaultVariant.
- :class:`SamplingActuator` — promotes a flagged service to keep-100%
  span capture (seeded with its flag-time exemplar trace ids from the
  PR 6 rings) while quiet services keep the configured head-sampling
  policy (``ANOMALY_HISTORY_SPANS``'s per-service map), publishing the
  merged policy through one callback.
- :class:`CollectorActuator` — steers a REAL collector: pushes a
  tail-sampling policy document (keep 100% of the flagged service,
  exemplar-seeded; head-sample quiet services at a base rate) to a
  policy file (atomic write + reloader sidecar) or an HTTP endpoint,
  with refcounted holds and exact-state revert; its keep ratio is the
  measured storage-reduction number.

When a counterfactual pre-flight verifier (``runtime.shadow``) is
wired via the ``preflight=`` hook, an act that passed every guardrail
below is NOT released immediately: the episode parks in
``STATE_PREFLIGHT`` while the worker replays the last minutes of
recorded history with the proposed mitigation applied — released to
ACTIVE only if the shadow's heads clear; refused otherwise (budget
token refunded, flag streak reset, ``preflight_refused`` flight
evidence + dump).

A control loop that can touch production flags must be unable to make
an outage worse. The guardrails, built like the PR 2 brownout ladder:

- **Hysteresis** — N consecutive flagged batches to act, M consecutive
  clean batches to verify recovery and revert. One noisy batch never
  flips a flag.
- **Token-bucket budget** — a flapping detector exhausts the bucket
  and the flags STAY PUT in their last state; refill bounds the
  sustained actuation rate.
- **Role/epoch gating** — only the PRIMARY actuates; a standby
  observes episodes without writing; a fenced daemon's actuator writes
  are refused by ``fence.check(path="remediation")`` — the FIFTH
  fenced write path, beside checkpoint/offsets/replication/history.
- **Verified recovery** — after acting, the controller watches its own
  detection heads: M clean batches within the deadline = VERIFIED
  (``anomaly_time_to_mitigate_seconds`` observed, act→recover interval
  recorded in the flight recorder, actuation reverted); deadline
  expiry = automatic rollback of the actuation plus a sticky
  DEGRADED-style ``MITIGATION_FAILED`` state and a flight evidence
  dump.
- **Hard fail-safety** — :meth:`RemediationController.observe` is the
  ONLY hot-path entry and does dictionary work under one lock, never
  I/O. Actuator writes run on a dedicated worker thread with bounded
  per-write timeouts and capped jittered retry (the ``otlp_export``
  sender discipline); the job queue is bounded (overflow = action
  dropped and counted, fail closed). A dead, slow, RST-ing or
  torn-writing flagd can cost queued actions — never an ingest stall,
  and never a turn of the pipeline's dispatch lock.

Knob registry: ``utils.config.REMEDIATION_KNOBS`` (enable defaults
OFF — auto-mitigation is strictly opt-in). Bench:
``runtime/mitigbench.py`` (``make mitigbench``) measures
time-to-mitigate beside time-to-detect per scenario, exercises the
rollback drill, and gates zero flag oscillation over a long clean run.
Chaos proofs: tests/test_remediation.py.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Iterable

from ..utils.flags import (
    FlagFileStore,
    atomic_write_doc,
    capped_jitter_backoff,
)
from .checkpoint import StaleEpochError

log = logging.getLogger(__name__)

# Episode states (per service). FAILED is the DEGRADED-analogue: the
# mitigation did not recover the system within the deadline; it was
# rolled back (when enabled) and the service is sticky-failed until a
# full clean streak passes. PREFLIGHT sits between PENDING and ACTIVE
# when a counterfactual verifier (runtime.shadow) is wired: the budget
# token is already taken, the actuator writes are NOT yet enqueued,
# and the shadow replay's verdict decides release (→ ACTIVE) or
# refusal (→ back to PENDING, token refunded, flight evidence).
STATE_IDLE = "idle"
STATE_PENDING = "pending"
STATE_PREFLIGHT = "preflight"
STATE_ACTIVE = "active"
STATE_FAILED = "mitigation_failed"

# Per-scenario mitigation map: detector service name → the flagd fault
# flags whose evaluating code paths that service owns. Disabling the
# flag disables the faulty path (cache, flood, GC pressure, …) because
# every service evaluates these with a falsy default — the reference's
# own mitigation seam. Deployments with different service names pass
# their own map; mitigbench builds one per scenario.
DEFAULT_FLAG_POLICY: dict[str, tuple[str, ...]] = {
    "payment": ("paymentFailure", "paymentUnreachable"),
    "cart": ("cartFailure",),
    "product-catalog": ("productCatalogFailure",),
    "ad": ("adFailure", "adHighCpu", "adManualGc"),
    "recommendation": ("recommendationCacheFailure",),
    "frontend": ("imageSlowLoad", "loadGeneratorFloodHomepage"),
    "checkout": ("kafkaQueueProblems",),
    "fraud-detection": ("kafkaQueueProblems",),
}

# Time-to-mitigate histogram ladder (seconds): TTD sits at 0.25–1.75 s,
# actuation + recovery verification adds hysteresis batches, so the
# interesting band runs ~1 s to ~2 min.
TTM_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 30.0, 60.0, 120.0)


class ActuationError(RuntimeError):
    """An actuator write failed after its transport retries."""


class FlagdActuator:
    """Mitigation-flag actuator over the flagd control seam.

    Two write paths, one policy: a local store (``FlagFileStore`` →
    ``atomic_write_doc`` on the shared file every service hot-reloads;
    plain ``FlagEvaluator`` → in-memory ``replace``) or a remote flag
    editor (``url`` mode: GET ``/api/read-file``, POST
    ``/api/write-to-file`` with bounded timeouts — the flagd-ui write
    surface the gateway mounts at ``/feature``). ``apply`` returns a
    revert token holding each touched flag's exact prior
    ``state``/``defaultVariant``; ``revert``/rollback restores it.
    """

    name = "flagd"

    def __init__(
        self,
        store=None,
        url: str = "",
        policy: dict[str, tuple[str, ...]] | None = None,
        timeout_s: float = 1.0,
    ):
        if store is None and not url:
            raise ValueError("FlagdActuator needs a store or a url")
        self.store = store
        self.url = url.rstrip("/") if url else ""
        self.policy = dict(policy if policy is not None else DEFAULT_FLAG_POLICY)
        self.timeout_s = float(timeout_s)
        self.writes = 0
        # Per-flag holds (refcounted): two services can map the same
        # fault flag (checkout and fraud-detection both own
        # kafkaQueueProblems), and the FIRST verified recovery must
        # not re-enable a flag another service's episode still relies
        # on — the flag re-enables only when the LAST hold releases,
        # restoring the prior recorded at first disable. Guarded by a
        # lock although the single worker thread is the only caller
        # today (the refcount must not silently break if a second
        # worker ever appears).
        self._holds_lock = threading.Lock()
        self._holds: dict[str, dict] = {}  # flag → {count, prior}

    # -- doc IO (each call bounded; retries live in the worker) --------

    def _read_doc(self) -> dict:
        if self.url:
            with urllib.request.urlopen(
                f"{self.url}/api/read-file", timeout=self.timeout_s
            ) as resp:
                return json.load(resp)
        return self.store.snapshot()

    def _write_doc(self, doc: dict) -> None:
        self.writes += 1
        if self.url:
            body = json.dumps({"data": doc}).encode()
            req = urllib.request.Request(
                f"{self.url}/api/write-to-file", data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                return
        if isinstance(self.store, FlagFileStore):
            atomic_write_doc(self.store.path, doc)
            self.store._maybe_reload(force=True)
        else:
            self.store.replace(doc)

    # -- actuation -----------------------------------------------------

    def apply(self, service: str):
        """Disable the service's fault flags; returns the revert token
        (the tuple of flag keys this service now HOLDS) or None when
        nothing was actuated (no mapped flags in the doc, or every
        mapped flag was operator-disabled already). A flag another
        episode already holds is joined (refcount++), not rewritten."""
        keys = self.policy.get(service, ())
        if not keys:
            return None
        doc = self._read_doc()
        flags = doc.get("flags", {})
        held: list[str] = []
        changed = False
        with self._holds_lock:
            for key in keys:
                spec = flags.get(key)
                if not isinstance(spec, dict):
                    continue
                hold = self._holds.get(key)
                if hold is not None:
                    # Another service's episode already disabled this
                    # flag: join the hold, write nothing.
                    hold["count"] += 1
                    held.append(key)
                    continue
                if str(spec.get("state", "ENABLED")).upper() == "DISABLED":
                    continue  # operator-disabled: not ours to manage
                self._holds[key] = {
                    "count": 1,
                    "prior": {
                        "state": spec.get("state", "ENABLED"),
                        "defaultVariant": spec.get("defaultVariant"),
                    },
                }
                spec["state"] = "DISABLED"
                held.append(key)
                changed = True
        if changed:
            try:
                self._write_doc(doc)
            except BaseException:
                # The write never landed: release the holds this call
                # minted so the worker's retry re-takes them cleanly.
                with self._holds_lock:
                    for key in held:
                        hold = self._holds.get(key)
                        if hold is None:
                            continue
                        hold["count"] -= 1
                        if hold["count"] <= 0:
                            del self._holds[key]
                raise
        return tuple(held) or None

    def revert(self, service: str, token) -> None:
        """Release this service's holds; each flag restores to its
        recorded prior state when (and only when) its LAST hold
        releases (rollback and verified-recovery revert share this)."""
        if not token:
            return
        with self._holds_lock:
            restore: dict[str, dict] = {}
            decremented: list[str] = []
            for key in token:
                hold = self._holds.get(key)
                if hold is None:
                    continue
                hold["count"] -= 1
                decremented.append(key)
                if hold["count"] <= 0:
                    restore[key] = hold["prior"]
        if not restore:
            return
        try:
            doc = self._read_doc()
            flags = doc.get("flags", {})
            changed = False
            for key, prior in restore.items():
                spec = flags.get(key)
                if not isinstance(spec, dict):
                    continue  # flag deleted since: nothing to restore
                spec["state"] = prior["state"]
                if prior["defaultVariant"] is not None:
                    spec["defaultVariant"] = prior["defaultVariant"]
                changed = True
            if changed:
                self._write_doc(doc)
            with self._holds_lock:
                for key in restore:
                    self._holds.pop(key, None)
        except BaseException:
            # The restore never landed: re-take the decrements so the
            # worker's retry releases them again (idempotent retry).
            with self._holds_lock:
                for key in decremented:
                    hold = self._holds.get(key)
                    if hold is not None:
                        hold["count"] += 1
            raise


class SamplingActuator:
    """Exemplar-guided sampling-policy actuator.

    Keeps the set of promoted (keep-100%) services and publishes the
    merged per-service policy — base head-sampling rates from
    ``ANOMALY_HISTORY_SPANS`` with every promoted service raised to
    1.0 — through one ``publish(policy, seeds)`` callback (the daemon
    wires it to the history writer's span-capture sampler; the same
    shape a collector tail-sampling push would take). ``seeds`` carries
    each promoted service's flag-time exemplar trace ids — the
    replay-corpus anchor linking the recorded drill to Jaeger traces.
    """

    name = "sampling"

    def __init__(
        self,
        publish: Callable[[dict[str, float], dict[str, list]], None],
        base_policy: dict[str, float] | None = None,
        exemplar_fn: Callable[[str], list] | None = None,
    ):
        self._publish = publish
        self.base_policy = dict(base_policy or {})
        self._exemplar_fn = exemplar_fn
        self._promoted: dict[str, list] = {}
        self._lock = threading.Lock()
        self.publishes = 0

    def policy(self) -> dict[str, float]:
        with self._lock:
            merged = dict(self.base_policy)
            for svc in self._promoted:
                merged[svc] = 1.0
            return merged

    def _push(self) -> None:
        with self._lock:
            merged = dict(self.base_policy)
            seeds = {}
            for svc, ex in self._promoted.items():
                merged[svc] = 1.0
                seeds[svc] = list(ex)
            self.publishes += 1
        self._publish(merged, seeds)

    def apply(self, service: str):
        exemplars = []
        if self._exemplar_fn is not None:
            try:
                exemplars = list(self._exemplar_fn(service) or [])
            except Exception:  # noqa: BLE001 — exemplar seeds are
                # best-effort garnish; a raced ring read must not fail
                # the sampling promotion itself.
                exemplars = []
        with self._lock:
            self._promoted[service] = exemplars
        self._push()
        return True

    def revert(self, service: str, token) -> None:
        with self._lock:
            self._promoted.pop(service, None)
        self._push()


_PRIOR_ABSENT = object()  # CollectorActuator: "no policy file existed"


class CollectorActuator:
    """Tail-sampling steering for a REAL collector — ROADMAP item 4's
    second leg, PAPER.md's sampling seam driven by the detector.

    When a service flags, this actuator renders a tail-sampling policy
    document that keeps 100% of the flagged service's traces
    (``string_attribute(service.name)`` ∧ ``always_sample``, seeded
    with its flag-time exemplar trace ids) while every quiet service
    head-samples at ``base_keep`` (probabilistic) — the
    ``deploy/otelcol-config-anomaly.yml`` tail-sampling block's shape,
    so an `otelcol` config reloader can merge it verbatim. Two
    transports, same policy: ``policy_path`` writes the rendered JSON
    through the flag plane's ONE atomic write primitive
    (``atomic_write_doc`` — this module is already inside the
    sanitycheck-pinned writer set; a file watcher/reloader sidecar
    picks it up), or ``url`` POSTs it with a bounded timeout (a torn
    or dead endpoint raises → the worker's capped jittered retry).

    Guardrails match :class:`FlagdActuator`: per-service refcounted
    holds (two episodes on one service join, not rewrite), exact-state
    revert (the pre-actuation file content — or its ABSENCE — is
    recorded at first hold and restored when the LAST hold releases),
    and every write runs behind the controller's epoch fence + token
    budget. ``keep_ratio()`` reports the policy-implied storage
    fraction (promoted·1.0 + quiet·base_keep over all services) — the
    ``anomaly_collector_keep_ratio`` gauge; mitigbench measures the
    row-level ratio on real replayed traffic beside it.
    """

    name = "collector"

    def __init__(
        self,
        policy_path: str = "",
        url: str = "",
        base_keep: float = 0.1,
        exemplar_fn: Callable[[str], list] | None = None,
        services_fn: Callable[[], list] | None = None,
        timeout_s: float = 1.0,
    ):
        if not policy_path and not url:
            raise ValueError(
                "CollectorActuator needs a policy_path or a url"
            )
        self.policy_path = policy_path
        self.url = url.rstrip("/") if url else ""
        self.base_keep = min(max(float(base_keep), 0.0), 1.0)
        self._exemplar_fn = exemplar_fn
        self._services_fn = services_fn
        self.timeout_s = float(timeout_s)
        self.writes = 0
        self._holds_lock = threading.Lock()
        self._holds: dict[str, dict] = {}  # svc → {count, exemplars}
        self._prior = _PRIOR_ABSENT  # captured at FIRST hold only

    # -- policy rendering ----------------------------------------------

    def render_policy(self) -> dict:
        """The merged policy doc for the CURRENT hold set (JSON — a
        strict YAML subset, so collector config tooling reads it
        as-is): one and(service-match, always_sample) tail policy per
        promoted service, one probabilistic baseline for everyone
        else, plus the exemplar seeds under a vendor block."""
        with self._holds_lock:
            promoted = {
                svc: list(h["exemplars"]) for svc, h in self._holds.items()
            }
        policies = [
            {
                "name": f"anomaly-keep-{svc}",
                "type": "and",
                "and": {"and_sub_policy": [
                    {
                        "name": f"svc-{svc}",
                        "type": "string_attribute",
                        "string_attribute": {
                            "key": "service.name", "values": [svc],
                        },
                    },
                    {"name": "always", "type": "always_sample"},
                ]},
            }
            for svc in sorted(promoted)
        ]
        policies.append({
            "name": "anomaly-baseline-head",
            "type": "probabilistic",
            "probabilistic": {
                "sampling_percentage": round(self.base_keep * 100.0, 4),
            },
        })
        return {
            "processors": {
                "tail_sampling/anomaly": {
                    "decision_wait": "2s",
                    "policies": policies,
                },
            },
            "anomaly": {
                "promoted": sorted(promoted),
                "base_keep": self.base_keep,
                "exemplar_seeds": promoted,
            },
        }

    def _push(self, doc: dict) -> None:
        self.writes += 1
        if self.url:
            body = json.dumps(doc).encode()
            req = urllib.request.Request(
                f"{self.url}/api/sampling-policy", data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                return
        atomic_write_doc(self.policy_path, doc)

    def _capture_prior_locked(self) -> None:
        """Record the pre-actuation policy file EXACTLY (or its
        absence) at the first hold — the revert target. An existing
        file this actuator cannot parse is refused (raise → retry →
        counted): never steer a collector whose config can't be
        restored byte-for-byte-equivalent."""
        if self._holds or not self.policy_path:
            return
        try:
            with open(self.policy_path, "r") as f:
                self._prior = json.load(f)
        except FileNotFoundError:
            self._prior = _PRIOR_ABSENT
        except (OSError, ValueError) as e:
            raise ActuationError(
                f"collector policy at {self.policy_path} is not "
                f"restorable: {e}"
            )

    # -- actuation -----------------------------------------------------

    def apply(self, service: str):
        exemplars = []
        if self._exemplar_fn is not None:
            try:
                exemplars = list(self._exemplar_fn(service) or [])
            except Exception:  # noqa: BLE001 — best-effort garnish,
                # same contract as SamplingActuator.
                exemplars = []
        with self._holds_lock:
            self._capture_prior_locked()
            hold = self._holds.get(service)
            if hold is not None:
                hold["count"] += 1
                return service  # joined: policy already keeps 100%
            self._holds[service] = {"count": 1, "exemplars": exemplars}
        try:
            self._push(self.render_policy())
        except BaseException:
            # The push never landed: release the hold this call minted
            # so the worker's retry re-takes it cleanly.
            with self._holds_lock:
                hold = self._holds.get(service)
                if hold is not None:
                    hold["count"] -= 1
                    if hold["count"] <= 0:
                        del self._holds[service]
            raise
        return service

    def revert(self, service: str, token) -> None:
        if not token:
            return
        with self._holds_lock:
            hold = self._holds.get(service)
            if hold is None:
                return
            hold["count"] -= 1
            if hold["count"] > 0:
                return  # another episode still holds this service
            del self._holds[service]
            last = not self._holds
            prior = self._prior
        try:
            if not last:
                # Other services still promoted: re-render without
                # this one.
                self._push(self.render_policy())
            elif self.url:
                self._push({"reset": True})
            elif prior is _PRIOR_ABSENT:
                # Exact-state revert: the file did not exist before the
                # first hold, so the LAST release removes it.
                self.writes += 1
                try:
                    os.remove(self.policy_path)
                except FileNotFoundError:
                    pass
            else:
                self.writes += 1
                atomic_write_doc(self.policy_path, prior)
        except BaseException:
            # The restore never landed: re-take the hold so the
            # worker's retry releases it again (idempotent retry).
            with self._holds_lock:
                re = self._holds.setdefault(
                    service, {"count": 0, "exemplars": []}
                )
                re["count"] += 1
            raise
        if last:
            with self._holds_lock:
                if not self._holds:
                    self._prior = _PRIOR_ABSENT

    def keep_ratio(self) -> float:
        """Policy-implied storage fraction over the known service set
        (1.0 per promoted, ``base_keep`` per quiet) — what the current
        policy would keep of a uniform stream; the exported gauge."""
        services = list(self._services_fn() or []) if (
            self._services_fn is not None
        ) else []
        with self._holds_lock:
            promoted = set(self._holds)
        universe = set(services) | promoted
        if not universe:
            return self.base_keep
        kept = sum(
            1.0 if svc in promoted else self.base_keep for svc in universe
        )
        return kept / len(universe)


class TokenBucket:
    """Actuation budget: ``capacity`` burst, one token per
    ``refill_s`` observed-timebase seconds sustained."""

    def __init__(self, capacity: int, refill_s: float):
        self.capacity = max(int(capacity), 1)
        self.refill_s = float(refill_s)
        self.tokens = float(self.capacity)
        self._t: float | None = None

    def advance(self, t: float) -> None:
        if self._t is not None and t > self._t:
            self.tokens = min(
                self.tokens + (t - self._t) / self.refill_s,
                float(self.capacity),
            )
        if self._t is None or t > self._t:
            self._t = t

    def take(self) -> bool:
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class RemediationController:
    """The supervised control loop (module docstring for the contract).

    ``observe(t, flagged)`` is the hot-path entry (harvester/pump
    thread): per-service streak bookkeeping under one lock, never I/O.
    ``tick(t)`` (pump cadence) advances deadlines/budget when no
    reports arrive. Actuator writes run on the worker thread with
    fencing, bounded timeouts and capped jittered retry.
    """

    def __init__(
        self,
        actuators: Iterable,
        enabled: bool = False,
        act_batches: int = 3,
        clear_batches: int = 8,
        budget: int = 4,
        budget_refill_s: float = 60.0,
        deadline_s: float = 30.0,
        rollback: bool = True,
        role_fn: Callable[[], str] | None = None,
        fence=None,
        flight=None,
        queue_max: int = 64,
        retry_attempts: int = 4,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        preflight: Callable[[str], object] | None = None,
        bundle_fn: Callable[[str], str | None] | None = None,
    ):
        self.actuators = list(actuators)
        self.enabled = bool(enabled)
        self.act_batches = max(int(act_batches), 1)
        self.clear_batches = max(int(clear_batches), 1)
        self.deadline_s = float(deadline_s)
        self.rollback = bool(rollback)
        self._role_fn = role_fn
        self._fence = fence
        self._flight = flight
        # Counterfactual pre-flight gate (runtime.shadow): called with
        # the service name ON THE WORKER THREAD (it replays minutes of
        # recorded frames — never the hot path), returning an object
        # with ``would_help``/``reason`` (PreflightVerdict) or a bare
        # bool. None = no gate: act immediately (the PR 13 behavior).
        self._preflight = preflight
        # Provenance citation hook (runtime.provenance via the daemon):
        # newest evidence-bundle id for a service, stamped into the
        # act/pre-flight flight records so every mitigation names the
        # verdict it answers. None = records carry no citation.
        self._bundle_fn = bundle_fn
        self.bucket = TokenBucket(budget, budget_refill_s)
        self._retry_attempts = max(int(retry_attempts), 1)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_cap_s = float(backoff_cap_s)

        self._lock = threading.Lock()
        self._episodes: dict[str, dict] = {}
        # Applied revert tokens, (service, actuator name) → token;
        # written by the worker, read by revert/rollback jobs.
        self._applied: dict[tuple[str, str], object] = {}
        self._jobs: deque = deque()
        self._queue_max = max(int(queue_max), 1)
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop_event = threading.Event()
        self._worker: threading.Thread | None = None
        self._closed = False

        # Counters (exported by the daemon as deltas).
        self.actions_total: dict[str, int] = {}      # by actuator
        self.rollbacks_total = 0
        self.verified_total = 0
        self.failed_total = 0
        self.refused_role = 0
        self.refused_fenced = 0
        self.budget_exhausted = 0
        self.actuator_errors = 0
        self.queue_dropped = 0
        self._ttm_samples: list[tuple[float, float]] = []  # (ttm, act→recover)
        # Pre-flight bookkeeping (daemon-exported as deltas):
        # verdicts by direction, refusals by reason, act→verdict
        # wall intervals.
        self.preflight_verdicts: dict[str, int] = {}
        self.preflight_refused: dict[str, int] = {}
        self._preflight_samples: list[float] = []
        self._t_now = 0.0  # last observed timebase (release stamps t_act)

    # -- hot path ------------------------------------------------------

    def observe(
        self, t_now: float, flagged: Iterable[str],
        services: Iterable[str] | None = None,
    ) -> None:
        """One flag report (hot path: dict work under the lock only).

        ``flagged`` is the report's per-service verdict list;
        ``services`` optionally names every service the report covered
        (defaults to flagged ∪ services with open episodes — enough,
        since a clean streak only matters once an episode exists).
        """
        flagged_set = set(flagged)
        with self._lock:
            self._t_now = t_now
            self.bucket.advance(t_now)
            universe = set(self._episodes) | flagged_set
            if services is not None:
                universe |= set(services)
            for svc in universe:
                ep = self._episodes.get(svc)
                if svc in flagged_set:
                    if ep is None:
                        ep = self._episodes[svc] = {
                            "state": STATE_IDLE, "flag_streak": 0,
                            "clean_streak": 0, "t_first_flag": t_now,
                            "t_act": None, "t_first_clean": None,
                            "noted": set(),
                        }
                    if ep["flag_streak"] == 0:
                        ep["t_first_flag"] = (
                            t_now if ep["state"] in (STATE_IDLE,)
                            else ep["t_first_flag"]
                        )
                    ep["flag_streak"] += 1
                    ep["clean_streak"] = 0
                    if ep["state"] == STATE_IDLE:
                        ep["state"] = STATE_PENDING
                    if (
                        ep["state"] == STATE_PENDING
                        and ep["flag_streak"] >= self.act_batches
                    ):
                        self._maybe_act_locked(svc, ep, t_now)
                elif ep is not None:
                    ep["flag_streak"] = 0
                    ep["clean_streak"] += 1
                    if ep["state"] == STATE_ACTIVE:
                        if ep["clean_streak"] == 1:
                            ep["t_first_clean"] = t_now
                        if ep["clean_streak"] >= self.clear_batches:
                            self._verify_locked(svc, ep, t_now)
                    elif ep["clean_streak"] >= self.clear_batches:
                        # PENDING that never acted, or sticky FAILED:
                        # a full clean streak closes the episode. A
                        # PREFLIGHT episode closing this way (the
                        # incident cleared on its own while the shadow
                        # replay ran) refunds the token the act
                        # decision took — the in-flight verdict finds
                        # the episode gone and is discarded.
                        if ep["state"] == STATE_PREFLIGHT:
                            self.bucket.tokens = min(
                                self.bucket.tokens + 1.0,
                                float(self.bucket.capacity),
                            )
                        del self._episodes[svc]
            expired = self._deadline_scan_locked(t_now)
        self._dump_expired(expired)
        self._wake.set()

    def tick(self, t_now: float) -> None:
        """Deadline/budget housekeeping when no reports arrive (pump
        cadence; observed timebase, same clock as observe)."""
        with self._lock:
            self._t_now = t_now
            self.bucket.advance(t_now)
            expired = self._deadline_scan_locked(t_now)
        self._dump_expired(expired)
        self._wake.set()

    # -- locked transitions --------------------------------------------

    def _record(self, kind_detail: dict) -> None:
        if self._flight is not None:
            self._flight.record("mitigation", **kind_detail)

    def _maybe_act_locked(self, svc: str, ep: dict, t_now: float) -> None:
        if not self.enabled:
            if "observe_only" not in ep["noted"]:
                ep["noted"].add("observe_only")
                self._record({
                    "op": "observe_only", "service": svc,
                    "streak": ep["flag_streak"],
                })
            return
        role = self._role_fn() if self._role_fn is not None else "primary"
        if role != "primary":
            if "refused_role" not in ep["noted"]:
                ep["noted"].add("refused_role")
                self.refused_role += 1
                self._record({
                    "op": "refused", "service": svc, "role": role,
                })
            return
        if not self.bucket.take():
            self.budget_exhausted += 1
            if "budget" not in ep["noted"]:
                ep["noted"].add("budget")
                self._record({
                    "op": "budget_exhausted", "service": svc,
                    "tokens": self.bucket.tokens,
                })
            return
        if (
            self._closed
            or len(self._jobs) + len(self.actuators) > self._queue_max
        ):
            # The worker queue cannot take every apply job (a wedged
            # actuator backed it up): do NOT act half-way — refund the
            # token, count the refusal, stay PENDING and retry on a
            # later batch. Counting an action whose write never even
            # enqueued would lie to the metrics AND to the episode
            # state machine (its deadline would later "roll back" a
            # no-op).
            self.bucket.tokens = min(
                self.bucket.tokens + 1.0, float(self.bucket.capacity)
            )
            self.queue_dropped += len(self.actuators)
            if "queue_full" not in ep["noted"]:
                ep["noted"].add("queue_full")
                self._record({
                    "op": "queue_full", "service": svc,
                    "depth": len(self._jobs),
                })
            return
        ep["noted"].discard("budget")
        # Evidence citation: stamp the episode with the newest bundle
        # id for this service ONCE, at escalation — the id every
        # downstream record (act, preflight park/refusal) carries.
        ep["bundle"] = self._cite(svc)
        if self._preflight is not None:
            # Counterfactual gate: hold the token, park the episode in
            # PREFLIGHT, and let the worker replay recorded history
            # with the proposed mitigation applied before ANY actuator
            # write is even enqueued. The wall stamp starts the
            # act→verdict interval (``anomaly_preflight_seconds``).
            ep["state"] = STATE_PREFLIGHT
            ep["t_act"] = None
            ep["w_preflight"] = time.monotonic()
            self._enqueue_locked(("preflight", None, svc))
            self._record({
                "op": "preflight", "service": svc, "t": t_now,
                "streak": ep["flag_streak"],
                "bundle": ep.get("bundle"),
            })
            return
        self._act_locked(svc, ep, t_now)

    def _cite(self, svc: str) -> str | None:
        """Newest evidence-bundle id for ``svc`` via the daemon hook
        (pipeline query lock only — cheap dict copy, no I/O; a hook
        failure costs the citation, never the episode)."""
        if self._bundle_fn is None:
            return None
        try:
            return self._bundle_fn(svc)
        except Exception:  # noqa: BLE001 — citation is best-effort
            return None

    def _act_locked(self, svc: str, ep: dict, t_now: float) -> None:
        """Release the act: enqueue every actuator's apply (directly
        from hysteresis when no pre-flight gate is wired; from the
        worker's released verdict otherwise)."""
        ep["state"] = STATE_ACTIVE
        ep["t_act"] = t_now
        ep["applied"] = 0       # actuator applies that LANDED
        ep["apply_failed"] = 0  # applies that exhausted their retries
        for act in self.actuators:
            # actions_total counts on worker SUCCESS (not here): an
            # apply that fails every retry must not mint a phantom
            # action for the dashboards/bench to report.
            self._enqueue_locked(("apply", act, svc))
        self._record({
            "op": "act", "service": svc, "t": t_now,
            "streak": ep["flag_streak"],
            "actuators": [a.name for a in self.actuators],
            "tokens_left": self.bucket.tokens,
            "bundle": ep.get("bundle"),
        })

    def _verify_locked(self, svc: str, ep: dict, t_now: float) -> None:
        ttm = float(ep["t_first_clean"] - ep["t_first_flag"])
        act_to_recover = float(ep["t_first_clean"] - (ep["t_act"] or t_now))
        self.verified_total += 1
        self._ttm_samples.append((ttm, act_to_recover))
        for act in self.actuators:
            self._enqueue_locked(("revert", act, svc))
        self._record({
            "op": "verified", "service": svc,
            "time_to_mitigate_s": round(ttm, 3),
            "act_to_recover_s": round(act_to_recover, 3),
            "clean_batches": ep["clean_streak"],
        })
        del self._episodes[svc]

    def _deadline_scan_locked(self, t_now: float) -> list[tuple[str, bool]]:
        """Expire missed-deadline episodes; returns the (service,
        rolled_back) list for the CALLER to dump evidence on — the
        dump is file I/O and must happen outside the controller lock
        (observe()'s no-I/O contract)."""
        expired: list[tuple[str, bool]] = []
        fenced = self._fence is not None and self._fence.stale()
        for svc, ep in list(self._episodes.items()):
            if ep["state"] != STATE_ACTIVE or ep["t_act"] is None:
                continue
            if t_now - ep["t_act"] <= self.deadline_s:
                continue
            # No verified recovery inside the deadline: the mitigation
            # did not work. Roll it back (unless configured sticky) and
            # park the service in the DEGRADED-style FAILED state.
            self.failed_total += 1
            ep["state"] = STATE_FAILED
            ep["clean_streak"] = 0
            rolling = self.rollback and not fenced
            if rolling:
                self.rollbacks_total += 1
                for act in self.actuators:
                    self._enqueue_locked(("revert", act, svc))
            op = "rollback" if self.rollback else "failed_sticky"
            if self.rollback and fenced:
                # A fenced daemon CANNOT restore the flag — every
                # actuator write is fence-refused, and pretending a
                # rollback happened would lie to the metrics. The
                # successor primary owns the store now (and will act
                # on its own verdicts if the incident persists); this
                # daemon records the refusal honestly.
                op = "rollback_refused_fenced"
            self._record({
                "op": op, "service": svc,
                "deadline_s": self.deadline_s,
                "acted_at": ep["t_act"], "t": t_now,
            })
            expired.append((svc, rolling))
        return expired

    def _dump_expired(self, expired: list[tuple[str, bool]]) -> None:
        """Evidence dumps for deadline expiries (outside the lock:
        FlightRecorder.dump writes a file, and a slow disk must stall
        neither observe() nor any thread waiting on the controller)."""
        if self._flight is None:
            return
        for svc, rolled_back in expired:
            self._flight.dump(
                "mitigation-failed", service=svc,
                rolled_back=rolled_back,
            )

    def _enqueue_locked(self, job: tuple) -> None:
        if self._closed:
            self.queue_dropped += 1
            return
        if len(self._jobs) >= self._queue_max:
            # Fail closed: the action is dropped and counted — a wedged
            # flagd must cost actions, never memory or the hot path.
            self.queue_dropped += 1
            return
        self._jobs.append(job)
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._work_loop, name="remediation-worker",
                daemon=True,
            )
            self._worker.start()

    # -- worker --------------------------------------------------------

    def _retry_delay(self, attempt: int) -> float:
        return capped_jitter_backoff(
            attempt, self._backoff_base_s, self._backoff_cap_s
        )

    def _work_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._jobs:
                        self._idle.set()
                        if self._closed:
                            return
                        break
                    self._idle.clear()
                    op, act, svc = self._jobs.popleft()
                self._run_job(op, act, svc)

    def _run_job(self, op: str, act, svc: str) -> None:
        for attempt in range(self._retry_attempts):
            try:
                if self._fence is not None:
                    # The fifth fenced write path: a superseded daemon
                    # must not touch production flags, not even to
                    # revert — the new primary owns the loop now.
                    self._fence.check(path="remediation")
                if op == "preflight":
                    # Past the fence check: a fenced daemon never even
                    # replays (the StaleEpochError branch below refunds
                    # and parks the episode). The verdict path handles
                    # its own failures fail-closed — no retry loop.
                    self._finish_preflight(svc)
                    return
                if op == "apply":
                    token = act.apply(svc)
                    with self._lock:
                        if token is not None:
                            self._applied[(svc, act.name)] = token
                        self.actions_total[act.name] = (
                            self.actions_total.get(act.name, 0) + 1
                        )
                        ep = self._episodes.get(svc)
                        if ep is not None and "applied" in ep:
                            ep["applied"] += 1
                else:
                    # Read WITHOUT popping: a transient revert failure
                    # must keep the token for the retry — popping
                    # first would turn the retry into a silent no-op
                    # and leave the mitigation in place forever.
                    with self._lock:
                        token = self._applied.get((svc, act.name))
                    act.revert(svc, token)
                    with self._lock:
                        self._applied.pop((svc, act.name), None)
                return
            except StaleEpochError:
                with self._lock:
                    self.refused_fenced += 1
                    if op == "preflight":
                        # The fenced daemon's act decision is void:
                        # refund the token and park the episode back
                        # in PENDING (the successor primary owns the
                        # loop — it will run its OWN pre-flight).
                        ep = self._episodes.get(svc)
                        if (
                            ep is not None
                            and ep.get("state") == STATE_PREFLIGHT
                        ):
                            self.bucket.tokens = min(
                                self.bucket.tokens + 1.0,
                                float(self.bucket.capacity),
                            )
                            ep["state"] = STATE_PENDING
                            ep["t_act"] = None
                self._record({
                    "op": "fenced", "service": svc,
                    "actuator": act.name if act is not None else "preflight",
                })
                return
            except Exception:  # noqa: BLE001 — actuator transport
                # faults (dead/slow/RST flagd, torn endpoint) are the
                # chaos this worker exists to absorb: capped jittered
                # retry, then count + log, never a dead worker thread.
                if attempt + 1 >= self._retry_attempts:
                    with self._lock:
                        self.actuator_errors += 1
                        if op == "apply":
                            ep = self._episodes.get(svc)
                            if (
                                ep is not None
                                and ep.get("state") == STATE_ACTIVE
                                and "apply_failed" in ep
                            ):
                                ep["apply_failed"] += 1
                                if (
                                    ep["apply_failed"]
                                    >= len(self.actuators)
                                    and ep.get("applied", 0) == 0
                                ):
                                    # EVERY actuator's apply died:
                                    # nothing was actuated. Refund
                                    # the budget token and fall back
                                    # to PENDING — no phantom action,
                                    # no phantom rollback later, and
                                    # the episode may retry acting on
                                    # a later flagged batch.
                                    self.bucket.tokens = min(
                                        self.bucket.tokens + 1.0,
                                        float(self.bucket.capacity),
                                    )
                                    ep["state"] = STATE_PENDING
                                    ep["t_act"] = None
                    self._record({
                        "op": "actuator_error", "service": svc,
                        "actuator": act.name, "job": op,
                        "attempts": attempt + 1,
                    })
                    log.exception(
                        "remediation %s via %s for %s failed after %d "
                        "attempts", op, act.name, svc, attempt + 1,
                    )
                    return
                if self._stop_event.wait(self._retry_delay(attempt)):
                    return  # closing: abandon the backoff sleep

    def _finish_preflight(self, svc: str) -> None:
        """Worker-side verdict: run the counterfactual replay (outside
        the controller lock — it decodes minutes of frames), then
        release the act or refuse it. Fail closed: a verifier that
        raised has proven nothing, so the act is refused."""
        with self._lock:
            ep = self._episodes.get(svc)
            if ep is None or ep.get("state") != STATE_PREFLIGHT:
                return  # episode closed while queued: token already refunded
            w0 = ep.get("w_preflight") or time.monotonic()
        try:
            verdict = self._preflight(svc)
        except Exception as e:  # noqa: BLE001 — any verifier fault
            # refuses the act; the evidence names the exception.
            verdict = None
            error = f"{type(e).__name__}: {e}"
        else:
            error = None
        verdict_s = time.monotonic() - w0
        would_help = bool(getattr(verdict, "would_help", verdict))
        reason = str(
            getattr(verdict, "reason", "cleared" if would_help else "refused")
        )
        if error is not None:
            reason = "error"
        detail = {
            k: getattr(verdict, k)
            for k in (
                "batches", "records", "corrupt", "virtual_s", "wall_s",
                "speedup", "flagged_tail", "clear_tail",
            )
            if hasattr(verdict, k)
        }
        refused_dump = False
        bundle = None
        with self._lock:
            ep = self._episodes.get(svc)
            stale = ep is None or ep.get("state") != STATE_PREFLIGHT
            if ep is not None:
                bundle = ep.get("bundle")
            if not stale:
                self._preflight_samples.append(verdict_s)
                if would_help:
                    self.preflight_verdicts["released"] = (
                        self.preflight_verdicts.get("released", 0) + 1
                    )
                    self._act_locked(svc, ep, self._t_now)
                else:
                    # Refusal: the mitigation would NOT have helped.
                    # Refund the token, reset the streak (a fresh
                    # act_batches run of flagged reports is needed
                    # before the next attempt), stay PENDING.
                    self.preflight_verdicts["refused"] = (
                        self.preflight_verdicts.get("refused", 0) + 1
                    )
                    self.preflight_refused[reason] = (
                        self.preflight_refused.get(reason, 0) + 1
                    )
                    self.bucket.tokens = min(
                        self.bucket.tokens + 1.0,
                        float(self.bucket.capacity),
                    )
                    ep["state"] = STATE_PENDING
                    ep["t_act"] = None
                    ep["flag_streak"] = 0
                    refused_dump = True
        if stale:
            return
        if would_help:
            self._record({
                "op": "preflight_released", "service": svc,
                "verdict_s": round(verdict_s, 4), **detail,
            })
            return
        # Evidence OUTSIDE the lock (dump writes a file).
        if self._flight is not None:
            self._flight.record(
                "preflight_refused", service=svc, reason=reason,
                verdict_s=round(verdict_s, 4), bundle=bundle,
                **({"error": error} if error else {}), **detail,
            )
        if refused_dump and self._flight is not None:
            # NB: ``reason`` is dump()'s positional parameter (the
            # file-name stem) — the verdict's reason rides as
            # ``refusal_reason`` context.
            self._flight.dump(
                "preflight-refused", service=svc, refusal_reason=reason,
                verdict_s=round(verdict_s, 4), bundle=bundle, **detail,
            )

    # -- surface -------------------------------------------------------

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Wait for the worker queue to empty (tests/bench only) —
        the BackgroundPoster.flush discipline: queue empty AND the
        worker idle, polled, so a just-enqueued job can't hide behind
        a stale idle flag."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                empty = not self._jobs and self._worker is None
            if empty or (self._idle.is_set() and self.queue_depth() == 0):
                return True
            self._wake.set()
            time.sleep(0.002)
        return False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._jobs)

    def active_count(self) -> int:
        with self._lock:
            return sum(
                1 for ep in self._episodes.values()
                if ep["state"] in (STATE_ACTIVE, STATE_FAILED)
            )

    def state_of(self, service: str) -> str:
        with self._lock:
            ep = self._episodes.get(service)
            return ep["state"] if ep is not None else STATE_IDLE

    def failed_services(self) -> list[str]:
        with self._lock:
            return sorted(
                svc for svc, ep in self._episodes.items()
                if ep["state"] == STATE_FAILED
            )

    def take_ttm_samples(self) -> list[tuple[float, float]]:
        """Drain (ttm_s, act_to_recover_s) pairs accumulated since the
        last call — the daemon turns them into histogram observations."""
        with self._lock:
            samples, self._ttm_samples = self._ttm_samples, []
            return samples

    def take_preflight_samples(self) -> list[float]:
        """Drain act→verdict wall intervals accumulated since the last
        call (``anomaly_preflight_seconds`` observations)."""
        with self._lock:
            samples, self._preflight_samples = self._preflight_samples, []
            return samples

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "actions": dict(self.actions_total),
                "rollbacks": self.rollbacks_total,
                "verified": self.verified_total,
                "failed": self.failed_total,
                "refused_role": self.refused_role,
                "refused_fenced": self.refused_fenced,
                "budget_exhausted": self.budget_exhausted,
                "actuator_errors": self.actuator_errors,
                "queue_dropped": self.queue_dropped,
                "queue_depth": len(self._jobs),
                "preflight_verdicts": dict(self.preflight_verdicts),
                "preflight_refused": dict(self.preflight_refused),
                "tokens": round(self.bucket.tokens, 3),
                "active": sum(
                    1 for ep in self._episodes.values()
                    if ep["state"] in (STATE_ACTIVE, STATE_FAILED)
                ),
                "states": {
                    svc: ep["state"]
                    for svc, ep in self._episodes.items()
                },
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            worker = self._worker
        self._stop_event.set()
        self._wake.set()
        if worker is not None:
            worker.join(timeout=3.0)
