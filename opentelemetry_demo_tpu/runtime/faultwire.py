"""Fault-injecting TCP proxy: the chaos harness's wire layer.

The reference injects every failure through flagd flags — network
misbehaviour included (``kafkaQueueProblems`` starves the consumer from
inside the broker path). This proxy injects the failures a *flag
cannot*: the transport faults between the detector and its
dependencies. Park it between the daemon and the in-repo Kafka broker
(or an OTLP receiver) and it can, per the chaos plan:

- **delay** every forwarded chunk (``delay_s``) — congested link;
- **truncate mid-frame** (``truncate_after`` bytes client→upstream,
  then a hard RST) — a peer dying mid-protocol-frame, the case length-
  prefixed protocols like Kafka's are most sensitive to;
- **RST new connections** (``rst_connects``) — a listener that accepts
  and immediately resets, the half-crashed-broker shape;
- **blackhole** (``blackhole``) — accept and read but forward nothing:
  the half-open connection that pins naive clients forever;
- **corrupt** (``corrupt_rate`` / ``corrupt_seed`` /
  ``corrupt_offset``) — deterministic seeded BIT FLIPS in forwarded
  bytes: each absolute per-direction stream offset ≥ ``corrupt_offset``
  flips one bit with probability ``corrupt_rate``, chosen by a
  splitmix64 hash of (seed, offset) so the same plan replays exactly
  regardless of TCP chunk boundaries. This is the silent-corruption
  shape checksums exist for — a NIC/switch/DMA flipping bits that TCP's
  16-bit checksum misses — and the chaos proof that a flipped byte on
  the replication link is caught at the frame boundary
  (``runtime.frame``), quarantined and survived, never merged.
  :func:`corrupt_bytes` exposes the same deterministic flip plan for
  at-rest corruption (chaos tests flip checkpoint FILES with it);
- **kill live connections** (:meth:`kill_connections`) — RST both
  sides of every in-flight session, the broker-restart shape.

Faults are plain attributes, togglable at runtime (tests flip them
mid-stream), and env-seedable in the spirit of the reference's
flag-driven failures: ``FAULTWIRE_DELAY_MS``,
``FAULTWIRE_TRUNCATE_AFTER``, ``FAULTWIRE_RST=1``,
``FAULTWIRE_BLACKHOLE=1``, ``FAULTWIRE_CORRUPT_RATE`` (flip
probability per byte), ``FAULTWIRE_CORRUPT_SEED``,
``FAULTWIRE_CORRUPT_OFFSET`` (spare the first N bytes of each
direction — e.g. let a handshake through clean).

This is a test/chaos tool with a real socket surface — the daemon under
test cannot tell it from a misbehaving network, which is the point.
"""

from __future__ import annotations

import os
import socket
import struct
import threading


def _splitmix64(x: int) -> int:
    """Scalar splitmix64 (ops.hashing's generator, stdlib-only here):
    the per-offset corruption coin — hash quality matters because the
    flip plan must look like random line noise, not a pattern a
    checksum could be accidentally blind to."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def corrupt_bytes(
    data: bytes,
    seed: int,
    rate: float,
    start: int = 0,
    offset: int = 0,
) -> tuple[bytes, int]:
    """Deterministic seeded bit-flip plan → (mutated bytes, n_flipped).

    Byte at absolute stream position ``start + i`` flips one bit iff
    ``splitmix64(seed, position)`` lands under ``rate`` — and only at
    positions ≥ ``offset``. Deterministic in (seed, position) alone, so
    the same corruption replays identically across chunk boundaries,
    reconnects and runs; the flipped BIT index comes from the same
    hash. Used by the proxy's live corrupt mode and directly by chaos
    tests for at-rest (checkpoint file) corruption.
    """
    if rate <= 0 or not data:
        return data, 0
    threshold = int(rate * (1 << 32))
    out = None
    flipped = 0
    for i in range(len(data)):
        pos = start + i
        if pos < offset:
            continue
        h = _splitmix64((seed << 1) ^ (pos * 0x9E3779B97F4A7C15 + 1))
        if (h & 0xFFFFFFFF) < threshold:
            if out is None:
                out = bytearray(data)
            out[i] ^= 1 << ((h >> 32) & 7)
            flipped += 1
    return (bytes(out) if out is not None else data), flipped


def _rst_close(sock: socket.socket) -> None:
    """Close with SO_LINGER(1, 0): the kernel sends RST, not FIN — the
    abortive teardown a crashed process produces."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class FaultWire:
    """TCP fault proxy: listen on ``host:port``, forward to upstream."""

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.upstream = (upstream_host, upstream_port)
        # Fault plan (mutable at runtime; env-seeded like a fault flag).
        self.delay_s = float(os.environ.get("FAULTWIRE_DELAY_MS", "0")) / 1e3
        trunc = os.environ.get("FAULTWIRE_TRUNCATE_AFTER", "")
        self.truncate_after: int | None = int(trunc) if trunc else None
        self.rst_connects = os.environ.get("FAULTWIRE_RST", "") == "1"
        self.blackhole = os.environ.get("FAULTWIRE_BLACKHOLE", "") == "1"
        # Corrupt mode: deterministic seeded bit flips (see module doc
        # and corrupt_bytes). rate = per-byte flip probability; offset
        # spares each direction's first N stream bytes; positions are
        # per-connection per-direction, so the plan is reproducible.
        self.corrupt_rate = float(
            os.environ.get("FAULTWIRE_CORRUPT_RATE", "0")
        )
        self.corrupt_seed = int(os.environ.get("FAULTWIRE_CORRUPT_SEED", "0"))
        self.corrupt_offset = int(
            os.environ.get("FAULTWIRE_CORRUPT_OFFSET", "0")
        )
        # Stats (observability for assertions and operators).
        self.conns_total = 0
        self.conns_killed = 0
        self.bytes_forwarded = 0
        self.bytes_corrupted = 0
        self._lock = threading.Lock()
        self._pairs: list[tuple[socket.socket, socket.socket]] = []
        self._stop = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="faultwire-accept", daemon=True
        )

    # -- control --------------------------------------------------------

    def clear(self) -> None:
        """Drop every fault back to clean forwarding."""
        self.delay_s = 0.0
        self.truncate_after = None
        self.rst_connects = False
        self.blackhole = False
        self.corrupt_rate = 0.0

    def kill_connections(self) -> None:
        """RST both legs of every live session (broker-restart shape)."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
            self.conns_killed += len(pairs)
        for client, up in pairs:
            _rst_close(client)
            _rst_close(up)

    def start(self) -> None:
        self._acceptor.start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._acceptor.join(timeout=2.0)
        self.kill_connections()

    # -- data path ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                client, _addr = self._sock.accept()
            except OSError:
                return
            self.conns_total += 1
            if self.rst_connects:
                _rst_close(client)
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                # Upstream down: the client sees exactly what it would
                # against the dead upstream — a refused/reset connect.
                _rst_close(client)
                continue
            with self._lock:
                self._pairs.append((client, up))
            # Budget shared across both pump directions so "truncate
            # after N bytes" means N bytes of *protocol*, whichever
            # side is mid-frame when it runs out.
            budget = (
                [self.truncate_after]
                if self.truncate_after is not None else None
            )
            for src, dst, c2u in ((client, up, True), (up, client, False)):
                threading.Thread(
                    target=self._pump, args=(src, dst, c2u, client, up, budget),
                    name="faultwire-pump", daemon=True,
                ).start()

    def _pump(self, src, dst, c2u, client, up, budget) -> None:
        import time as _time

        # Per-direction absolute stream position for the corrupt mode's
        # deterministic flip plan (independent of TCP chunking).
        pos = 0
        try:
            while not self._stop:
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                if self.blackhole and c2u:
                    continue  # swallow the request; never answer
                if self.delay_s > 0:
                    _time.sleep(self.delay_s)
                if self.corrupt_rate > 0:
                    # Salt the seed by direction so the two pumps of
                    # one session don't flip mirrored positions.
                    chunk, flipped = corrupt_bytes(
                        chunk,
                        seed=self.corrupt_seed * 2 + (1 if c2u else 0),
                        rate=self.corrupt_rate,
                        start=pos,
                        offset=self.corrupt_offset,
                    )
                    self.bytes_corrupted += flipped
                pos += len(chunk)
                if budget is not None:
                    with self._lock:
                        take = max(min(budget[0], len(chunk)), 0)
                        budget[0] -= take
                        spent = budget[0] <= 0
                    chunk = chunk[:take]
                    if chunk:
                        try:
                            dst.sendall(chunk)
                        except OSError:
                            break
                        self.bytes_forwarded += len(chunk)
                    if spent:
                        # Mid-frame cut: RST both legs so neither side
                        # can mistake this for a graceful close.
                        _rst_close(client)
                        _rst_close(up)
                        break
                    continue
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
                self.bytes_forwarded += len(chunk)
        finally:
            # Half-close propagation: EOF on one side ends the session.
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass
            with self._lock:
                self._pairs = [
                    p for p in self._pairs if p != (client, up)
                ]
