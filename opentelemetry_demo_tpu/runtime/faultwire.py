"""Fault-injecting TCP proxy: the chaos harness's wire layer.

The reference injects every failure through flagd flags — network
misbehaviour included (``kafkaQueueProblems`` starves the consumer from
inside the broker path). This proxy injects the failures a *flag
cannot*: the transport faults between the detector and its
dependencies. Park it between the daemon and the in-repo Kafka broker
(or an OTLP receiver) and it can, per the chaos plan:

- **delay** every forwarded chunk (``delay_s``) — congested link;
- **truncate mid-frame** (``truncate_after`` bytes client→upstream,
  then a hard RST) — a peer dying mid-protocol-frame, the case length-
  prefixed protocols like Kafka's are most sensitive to;
- **RST new connections** (``rst_connects``) — a listener that accepts
  and immediately resets, the half-crashed-broker shape;
- **blackhole** (``blackhole``) — accept and read but forward nothing:
  the half-open connection that pins naive clients forever;
- **kill live connections** (:meth:`kill_connections`) — RST both
  sides of every in-flight session, the broker-restart shape.

Faults are plain attributes, togglable at runtime (tests flip them
mid-stream), and env-seedable in the spirit of the reference's
flag-driven failures: ``FAULTWIRE_DELAY_MS``,
``FAULTWIRE_TRUNCATE_AFTER``, ``FAULTWIRE_RST=1``,
``FAULTWIRE_BLACKHOLE=1``.

This is a test/chaos tool with a real socket surface — the daemon under
test cannot tell it from a misbehaving network, which is the point.
"""

from __future__ import annotations

import os
import socket
import struct
import threading


def _rst_close(sock: socket.socket) -> None:
    """Close with SO_LINGER(1, 0): the kernel sends RST, not FIN — the
    abortive teardown a crashed process produces."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class FaultWire:
    """TCP fault proxy: listen on ``host:port``, forward to upstream."""

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.upstream = (upstream_host, upstream_port)
        # Fault plan (mutable at runtime; env-seeded like a fault flag).
        self.delay_s = float(os.environ.get("FAULTWIRE_DELAY_MS", "0")) / 1e3
        trunc = os.environ.get("FAULTWIRE_TRUNCATE_AFTER", "")
        self.truncate_after: int | None = int(trunc) if trunc else None
        self.rst_connects = os.environ.get("FAULTWIRE_RST", "") == "1"
        self.blackhole = os.environ.get("FAULTWIRE_BLACKHOLE", "") == "1"
        # Stats (observability for assertions and operators).
        self.conns_total = 0
        self.conns_killed = 0
        self.bytes_forwarded = 0
        self._lock = threading.Lock()
        self._pairs: list[tuple[socket.socket, socket.socket]] = []
        self._stop = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="faultwire-accept", daemon=True
        )

    # -- control --------------------------------------------------------

    def clear(self) -> None:
        """Drop every fault back to clean forwarding."""
        self.delay_s = 0.0
        self.truncate_after = None
        self.rst_connects = False
        self.blackhole = False

    def kill_connections(self) -> None:
        """RST both legs of every live session (broker-restart shape)."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
            self.conns_killed += len(pairs)
        for client, up in pairs:
            _rst_close(client)
            _rst_close(up)

    def start(self) -> None:
        self._acceptor.start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._acceptor.join(timeout=2.0)
        self.kill_connections()

    # -- data path ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                client, _addr = self._sock.accept()
            except OSError:
                return
            self.conns_total += 1
            if self.rst_connects:
                _rst_close(client)
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                # Upstream down: the client sees exactly what it would
                # against the dead upstream — a refused/reset connect.
                _rst_close(client)
                continue
            with self._lock:
                self._pairs.append((client, up))
            # Budget shared across both pump directions so "truncate
            # after N bytes" means N bytes of *protocol*, whichever
            # side is mid-frame when it runs out.
            budget = (
                [self.truncate_after]
                if self.truncate_after is not None else None
            )
            for src, dst, c2u in ((client, up, True), (up, client, False)):
                threading.Thread(
                    target=self._pump, args=(src, dst, c2u, client, up, budget),
                    name="faultwire-pump", daemon=True,
                ).start()

    def _pump(self, src, dst, c2u, client, up, budget) -> None:
        import time as _time

        try:
            while not self._stop:
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                if self.blackhole and c2u:
                    continue  # swallow the request; never answer
                if self.delay_s > 0:
                    _time.sleep(self.delay_s)
                if budget is not None:
                    with self._lock:
                        take = max(min(budget[0], len(chunk)), 0)
                        budget[0] -= take
                        spent = budget[0] <= 0
                    chunk = chunk[:take]
                    if chunk:
                        try:
                            dst.sendall(chunk)
                        except OSError:
                            break
                        self.bytes_forwarded += len(chunk)
                    if spent:
                        # Mid-frame cut: RST both legs so neither side
                        # can mistake this for a graceful close.
                        _rst_close(client)
                        _rst_close(up)
                        break
                    continue
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
                self.bytes_forwarded += len(chunk)
        finally:
            # Half-close propagation: EOF on one side ends the session.
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass
            with self._lock:
                self._pairs = [
                    p for p in self._pairs if p != (client, up)
                ]
