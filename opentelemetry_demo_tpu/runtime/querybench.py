"""Query-plane benchmark: read latency/QPS beside live ingest.

The overloadbench/replbench sibling for the read path: run a REAL
DetectorPipeline under steady span load, stand up the actual HTTP
query service (runtime.query) over the dispatch-lock snapshot helper,
and hammer it from concurrent clients while ingest keeps pumping:

- ``query_p99_ms`` / ``query_p50_ms`` — per-request wall time through
  the full stack (HTTP parse → snapshot cache → numpy sketch reads →
  JSON), the number an operator's dashboard refresh actually pays;
- ``query_qps`` — sustained answered queries/s at that latency;
- ``ingest_ratio`` — ingest spans/s WITH the query hammer running vs
  a query-free baseline measured the same way in the same process:
  the "reads must not degrade the write path" guard (bench.py's
  ingest/lag SLOs stay gated independently; this localizes any
  interference to the query plane itself).

``make querybench`` prints ONE json line; ``bench.py`` lifts
``query_p99_ms`` / ``query_qps`` into the flagship artifact (guarded
by ``BENCH_QUERY`` + try/except, the additive-field rule).
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .lagbench import make_columns
from .pipeline import DetectorPipeline
from .provenance import REASON_LATENCY, ProvenanceEngine
from .query import QueryEngine, QueryService

SERVICES = (
    "frontend", "cart", "checkout", "currency",
    "payment", "shipping", "email", "ad",
)


def _snapshot_fn(detector, pipe):
    """The daemon's snapshot discipline, bench-local: copy under the
    dispatch lock (dispatch donates), meta in the replication shape."""

    def snapshot():
        with pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in detector.state._asdict().items()
            }
            clock_t_prev = detector.clock._t_prev
        return arrays, {
            "offsets": {},
            "service_names": pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(detector.config._replace(sketch_impl=None)),
            "query": pipe.query_meta(),
        }

    return snapshot


def measure_query(
    seconds: float = 2.0,
    batch: int = 256,
    pump_interval_s: float = 0.01,
    query_threads: int = 4,
    query_interval_s: float = 0.02,
    seed: int = 0,
    config: DetectorConfig | None = None,
) -> dict:
    """Ingest-alone baseline, then ingest + concurrent query clients.

    Both phases run the identical pump loop in the same process, so
    the ingest_ratio isolates the query plane's interference instead
    of run-to-run weather. Clients are PACED (``query_interval_s``
    between requests, ~Grafana-refresh cadence ×N panels) rather than
    busy-looping: an unpaced hammer on a 2-core CI box measures GIL
    starvation of the pump thread, not the query plane — and no real
    dashboard polls in a hot loop."""
    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    detector = AnomalyDetector(config)
    prov = ProvenanceEngine(config)
    pipe = DetectorPipeline(detector, batch_size=batch, provenance=prov)
    for name in SERVICES:
        pipe.tensorizer.service_id(name)
    # Seed the explain ring so the /query/explain leg serves bundles of
    # realistic size (synthetic steady load rarely flags): built by the
    # REAL engine, landed the way replication lands them — the measured
    # cost is serialize + ship of true bundle payloads, not of "{}".
    pipe.restore_query_meta({
        "explains": [
            prov.build(
                t_batch=float(i), seq=i, service=i % len(SERVICES),
                label=SERVICES[i % len(SERVICES)],
                signals=[REASON_LATENCY], exemplars=[], state=None,
                hh_candidates=[], trace_id=None,
            )
            for i in range(8)
        ],
    })
    engine = QueryEngine(
        snapshot_fn=_snapshot_fn(detector, pipe), max_staleness_s=0.5
    )
    service = QueryService(engine, host="127.0.0.1", port=0)
    service.start()
    rng = np.random.default_rng(seed)

    def feed(t0: float, run_s: float) -> float:
        """Pump at cadence for run_s; returns spans/s over the phase."""
        spans0 = pipe.stats.spans
        t = t0
        t_end = time.monotonic() + run_s
        t_wall0 = time.monotonic()
        while time.monotonic() < t_end:
            cols = make_columns(rng, batch)
            cols = cols._replace(
                svc=(cols.svc % len(SERVICES)).astype(np.int32)
            )
            pipe.submit_columns(cols)
            pipe.pump(t)
            t += pump_interval_s
            time.sleep(pump_interval_s)
        pipe.drain()
        wall = max(time.monotonic() - t_wall0, 1e-6)
        return (pipe.stats.spans - spans0) / wall, t

    # Warmup (compile) + ingest-alone baseline.
    pipe.submit_columns(make_columns(rng, batch))
    pipe.pump(0.0)
    pipe.drain()
    baseline_rate, t_virtual = feed(pump_interval_s, seconds)

    # Query hammer beside live ingest.
    paths = [
        "/query/topk?service=frontend",
        "/query/cardinality?service=cart",
        "/query/zscore?service=checkout",
        "/query/anomalies?limit=10",
        "/query/explain?limit=5",
        "/query/services",
    ]
    latencies: list[float] = []
    explain_lat: list[float] = []
    errors = [0]
    lat_lock = threading.Lock()
    stop = threading.Event()

    def hammer(widx: int) -> None:
        conn = http.client.HTTPConnection(
            "127.0.0.1", service.port, timeout=5.0
        )
        i = widx
        while not stop.is_set():
            path = paths[i % len(paths)]
            i += 1
            t0 = time.perf_counter()
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except Exception:  # noqa: BLE001 — count, reconnect, go on
                ok = False
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", service.port, timeout=5.0
                )
            dt = time.perf_counter() - t0
            with lat_lock:
                if ok:
                    latencies.append(dt)
                    # The explain leg gets its own percentile (bundles
                    # are the fattest answers on the plane) while still
                    # counting toward the aggregate QPS above.
                    if path.startswith("/query/explain"):
                        explain_lat.append(dt)
                else:
                    errors[0] += 1
            if query_interval_s > dt:
                time.sleep(query_interval_s - dt)
        conn.close()

    workers = [
        threading.Thread(target=hammer, args=(w,), daemon=True)
        for w in range(query_threads)
    ]
    t_q0 = time.monotonic()
    for w in workers:
        w.start()
    try:
        query_rate, _ = feed(t_virtual, seconds)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5.0)
        query_wall = max(time.monotonic() - t_q0, 1e-6)
        service.stop()
    lat_ms = np.asarray(latencies) * 1e3
    exp_ms = np.asarray(explain_lat) * 1e3
    return {
        "query_p50_ms": (
            round(float(np.percentile(lat_ms, 50)), 3) if len(lat_ms) else None
        ),
        "query_p99_ms": (
            round(float(np.percentile(lat_ms, 99)), 3) if len(lat_ms) else None
        ),
        "explain_p99_ms": (
            round(float(np.percentile(exp_ms, 99)), 3) if len(exp_ms) else None
        ),
        "explain_queries": int(len(exp_ms)),
        "query_qps": round(len(lat_ms) / query_wall, 1),
        "query_errors": int(errors[0]),
        "queries_total": int(len(lat_ms)),
        "query_threads": int(query_threads),
        "ingest_spans_per_sec": round(query_rate, 1),
        "ingest_spans_per_sec_baseline": round(baseline_rate, 1),
        "ingest_ratio": round(query_rate / max(baseline_rate, 1e-9), 3),
        "spans_fed": int(pipe.stats.spans),
    }


def main() -> None:
    print(json.dumps(measure_query()))


if __name__ == "__main__":
    main()
