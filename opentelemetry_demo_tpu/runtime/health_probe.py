"""grpc_health_probe analogue: exit 0 iff a gRPC server reports SERVING.

The reference's deploy story health-gates startup on gRPC health
(every service registers grpc.health.v1 — /root/reference/src/checkout/
main.go:223-224, src/currency/src/server.cpp:92-102); container images
ship the ``grpc_health_probe`` binary for compose/k8s probes. This is
that probe for this framework's images:

    python -m opentelemetry_demo_tpu.runtime.health_probe \
        [--addr 127.0.0.1:4317] [--service oteldemo.CartService]

Raw-bytes unary call (no stubs): request = HealthCheckRequest{service},
response field 1 must equal SERVING (1).

Per-component probing (the supervised runtime, runtime.supervision):
``--component kafka-orders`` is shorthand for
``--service anomaly.component.kafka-orders`` — exit 0 only while that
supervised component is UP (not in backoff or crash-looping), the
k8s-liveness handle on a single degraded ingest leg.

Role probing (hot-standby replication, runtime.replication):
``--role`` queries the daemon's ``/healthz`` JSON on the METRICS port
(``--addr host:9464`` — a standby serves no gRPC ingress, so the role
surface lives beside Prometheus) and prints ``PRIMARY``/``STANDBY``/
``PROMOTING``/``FENCED`` plus the current fencing epoch::

    python -m opentelemetry_demo_tpu.runtime.health_probe \
        --role --addr 127.0.0.1:9464
    PRIMARY epoch=3

Exit 0 whenever the role was readable — a healthy standby IS healthy;
gate k8s readiness on the printed role, not the exit code, when only
the primary should receive traffic.
"""

from __future__ import annotations

import argparse
import sys

from . import wire
from .grpc_health import SERVING


def _healthz_doc(addr: str, timeout_s: float) -> dict | None:
    """The daemon's /healthz JSON, or None when unreachable. A 503
    (degraded) still carries the body — a degraded daemon's role and
    fleet view must stay readable (that IS the triage question)."""
    import json
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://{addr}/healthz", timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode())
        except Exception:  # noqa: BLE001 — unreadable body = unknown
            return None
    except Exception:  # noqa: BLE001 — any transport/parse failure is
        return None  # "unreadable" to the caller


def probe_role(addr: str, timeout_s: float = 3.0) -> tuple[str, int] | None:
    """(role, epoch) from the daemon's /healthz, or None when
    unreachable/old (a pre-replication daemon omits the fields —
    reported as primary at epoch 0, which is exactly what it is)."""
    doc = _healthz_doc(addr, timeout_s)
    if doc is None:
        return None
    return str(doc.get("role", "primary")), int(doc.get("epoch", 0))


def probe_shard(addr: str, timeout_s: float = 3.0) -> dict | None:
    """The /healthz ``fleet`` block (runtime.fleet membership: ring
    version, member set, peer liveness, reshard counters) from the
    daemon's metrics port, or None when unreachable / not a fleet
    member (single-shard daemons carry no fleet block)."""
    doc = _healthz_doc(addr, timeout_s)
    if doc is None:
        return None
    fleet = doc.get("fleet")
    return fleet if isinstance(fleet, dict) else None


def probe(addr: str, service: str = "", timeout_s: float = 3.0) -> bool:
    import grpc

    channel = grpc.insecure_channel(addr)
    check = channel.unary_unary(
        "/grpc.health.v1.Health/Check",
        request_serializer=None,
        response_deserializer=None,
    )
    request = wire.encode_len(1, service.encode()) if service else b""
    try:
        resp = check(request, timeout=timeout_s)
    except grpc.RpcError:
        return False
    finally:
        channel.close()
    return wire.first(wire.scan_fields(resp), 1) == SERVING


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--addr", default="127.0.0.1:4317")
    parser.add_argument("--service", default="")
    parser.add_argument(
        "--component", default="",
        help="supervised component name (shorthand for "
        "--service anomaly.component.<name>)",
    )
    parser.add_argument(
        "--role", action="store_true",
        help="print the replication role + epoch from /healthz on the "
        "metrics port (point --addr at host:9464, not the gRPC ingress)",
    )
    parser.add_argument(
        "--shard", action="store_true",
        help="print the fleet block from /healthz on the metrics port "
        "(shard id, ring version, live/total shards, per-peer "
        "liveness, reshard counters, frozen flag); exit 0 iff the "
        "block was readable",
    )
    parser.add_argument("--timeout", type=float, default=3.0)
    args = parser.parse_args()
    if args.shard:
        fleet = probe_shard(args.addr, args.timeout)
        if fleet is None:
            print("fleet view unreadable (not a fleet member?)",
                  file=sys.stderr)
            sys.exit(1)
        peers = ", ".join(
            f"{p}={'up' if st.get('alive') else 'DOWN'}"
            for p, st in sorted(fleet.get("peers", {}).items())
        ) or "none"
        print(
            f"{fleet.get('shard', '?').upper()} "
            f"ring={fleet.get('ring_version', 0):#x} "
            f"live={fleet.get('shards_live')}/{fleet.get('shards_total')} "
            f"reshards={fleet.get('reshards_total')} "
            f"refused={fleet.get('reshards_refused')} "
            f"frozen={fleet.get('frozen')} peers: {peers}"
        )
        sys.exit(0)
    if args.role:
        role_epoch = probe_role(args.addr, args.timeout)
        if role_epoch is None:
            print("role unreadable", file=sys.stderr)
            sys.exit(1)
        role, epoch = role_epoch
        print(f"{role.upper()} epoch={epoch}")
        sys.exit(0)
    service = args.service
    if args.component:
        from .supervision import HEALTH_PREFIX

        service = HEALTH_PREFIX + args.component
    sys.exit(0 if probe(args.addr, service, args.timeout) else 1)


if __name__ == "__main__":
    main()
