"""OTLP/gRPC receiver (:4317): the collector's primary telemetry ingress.

The reference collector listens for OTLP gRPC first and HTTP second
(/root/reference/src/otel-collector/otelcol-config.yml:5-8), and every
reference SDK defaults to gRPC export — so the sidecar speaks it too.
The transport is grpcio with *generic* raw-bytes handlers: no generated
stubs, no proto runtime on the hot path — request bytes go straight into
the same hand-rolled wire decoders the HTTP receiver uses
(runtime.otlp / runtime.otlp_metrics), and the response is the empty
Export*ServiceResponse (zero bytes is a valid empty proto3 message).

Service/method names are the public OTLP protocol's:
``opentelemetry.proto.collector.{trace,metrics}.v1``. Any OTLP gRPC
exporter (otel-go/java/python SDKs, another collector's ``otlp``
exporter) interoperates unchanged.
"""

from __future__ import annotations

from typing import Callable

from . import otlp, otlp_metrics
from .tensorize import SpanRecord

TRACE_EXPORT = "/opentelemetry.proto.collector.trace.v1.TraceService/Export"
METRICS_EXPORT = (
    "/opentelemetry.proto.collector.metrics.v1.MetricsService/Export"
)
LOGS_EXPORT = "/opentelemetry.proto.collector.logs.v1.LogsService/Export"


class OtlpGrpcReceiver:
    """gRPC twin of :class:`~.otlp.OtlpHttpReceiver` — same callbacks.

    ``on_records`` receives decoded SpanRecords per Export call;
    ``on_columnar`` (with the native decoder available) takes the C++
    columnar fast path; ``on_payload`` (the parallel ingest engine,
    ``runtime.ingest_pool``) hands the RAW request bytes to the decode
    pool and blocks only on the per-RPC ticket — malformed still
    answers ``INVALID_ARGUMENT``, a full pool queue the same retryable
    ``RESOURCE_EXHAUSTED`` as pipeline saturation;
    ``on_metric_records`` receives MetricRecords
    from the MetricsService. Malformed payloads answer
    ``INVALID_ARGUMENT`` (the client's fault) and are tallied in
    ``rejects``/``on_reject``; oversized messages are bounced by grpc
    itself (``RESOURCE_EXHAUSTED`` via ``max_receive_message_length``)
    before our handler runs; abrupt disconnects are absorbed by the
    transport. Callback failures propagate as ``INTERNAL`` — server
    bugs must surface.

    ``component_status`` (optional, from
    ``supervision.Supervisor.health_status``) lets the attached
    grpc.health.v1 service answer per-component Check requests
    (``anomaly.component.<name>``) beside the server-wide status.

    Backpressure (``retry_after``, the HTTP leg's 429 twin): while the
    pipeline is saturated, trace Exports abort with
    ``RESOURCE_EXHAUSTED`` — the OTLP spec's retryable status — with a
    ``retry-after-s`` trailing-metadata hint, tallied as
    ``rejects["saturated"]``. Metrics/logs Exports stay admitted (scrape
    cadence, not the flood the budget protects against).
    """

    def __init__(
        self,
        on_records: Callable[[list[SpanRecord]], None],
        host: str = "0.0.0.0",
        port: int = 4317,
        on_columnar: Callable | None = None,
        on_metric_records: Callable | None = None,
        on_log_records: Callable | None = None,
        max_workers: int = 4,
        on_reject: Callable[[str], None] | None = None,
        max_body_bytes: int = 16 << 20,
        component_status: Callable[[str], int | None] | None = None,
        retry_after: Callable[[], float | None] | None = None,
        on_payload: Callable | None = None,
    ):
        import grpc
        from concurrent import futures

        self.on_records = on_records
        self.on_columnar = on_columnar
        self.on_payload = on_payload
        self.on_metric_records = on_metric_records
        self.on_log_records = on_log_records
        self.on_reject = on_reject
        self.retry_after = retry_after
        self.rejects: dict[str, int] = {}
        receiver = self

        def _reject(reason: str) -> None:
            receiver.rejects[reason] = receiver.rejects.get(reason, 0) + 1
            if receiver.on_reject is not None:
                try:
                    receiver.on_reject(reason)
                except Exception:  # noqa: BLE001 — metrics must not kill ingest
                    pass

        def export_traces(request: bytes, context) -> bytes:
            if receiver.retry_after is not None:
                hint = receiver.retry_after()
                if hint is not None:
                    _reject("saturated")
                    # The OTLP/gRPC retryable contract: clients treat
                    # RESOURCE_EXHAUSTED as retry-with-backoff; the
                    # trailing metadata carries the server's hint
                    # (grpc_send honors it on the exporter side).
                    context.set_trailing_metadata(
                        (("retry-after-s", f"{hint:g}"),)
                    )
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"pipeline saturated; retry after {hint:g}s",
                    )
            if receiver.on_payload is not None:
                # Parallel ingest engine (runtime.ingest_pool): raw
                # body to the decode pool, block on this RPC's ticket.
                from .ingest_pool import (
                    IngestPoolSaturated,
                    IngestWorkerError,
                )

                try:
                    ticket = receiver.on_payload(request)
                except IngestPoolSaturated:
                    _reject("saturated")
                    context.set_trailing_metadata((("retry-after-s", "1"),))
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        "ingest pool saturated; retry",
                    )
                try:
                    ticket.result()
                except TimeoutError:
                    # Wedged flush: retryable, never a client fault.
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "ingest flush timed out; retry",
                    )
                except IngestWorkerError:
                    # Server-side flush failure — surface as INTERNAL
                    # exactly like a raising callback on the serial
                    # path, never as INVALID_ARGUMENT.
                    raise
                except Exception:
                    # Per-request DECODE verdict: the client's bytes.
                    _reject("malformed")
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "malformed OTLP payload",
                    )
                return b""  # empty ExportTraceServiceResponse
            columnar = None
            try:
                if receiver.on_columnar is not None:
                    columnar = otlp.decode_export_request_columnar(request)
                if columnar is None:
                    records = otlp.decode_export_request(request)
            except Exception:  # noqa: BLE001 — decoding the
                # client's bytes: whatever malformed protobuf raises is
                # the client's INVALID_ARGUMENT, never our crash.
                _reject("malformed")
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "malformed OTLP payload"
                )
            if columnar is not None:
                receiver.on_columnar(columnar)
            else:
                receiver.on_records(records)
            return b""  # empty ExportTraceServiceResponse

        def export_metrics(request: bytes, context) -> bytes:
            try:
                records = otlp_metrics.decode_metrics_request(request)
            except Exception:  # noqa: BLE001 — decoding the
                # client's bytes: whatever malformed protobuf raises is
                # the client's INVALID_ARGUMENT, never our crash.
                _reject("malformed")
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "malformed OTLP payload"
                )
            if receiver.on_metric_records is not None:
                receiver.on_metric_records(records)
            return b""  # empty ExportMetricsServiceResponse

        def export_logs(request: bytes, context) -> bytes:
            try:
                docs = otlp.decode_logs_request(request)
            except Exception:  # noqa: BLE001 — decoding the
                # client's bytes: whatever malformed protobuf raises is
                # the client's INVALID_ARGUMENT, never our crash.
                _reject("malformed")
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "malformed OTLP payload"
                )
            if receiver.on_log_records is not None:
                receiver.on_log_records(docs)
            return b""  # empty ExportLogsServiceResponse

        # grpc.health.v1 beside the OTLP ingress: the registration every
        # reference service performs (main.go:223-224, server.cpp:92-102),
        # and what the compose/k8s healthchecks probe on this daemon.
        # One watcher slot: the ingress pool is small (4 workers) and
        # Export throughput must never queue behind parked watchers.
        import threading

        from .grpc_health import HealthService

        self._stop_event = threading.Event()
        self._health = HealthService(
            {m.split("/")[1] for m in (TRACE_EXPORT, METRICS_EXPORT, LOGS_EXPORT)},
            self._stop_event,
            watcher_slots=1,
            component_status=component_status,
        )

        handlers = {
            TRACE_EXPORT: export_traces,
            METRICS_EXPORT: export_metrics,
            LOGS_EXPORT: export_logs,
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                health = receiver._health.add_to_generic_handlers(
                    grpc, details.method
                )
                if health is not None:
                    return health
                fn = handlers.get(details.method)
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=None, response_serializer=None
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="otlp-grpc"
            ),
            options=[
                # Oversized exports bounce at the transport with
                # RESOURCE_EXHAUSTED (the HTTP leg's 413 analogue)
                # instead of ballooning the decoder's heap.
                ("grpc.max_receive_message_length", max_body_bytes),
            ],
        )
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # grpc reports bind failure by returning port 0 instead of
            # raising; a daemon that silently boots with a dead primary
            # ingress is worse than one that refuses to boot.
            raise OSError(f"OTLP/gRPC receiver failed to bind {host}:{port}")

    def start(self) -> None:
        self._server.start()

    def alive(self) -> bool:
        """Liveness for the supervisor: started and not stopped. The
        grpc core owns its own threads (no Python thread to watch), so
        this reflects lifecycle state; the supervisor's deeper probe is
        a real health-check RPC on its own cadence."""
        return not self._stop_event.is_set()

    def stop(self, grace: float = 1.0) -> None:
        # NOT_SERVING reaches health watchers before the teardown.
        self._stop_event.set()
        self._server.stop(grace).wait()


def export_client(target: str):
    """(traces_fn, metrics_fn) raw-bytes unary callables for tests and
    the collector's gRPC exporter — each takes a serialized request and
    returns the (empty) response bytes."""
    import grpc

    channel = grpc.insecure_channel(target)
    traces = channel.unary_unary(
        TRACE_EXPORT, request_serializer=None, response_deserializer=None
    )
    metrics = channel.unary_unary(
        METRICS_EXPORT, request_serializer=None, response_deserializer=None
    )
    return traces, metrics
