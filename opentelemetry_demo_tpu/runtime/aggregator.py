"""Scatter-gather read tier over the sharded detector fleet.

The thin aggregator behind the EXISTING ``/query/*`` API: global reads
fan out to every shard's query plane (runtime.query over each shard's
own snapshot), the shard frames merge per endpoint, and the answer
comes back in one envelope labeled with
``shards_answered``/``shards_total`` — a blackholed, slow or dead
shard is ANNOTATED and the result degrades to a labeled PARTIAL
answer, never a crashed query and never a 5xx for a partial loss.

Merge semantics per endpoint:

- ``/query/services`` — union of the shard service lists (sorted);
- ``/query/topk`` / ``/query/cardinality`` / ``/query/zscore`` —
  service-keyed: the ring routes the read to the keyspace OWNER when a
  ring is wired (post-reshard that is the survivor that adopted the
  victim's frame); without a ring the fan-out keeps the shard that
  actually answered 200 (non-owners answer 404 for a service they
  never interned);
- ``/query/anomalies`` — events concatenated newest-first across
  shards, exemplar rings merged by service.

CONTRACT (pinned by scripts/sanitycheck.py, the runtime.query
discipline): this module NEVER touches detector state — no detector
import, no dispatch-lock reference, no snapshot function. It speaks
only HTTP to shard query planes, so it can run anywhere (its own
container: the ``anomaly-aggregator`` compose/k8s service) and the
loss of any shard can never take the global read surface down with
it.

Run standalone::

    ANOMALY_FLEET_SHARDS=3 \\
    ANOMALY_FLEET_QUERY_PEERS=shard0:9465,shard1:9465,shard2:9465 \\
    ANOMALY_AGGREGATOR_PORT=9470 \\
    python -m opentelemetry_demo_tpu.runtime.aggregator
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import NamedTuple
from urllib.parse import parse_qs, urlencode, urlparse

from ..telemetry import metrics as tele_metrics
from .fleet import HashRing, tenant_of

# The endpoints the aggregator understands (a strict subset of the
# shard query plane's vocabulary — flight/Grafana targets stay
# per-shard surfaces: a flight ring is process-local evidence).
AGG_ENDPOINTS = frozenset({
    "/", "/query/services", "/query/topk", "/query/cardinality",
    "/query/zscore", "/query/anomalies",
})

SERVICE_KEYED = frozenset({
    "/query/topk", "/query/cardinality", "/query/zscore",
})

LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class ShardAnswer(NamedTuple):
    shard: str
    status: int | None     # None = transport failure/timeout
    doc: dict | None
    error: str | None
    elapsed_s: float


def _fetch(
    shard: str, base: str, path: str, params: dict, timeout_s: float
) -> ShardAnswer:
    """One shard GET with a hard per-shard deadline. Every failure
    shape (refused, blackholed, RST mid-body, torn JSON) collapses to
    an annotated miss — the fan-out's promise is that no shard fault
    becomes an aggregator fault."""
    import http.client

    host, _, port = base.rpartition(":")
    t0 = time.perf_counter()
    try:
        conn = http.client.HTTPConnection(
            host or "127.0.0.1", int(port), timeout=timeout_s
        )
        try:
            query = urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
            conn.request("GET", path + ("?" + query if query else ""))
            resp = conn.getresponse()
            body = resp.read()
            doc = json.loads(body.decode()) if body else {}
            return ShardAnswer(
                shard, resp.status, doc, None,
                time.perf_counter() - t0,
            )
        finally:
            conn.close()
    except Exception as e:  # noqa: BLE001 — every transport/parse
        # failure is one annotated missing shard, never a crash
        return ShardAnswer(
            shard, None, None,
            f"{type(e).__name__}: {e}", time.perf_counter() - t0,
        )


class FleetAggregator:
    """Fans one query out to the shard query planes and merges.

    ``shards``: shard-id → query-plane base address (host:port).
    ``ring``/``tenant_map``: optional ownership routing for
    service-keyed endpoints (ring members must use the same shard
    ids). ``live_fn``: optional membership filter — shards it reports
    dead are skipped (annotated, not waited on).
    """

    def __init__(
        self,
        shards: dict[str, str],
        *,
        timeout_s: float = 1.0,
        ring: HashRing | None = None,
        tenant_map: dict[str, str] | None = None,
        live_fn=None,
    ):
        self.shards = dict(shards)
        self.timeout_s = float(timeout_s)
        self.ring = ring
        self.tenant_map = dict(tenant_map or {})
        self._live_fn = live_fn

    def close(self) -> None:
        pass  # fan-out threads are per-request daemons; nothing held

    # -- fan-out --------------------------------------------------------

    def _targets(self) -> dict[str, str]:
        if self._live_fn is None:
            return dict(self.shards)
        try:
            live = set(self._live_fn())
        except Exception:  # noqa: BLE001 — a broken membership view
            return dict(self.shards)  # degrades to full fan-out
        return {s: a for s, a in self.shards.items() if s in live}

    def _scatter(
        self, path: str, params: dict,
        skip: frozenset[str] = frozenset(),
    ) -> list[ShardAnswer]:
        """Fan out with a HARD wall-clock deadline.

        http.client's timeout bounds each socket operation, not the
        exchange: a shard trickling one byte per interval would keep
        every recv() under the limit and hang the query unboundedly.
        Dedicated daemon threads per request + a bounded join make the
        deadline real — a shard still mid-trickle at the deadline is
        annotated and abandoned (its thread dies with its next socket
        timeout), and no shared pool exists for a slow shard to clog."""
        targets = {
            s: a for s, a in self._targets().items() if s not in skip
        }
        results: dict[str, ShardAnswer] = {}

        def run(shard: str, base: str) -> None:
            results[shard] = _fetch(
                shard, base, path, params, self.timeout_s
            )

        threads = [
            threading.Thread(
                target=run, args=(shard, base),
                name=f"agg-fanout-{shard}", daemon=True,
            )
            for shard, base in targets.items()
        ]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 2.0 * self.timeout_s + 0.25
        for th in threads:
            th.join(max(deadline - time.monotonic(), 0.0))
        answers = []
        for shard in targets:
            got = results.get(shard)
            answers.append(got if got is not None else ShardAnswer(
                shard, None, None, "deadline exceeded", self.timeout_s,
            ))
        answers += [
            ShardAnswer(s, None, None, "membership: dead", 0.0)
            for s in self.shards
            if s not in targets and s not in skip
        ]
        return answers

    def _fetch_bounded(
        self, shard: str, base: str, path: str, params: dict
    ) -> ShardAnswer:
        """One shard fetch under the same hard deadline as _scatter —
        the owner-routed path must not be the one place a trickling
        shard can hang a query."""
        box: dict[str, ShardAnswer] = {}

        def run() -> None:
            box[shard] = _fetch(
                shard, base, path, params, self.timeout_s
            )

        th = threading.Thread(
            target=run, name=f"agg-fanout-{shard}", daemon=True
        )
        th.start()
        th.join(2.0 * self.timeout_s + 0.25)
        return box.get(shard) or ShardAnswer(
            shard, None, None, "deadline exceeded", self.timeout_s
        )

    # -- merge ----------------------------------------------------------

    def _fleet_meta(self, answers: list[ShardAnswer]) -> dict:
        ok = [a for a in answers if a.status == 200]
        per_shard = {}
        for a in sorted(answers):
            entry: dict = {"ok": a.status == 200}
            if a.status is not None:
                entry["status"] = a.status
            if a.error:
                entry["error"] = a.error
            if a.doc and isinstance(a.doc.get("meta"), dict):
                m = a.doc["meta"]
                for k in ("role", "epoch", "seq", "staleness_s"):
                    if k in m:
                        entry[k] = m[k]
            per_shard[a.shard] = entry
        meta = {
            "shards_total": len(self.shards),
            "shards_answered": len(ok),
            "partial": len(ok) < len(self.shards),
            "shards": per_shard,
        }
        if self.ring is not None:
            meta["ring_version"] = self.ring.version()
        return meta

    def dispatch(self, path: str, params: dict) -> tuple[int, dict]:
        """Route + merge one fleet query; (status, document). Never
        raises; a partial fleet answers 200 with ``partial: true``."""
        try:
            if path == "/":
                return 200, {
                    "status": "ok",
                    "tier": "aggregator",
                    "endpoints": sorted(AGG_ENDPOINTS - {"/"}),
                    "shards": sorted(self.shards),
                }
            if path not in AGG_ENDPOINTS:
                return 404, {"error": f"no such endpoint {path!r}"}
            if path in SERVICE_KEYED:
                return self._service_keyed(path, params)
            answers = self._scatter(path, params)
            meta = self._fleet_meta(answers)
            ok = [a for a in answers if a.status == 200]
            if path == "/query/services":
                names: set = set()
                for a in ok:
                    names.update(
                        (a.doc.get("data") or {}).get("services") or []
                    )
                data = {"services": sorted(names)}
            else:  # /query/anomalies
                events: list = []
                rings: dict = {}
                for a in ok:
                    d = a.doc.get("data") or {}
                    events.extend(d.get("events") or [])
                    for svc, ring in (d.get("exemplars") or {}).items():
                        merged = rings.setdefault(svc, [])
                        for tid in ring:
                            if tid not in merged:
                                merged.append(tid)
                events.sort(key=lambda e: -(e.get("t") or 0.0))
                limit = _int_param(params, "limit", 20)
                data = {"events": events[:limit], "exemplars": rings}
            if not ok:
                # TOTAL loss is the one honest 503 (nothing answered);
                # any partial answer stays 200 + labeled.
                return 503, {
                    "error": "no shard answered", "meta": meta,
                }
            return 200, {"data": data, "meta": meta}
        except Exception:  # noqa: BLE001 — an aggregator bug must
            # answer 500 like the shard plane's dispatch() does,
            # never tear down the keep-alive thread
            return 500, {"error": "internal aggregator error"}

    def _service_keyed(self, path: str, params: dict) -> tuple[int, dict]:
        service = params.get("service")
        if not service:
            return 400, {"error": "service parameter required"}
        owner = None
        if self.ring is not None:
            tenant = params.get("tenant") or tenant_of(
                service, self.tenant_map
            )
            try:
                owner = self.ring.owner_of(service, tenant)
            except RuntimeError:
                owner = None
        owner_answer = None
        if owner is not None and owner in self.shards:
            # Owner-routed: one shard holds this keyspace cell (after
            # a reshard, that is the survivor that adopted the
            # victim's frame). Fall through to fan-out if the owner
            # itself cannot answer — partial beats crashed.
            owner_answer = self._fetch_bounded(
                owner, self.shards[owner], path, params
            )
            if owner_answer.status == 200:
                meta = self._fleet_meta([owner_answer])
                meta["shards_total"] = len(self.shards)
                meta["partial"] = False
                meta["owner"] = owner
                return 200, {
                    "data": (owner_answer.doc or {}).get("data"),
                    "meta": meta,
                }
        # Fallback fan-out: the owner already spent its deadline —
        # carry its answer over instead of paying the dead shard's
        # timeout a second time.
        answers = self._scatter(
            path, params,
            skip=frozenset([owner]) if owner_answer is not None
            else frozenset(),
        )
        if owner_answer is not None:
            answers.append(owner_answer)
        meta = self._fleet_meta(answers)
        if owner is not None:
            meta["owner"] = owner
        ok = [a for a in answers if a.status == 200]
        if ok:
            # Deterministic pick: lowest shard id that answered (two
            # shards both answering a service happens transiently
            # right after a reshard merge — both hold the cell).
            best = sorted(ok)[0]
            return 200, {
                "data": (best.doc or {}).get("data"), "meta": meta,
            }
        not_found = [a for a in answers if a.status == 404]
        if len(not_found) == len(answers) and answers:
            return 404, {
                "error": f"unknown service {service!r}", "meta": meta,
            }
        # The owner (and everyone else) is unreachable/erroring: the
        # keyspace slice is browned out — a labeled partial answer
        # with no data, NOT a 5xx (the fleet contract: losing a shard
        # browns out its slice, it never crashes the read surface).
        return 200, {"data": None, "meta": meta}


def _int_param(params: dict, key: str, default: int) -> int:
    try:
        return int(params.get(key, default))
    except (TypeError, ValueError):
        return default


# -- HTTP surface -------------------------------------------------------


class AggregatorService:
    """HTTP server for the aggregator tier (GET-only; the shard query
    planes keep the Grafana/POST surfaces — dashboards point at a
    shard or at this tier interchangeably for the /query/* family)."""

    def __init__(
        self,
        aggregator: FleetAggregator,
        registry=None,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.aggregator = aggregator
        self.registry = registry
        self._host = host
        self._port_req = port
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def do_GET(self):  # noqa: N802 (http.server API)
                url = urlparse(self.path)
                params = {
                    k: v[0] for k, v in parse_qs(url.query).items()
                }
                t0 = time.perf_counter()
                status, doc = service.aggregator.dispatch(
                    url.path, params
                )
                try:
                    body = json.dumps(doc).encode()
                except (TypeError, ValueError):
                    status = 500
                    body = b'{"error": "internal aggregator error"}'
                try:
                    self.send_response(status)
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header(
                        "Access-Control-Allow-Origin", "*"
                    )
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-answer
                service._observe(
                    url.path, status, time.perf_counter() - t0
                )

            def log_message(self, *args):
                pass

        self._handler = Handler
        self._server = None
        self._thread = None
        self._started = False

    def _observe(self, endpoint: str, status: int, seconds: float) -> None:
        if self.registry is None:
            return
        label = endpoint if endpoint in AGG_ENDPOINTS else "other"
        self.registry.counter_add(
            tele_metrics.ANOMALY_QUERY_REQUESTS, 1.0,
            endpoint=f"agg:{label}", code=str(status),
        )
        self.registry.histogram_observe(
            tele_metrics.ANOMALY_QUERY_LATENCY, seconds,
            LATENCY_BUCKETS,
        )

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port_req

    def start(self) -> None:
        self._server = ThreadingHTTPServer(
            (self._host, self._port_req), self._handler
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="aggregator-http",
            daemon=True,
        )
        self._thread.start()
        self._started = True

    def alive(self) -> bool:
        return not self._started or self._thread.is_alive()

    def stop(self) -> None:
        if self._server is None:
            return
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()
        self.aggregator.close()


def main() -> None:
    """Standalone aggregator tier (the compose/k8s
    ``anomaly-aggregator`` service entry point)."""
    from ..utils.config import fleet_config, fleet_tenant_map
    from .fleet import parse_peer_list

    fl = fleet_config()
    shards = int(fl["ANOMALY_FLEET_SHARDS"])
    port = int(fl["ANOMALY_AGGREGATOR_PORT"])
    if shards < 2 or port < 0:
        raise SystemExit(
            "aggregator needs ANOMALY_FLEET_SHARDS >= 2 and "
            "ANOMALY_AGGREGATOR_PORT >= 0"
        )
    # Index-aligned query addresses; the aggregator is NOT a shard, so
    # self_index=-1 keeps every slot.
    addrs = parse_peer_list(
        str(fl["ANOMALY_FLEET_QUERY_PEERS"]), shards, self_index=-1
    )
    ring = HashRing(
        [f"shard-{i}" for i in range(shards)],
        vnodes=int(fl["ANOMALY_FLEET_VNODES"]),
    )
    aggregator = FleetAggregator(
        addrs,
        timeout_s=float(fl["ANOMALY_AGGREGATOR_TIMEOUT_S"]),
        ring=ring,
        tenant_map=fleet_tenant_map(fl["ANOMALY_FLEET_TENANTS"]),
    )
    service = AggregatorService(aggregator, port=port)
    service.start()
    print(
        f"anomaly-aggregator: query :{service.port} "
        f"shards {sorted(addrs)} ring {ring.version():#x}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        service.stop()


if __name__ == "__main__":
    main()
