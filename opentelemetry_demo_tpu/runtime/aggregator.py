"""Scatter-gather read tier over the sharded detector fleet.

The thin aggregator behind the EXISTING ``/query/*`` API: global reads
fan out to every shard's query plane (runtime.query over each shard's
own snapshot), the shard frames merge per endpoint, and the answer
comes back in one envelope labeled with
``shards_answered``/``shards_total`` — a blackholed, slow or dead
shard is ANNOTATED and the result degrades to a labeled PARTIAL
answer, never a crashed query and never a 5xx for a partial loss.

Merge semantics per endpoint:

- ``/query/services`` — union of the shard service lists (sorted);
- ``/query/topk`` / ``/query/cardinality`` / ``/query/zscore`` —
  service-keyed: the ring routes the read to the keyspace OWNER when a
  ring is wired (post-reshard that is the survivor that adopted the
  victim's frame); without a ring the fan-out keeps the shard that
  actually answered 200 (non-owners answer 404 for a service they
  never interned);
- ``/query/anomalies`` — events concatenated newest-first across
  shards, exemplar rings merged by service.

CONTRACT (pinned by scripts/sanitycheck.py, the runtime.query
discipline): this module NEVER touches detector state — no detector
import, no dispatch-lock reference, no snapshot function. It speaks
only HTTP to shard query planes, so it can run anywhere (its own
container: the ``anomaly-aggregator`` compose/k8s service) and the
loss of any shard can never take the global read surface down with
it.

Run standalone::

    ANOMALY_FLEET_SHARDS=3 \\
    ANOMALY_FLEET_QUERY_PEERS=shard0:9465,shard1:9465,shard2:9465 \\
    ANOMALY_AGGREGATOR_PORT=9470 \\
    python -m opentelemetry_demo_tpu.runtime.aggregator
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import NamedTuple
from urllib.parse import parse_qs, urlencode, urlparse

from ..telemetry import metrics as tele_metrics
from .fleet import HashRing, tenant_of

# The endpoints the aggregator understands (a strict subset of the
# shard query plane's vocabulary — the flight target stays a
# per-shard surface: a flight ring is process-local evidence).
AGG_ENDPOINTS = frozenset({
    "/", "/query/services", "/query/topk", "/query/cardinality",
    "/query/zscore", "/query/anomalies",
})

SERVICE_KEYED = frozenset({
    "/query/topk", "/query/cardinality", "/query/zscore",
})

# Grafana simple-JSON datasource surface (the same contract the shard
# query plane serves per-shard): dashboards point at the FLEET —
# service-keyed targets route to the keyspace owner, table targets
# merge across shards.
GRAFANA_ENDPOINTS = frozenset({"/search", "/query", "/annotations"})

# Query bodies are small Grafana target lists, never megabytes (the
# shard plane's 413 discipline, mirrored).
MAX_BODY_BYTES = 1 << 20

LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class ShardAnswer(NamedTuple):
    shard: str
    status: int | None     # None = transport failure/timeout
    doc: dict | list | None  # Grafana endpoints answer bare lists
    error: str | None
    elapsed_s: float


def _fetch(
    shard: str, base: str, path: str, params: dict, timeout_s: float,
    body: dict | None = None,
) -> ShardAnswer:
    """One shard GET (or POST when ``body`` rides along — the Grafana
    fan-out) with a hard per-shard deadline. Every failure shape
    (refused, blackholed, RST mid-body, torn JSON) collapses to an
    annotated miss — the fan-out's promise is that no shard fault
    becomes an aggregator fault."""
    import http.client

    host, _, port = base.rpartition(":")
    t0 = time.perf_counter()
    try:
        conn = http.client.HTTPConnection(
            host or "127.0.0.1", int(port), timeout=timeout_s
        )
        try:
            query = urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
            target = path + ("?" + query if query else "")
            if body is not None:
                conn.request(
                    "POST", target, body=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                )
            else:
                conn.request("GET", target)
            resp = conn.getresponse()
            raw = resp.read()
            doc = json.loads(raw.decode()) if raw else {}
            return ShardAnswer(
                shard, resp.status, doc, None,
                time.perf_counter() - t0,
            )
        finally:
            conn.close()
    except Exception as e:  # noqa: BLE001 — every transport/parse
        # failure is one annotated missing shard, never a crash
        return ShardAnswer(
            shard, None, None,
            f"{type(e).__name__}: {e}", time.perf_counter() - t0,
        )


class FleetAggregator:
    """Fans one query out to the shard query planes and merges.

    ``shards``: shard-id → query-plane base address (host:port).
    ``ring``/``tenant_map``: optional ownership routing for
    service-keyed endpoints (ring members must use the same shard
    ids). ``live_fn``: optional membership filter — shards it reports
    dead are skipped (annotated, not waited on).
    """

    def __init__(
        self,
        shards: dict[str, str],
        *,
        timeout_s: float = 1.0,
        ring: HashRing | None = None,
        tenant_map: dict[str, str] | None = None,
        live_fn=None,
        health_addrs: dict[str, str] | None = None,
    ):
        self.shards = dict(shards)
        self.timeout_s = float(timeout_s)
        self.ring = ring
        self.tenant_map = dict(tenant_map or {})
        self._live_fn = live_fn
        # Ring-staleness repair (``health_addrs``: shard-id → /healthz
        # address, the heartbeat list): a standalone aggregator pins a
        # boot-time ring, so after an adoption/resize it would route
        # service-keyed reads to a shard that no longer owns the key —
        # forever. When the owner misses, placement refreshes from the
        # shard /healthz fleet blocks (which publish members + the
        # adopted map — enough to rebuild the IDENTICAL ring) and the
        # read retries once against the new owner. The embedded
        # aggregator shares the live membership ring and passes None.
        self._health_addrs = dict(health_addrs or {})
        self._ring_refresh_t = 0.0
        self._ring_refreshes = 0

    def close(self) -> None:
        pass  # fan-out threads are per-request daemons; nothing held

    # -- fan-out --------------------------------------------------------

    def _targets(self) -> dict[str, str]:
        if self._live_fn is None:
            return dict(self.shards)
        try:
            live = set(self._live_fn())
        except Exception:  # noqa: BLE001 — a broken membership view
            return dict(self.shards)  # degrades to full fan-out
        return {s: a for s, a in self.shards.items() if s in live}

    def _scatter(
        self, path: str, params: dict,
        skip: frozenset[str] = frozenset(),
        body: dict | None = None,
    ) -> list[ShardAnswer]:
        """Fan out with a HARD wall-clock deadline.

        http.client's timeout bounds each socket operation, not the
        exchange: a shard trickling one byte per interval would keep
        every recv() under the limit and hang the query unboundedly.
        Dedicated daemon threads per request + a bounded join make the
        deadline real — a shard still mid-trickle at the deadline is
        annotated and abandoned (its thread dies with its next socket
        timeout), and no shared pool exists for a slow shard to clog."""
        targets = {
            s: a for s, a in self._targets().items() if s not in skip
        }
        results: dict[str, ShardAnswer] = {}

        def run(shard: str, base: str) -> None:
            results[shard] = _fetch(
                shard, base, path, params, self.timeout_s, body=body
            )

        threads = [
            threading.Thread(
                target=run, args=(shard, base),
                name=f"agg-fanout-{shard}", daemon=True,
            )
            for shard, base in targets.items()
        ]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 2.0 * self.timeout_s + 0.25
        for th in threads:
            th.join(max(deadline - time.monotonic(), 0.0))
        answers = []
        for shard in targets:
            got = results.get(shard)
            answers.append(got if got is not None else ShardAnswer(
                shard, None, None, "deadline exceeded", self.timeout_s,
            ))
        answers += [
            ShardAnswer(s, None, None, "membership: dead", 0.0)
            for s in self.shards
            if s not in targets and s not in skip
        ]
        return answers

    def _fetch_bounded(
        self, shard: str, base: str, path: str, params: dict,
        body: dict | None = None,
    ) -> ShardAnswer:
        """One shard fetch under the same hard deadline as _scatter —
        the owner-routed path must not be the one place a trickling
        shard can hang a query."""
        box: dict[str, ShardAnswer] = {}

        def run() -> None:
            box[shard] = _fetch(
                shard, base, path, params, self.timeout_s, body=body
            )

        th = threading.Thread(
            target=run, name=f"agg-fanout-{shard}", daemon=True
        )
        th.start()
        th.join(2.0 * self.timeout_s + 0.25)
        return box.get(shard) or ShardAnswer(
            shard, None, None, "deadline exceeded", self.timeout_s
        )

    # -- ring refresh ----------------------------------------------------

    def _refresh_ring(self) -> bool:
        """Rebuild placement from the shard /healthz fleet blocks;
        True when the ring actually CHANGED (the retry-once trigger).

        The fleet block publishes members + the adopted map, so the
        rebuilt ring is bit-identical to the shards' own (the
        zero-coordination property adoption relies on). Throttled: an
        owner-miss storm must not turn into a healthz-poll storm."""
        if self.ring is None or not self._health_addrs:
            return False
        now = time.monotonic()
        if now - self._ring_refresh_t < 0.5:
            return False
        self._ring_refresh_t = now
        current = self.ring.version()
        results: dict[str, ShardAnswer] = {}

        def run(shard: str, base: str) -> None:
            results[shard] = _fetch(
                shard, base, "/healthz", {}, self.timeout_s
            )

        threads = [
            threading.Thread(
                target=run, args=(s, a), name=f"agg-healthz-{s}",
                daemon=True,
            )
            for s, a in self._health_addrs.items()
        ]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 2.0 * self.timeout_s + 0.25
        for th in threads:
            th.join(max(deadline - time.monotonic(), 0.0))
        # Prefer the HIGHEST reshard count on a mismatching version:
        # mid-resize the laggard shards still publish the old ring,
        # and adopting a stale view would "refresh" into yesterday.
        best: dict | None = None
        for a in results.values():
            if a.status != 200 or not isinstance(a.doc, dict):
                continue
            fb = a.doc.get("fleet")
            if not isinstance(fb, dict) or not fb.get("members"):
                continue
            if fb.get("ring_version") == current:
                continue
            if (
                best is None
                or fb.get("reshards_total", 0)
                > best.get("reshards_total", 0)
            ):
                best = fb
        if best is None:
            return False
        self.ring = HashRing(
            [str(m) for m in best["members"]],
            vnodes=int(best.get("owned_vnodes") or self.ring.vnodes),
            adopted={
                str(v): str(h)
                for v, h in (best.get("adopted") or {}).items()
            },
        )
        self._ring_refreshes += 1
        return True

    # -- merge ----------------------------------------------------------

    def _fleet_meta(self, answers: list[ShardAnswer]) -> dict:
        ok = [a for a in answers if a.status == 200]
        per_shard = {}
        for a in sorted(answers):
            entry: dict = {"ok": a.status == 200}
            if a.status is not None:
                entry["status"] = a.status
            if a.error:
                entry["error"] = a.error
            if a.doc and isinstance(a.doc.get("meta"), dict):
                m = a.doc["meta"]
                for k in ("role", "epoch", "seq", "staleness_s"):
                    if k in m:
                        entry[k] = m[k]
            per_shard[a.shard] = entry
        meta = {
            "shards_total": len(self.shards),
            "shards_answered": len(ok),
            "partial": len(ok) < len(self.shards),
            "shards": per_shard,
        }
        if self.ring is not None:
            meta["ring_version"] = self.ring.version()
        return meta

    def dispatch(
        self, path: str, params: dict, body: dict | None = None,
    ) -> tuple[int, dict | list]:
        """Route + merge one fleet query; (status, document). Never
        raises; a partial fleet answers 200 with ``partial: true``.
        Grafana endpoints take the POST ``body`` and answer the bare
        lists the simple-JSON contract wants."""
        try:
            if path == "/":
                return 200, {
                    "status": "ok",
                    "tier": "aggregator",
                    "endpoints": sorted(
                        (AGG_ENDPOINTS | GRAFANA_ENDPOINTS) - {"/"}
                    ),
                    "shards": sorted(self.shards),
                }
            if path in GRAFANA_ENDPOINTS:
                return self._grafana(path, body or {})
            if path not in AGG_ENDPOINTS:
                return 404, {"error": f"no such endpoint {path!r}"}
            if path in SERVICE_KEYED:
                return self._service_keyed(path, params)
            answers = self._scatter(path, params)
            meta = self._fleet_meta(answers)
            ok = [a for a in answers if a.status == 200]
            if path == "/query/services":
                names: set = set()
                for a in ok:
                    names.update(
                        (a.doc.get("data") or {}).get("services") or []
                    )
                data = {"services": sorted(names)}
            else:  # /query/anomalies
                events: list = []
                rings: dict = {}
                for a in ok:
                    d = a.doc.get("data") or {}
                    events.extend(d.get("events") or [])
                    for svc, ring in (d.get("exemplars") or {}).items():
                        merged = rings.setdefault(svc, [])
                        for tid in ring:
                            if tid not in merged:
                                merged.append(tid)
                events.sort(key=lambda e: -(e.get("t") or 0.0))
                limit = _int_param(params, "limit", 20)
                data = {"events": events[:limit], "exemplars": rings}
            if not ok:
                # TOTAL loss is the one honest 503 (nothing answered);
                # any partial answer stays 200 + labeled.
                return 503, {
                    "error": "no shard answered", "meta": meta,
                }
            return 200, {"data": data, "meta": meta}
        except Exception:  # noqa: BLE001 — an aggregator bug must
            # answer 500 like the shard plane's dispatch() does,
            # never tear down the keep-alive thread
            return 500, {"error": "internal aggregator error"}

    def _service_keyed(self, path: str, params: dict) -> tuple[int, dict]:
        service = params.get("service")
        if not service:
            return 400, {"error": "service parameter required"}
        owner = None
        tenant = None
        if self.ring is not None:
            tenant = params.get("tenant") or tenant_of(
                service, self.tenant_map
            )
            try:
                owner = self.ring.owner_of(service, tenant)
            except RuntimeError:
                owner = None
        refreshed = False
        tried: set[str] = set()
        misses: list[ShardAnswer] = []
        if owner is not None and owner in self.shards:
            # Owner-routed: one shard holds this keyspace cell (after
            # a reshard, that is the survivor that adopted the
            # victim's frame). Fall through to fan-out if the owner
            # itself cannot answer — partial beats crashed.
            owner_answer = self._fetch_bounded(
                owner, self.shards[owner], path, params
            )
            tried.add(owner)
            if owner_answer.status == 200:
                meta = self._fleet_meta([owner_answer])
                meta["shards_total"] = len(self.shards)
                meta["partial"] = False
                meta["owner"] = owner
                return 200, {
                    "data": (owner_answer.doc or {}).get("data"),
                    "meta": meta,
                }
            misses.append(owner_answer)
            # The boot-time-ring staleness repair: an owner miss right
            # after an adoption/resize usually means OUR placement is
            # old, not that the key is gone. Refresh the ring from the
            # shard /healthz fleet blocks and retry ONCE against the
            # new owner — then (and only then) pay the full fan-out.
            if self._refresh_ring():
                refreshed = True
                try:
                    new_owner = self.ring.owner_of(service, tenant)
                except RuntimeError:
                    new_owner = None
                if (
                    new_owner is not None
                    and new_owner != owner
                    and new_owner in self.shards
                ):
                    retry = self._fetch_bounded(
                        new_owner, self.shards[new_owner], path, params
                    )
                    tried.add(new_owner)
                    if retry.status == 200:
                        meta = self._fleet_meta([retry])
                        meta["shards_total"] = len(self.shards)
                        meta["partial"] = False
                        meta["owner"] = new_owner
                        meta["ring_refreshed"] = True
                        return 200, {
                            "data": (retry.doc or {}).get("data"),
                            "meta": meta,
                        }
                    misses.append(retry)
                    owner = new_owner
        # Fallback fan-out: the tried owners already spent their
        # deadlines — carry their answers over instead of paying a
        # dead shard's timeout a second time.
        answers = self._scatter(path, params, skip=frozenset(tried))
        answers += misses
        meta = self._fleet_meta(answers)
        if owner is not None:
            meta["owner"] = owner
        if refreshed:
            meta["ring_refreshed"] = True
        ok = [a for a in answers if a.status == 200]
        if ok:
            # Deterministic pick: lowest shard id that answered (two
            # shards both answering a service happens transiently
            # right after a reshard merge — both hold the cell).
            best = sorted(ok)[0]
            return 200, {
                "data": (best.doc or {}).get("data"), "meta": meta,
            }
        not_found = [a for a in answers if a.status == 404]
        if len(not_found) == len(answers) and answers:
            return 404, {
                "error": f"unknown service {service!r}", "meta": meta,
            }
        # The owner (and everyone else) is unreachable/erroring: the
        # keyspace slice is browned out — a labeled partial answer
        # with no data, NOT a 5xx (the fleet contract: losing a shard
        # browns out its slice, it never crashes the read surface).
        return 200, {"data": None, "meta": meta}

    # -- Grafana simple-JSON (fleet-global datasource) -------------------

    def _grafana(self, path: str, body: dict) -> tuple[int, dict | list]:
        """Fleet-global Grafana surface: dashboards point at the
        FLEET, not a shard (the per-shard plane keeps serving its own
        copy — this tier merges). ``flight`` targets are deliberately
        absent: a flight ring is process-local evidence, and a merged
        one would interleave unrelated incident timelines."""
        if path == "/search":
            answers = self._scatter("/search", {}, body=body)
            targets: set = set()
            for a in answers:
                if a.status == 200 and isinstance(a.doc, list):
                    targets.update(
                        t for t in a.doc
                        if isinstance(t, str) and t != "flight"
                    )
            if not targets and not any(
                a.status == 200 for a in answers
            ):
                return 503, {"error": "no shard answered"}
            return 200, sorted(targets)
        if path == "/annotations":
            answers = self._scatter("/annotations", {}, body=body)
            merged: list = []
            answered = False
            for a in answers:
                if a.status == 200 and isinstance(a.doc, list):
                    answered = True
                    merged.extend(
                        e for e in a.doc if isinstance(e, dict)
                    )
            if not answered:
                return 503, {"error": "no shard answered"}
            merged.sort(key=lambda e: -(e.get("time") or 0.0))
            return 200, merged
        # /query: each target routes INDEPENDENTLY (a multi-target
        # body fanned out whole would 400 on every shard that never
        # interned one of the services), service-keyed targets to the
        # ring owner, table targets merged across the fleet.
        out: list = []
        for tgt in body.get("targets") or []:
            if not isinstance(tgt, dict):
                continue
            target = (tgt.get("target") or "").strip()
            single = {
                k: v for k, v in body.items() if k != "targets"
            }
            single["targets"] = [tgt]
            out.append(self._grafana_target(target, single))
        return 200, [f for f in out if f is not None]

    def _grafana_target(self, target: str, single: dict):
        """One target's merged frame (None = nobody answered — the
        frame is dropped, Grafana's convention for an empty result)."""
        kind, _, svc = target.partition(":")
        if svc and self.ring is not None:
            # Service-keyed series: the keyspace owner answers (post-
            # adoption, the heir). Owner miss → one refresh + retry,
            # then lowest-shard fan-out — the /query/* routing rules.
            tenant = tenant_of(svc, self.tenant_map)
            for attempt in range(2):
                try:
                    owner = self.ring.owner_of(svc, tenant)
                except RuntimeError:
                    break
                if owner not in self.shards:
                    break
                a = self._fetch_bounded(
                    owner, self.shards[owner], "/query", {}, body=single
                )
                if (
                    a.status == 200 and isinstance(a.doc, list)
                    and a.doc
                ):
                    return a.doc[0]
                if attempt == 0 and not self._refresh_ring():
                    break
        answers = self._scatter("/query", {}, body=single)
        frames = [
            a.doc[0] for a in sorted(answers)
            if a.status == 200 and isinstance(a.doc, list) and a.doc
            and isinstance(a.doc[0], dict)
        ]
        if not frames:
            return None
        if kind == "anomalies":
            # Table target: rows merge across shards (each shard flags
            # its own keyspace), newest first, columns from the first.
            rows: list = []
            for f in frames:
                rows.extend(f.get("rows") or [])
            rows.sort(key=lambda r: -(r[0] if r else 0.0))
            return {
                "type": "table",
                "columns": frames[0].get("columns") or [],
                "rows": rows,
            }
        # Timeseries (or a service-keyed target with no ring): first
        # shard with data wins — transiently-duplicated cells right
        # after an adoption pick deterministically, like /query/*.
        for f in frames:
            if f.get("datapoints") or f.get("rows"):
                return f
        return frames[0]


def _int_param(params: dict, key: str, default: int) -> int:
    try:
        return int(params.get(key, default))
    except (TypeError, ValueError):
        return default


# -- HTTP surface -------------------------------------------------------


class AggregatorService:
    """HTTP server for the aggregator tier: the /query/* family plus
    the Grafana simple-JSON datasource (POST /search /query
    /annotations) — dashboards point at the FLEET; the per-shard
    query planes keep serving their own copies."""

    def __init__(
        self,
        aggregator: FleetAggregator,
        registry=None,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.aggregator = aggregator
        self.registry = registry
        self._host = host
        self._port_req = port
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def _answer(self, path, params, body=None):
                t0 = time.perf_counter()
                status, doc = service.aggregator.dispatch(
                    path, params, body
                )
                try:
                    payload = json.dumps(doc).encode()
                except (TypeError, ValueError):
                    status = 500
                    payload = b'{"error": "internal aggregator error"}'
                try:
                    self.send_response(status)
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.send_header(
                        "Access-Control-Allow-Origin", "*"
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-answer
                service._observe(
                    path, status, time.perf_counter() - t0
                )

            def do_GET(self):  # noqa: N802 (http.server API)
                url = urlparse(self.path)
                params = {
                    k: v[0] for k, v in parse_qs(url.query).items()
                }
                self._answer(url.path, params)

            def do_POST(self):  # noqa: N802 — the Grafana surface
                # (the shard query plane's body discipline, mirrored:
                # unknowable framing closes, oversized refuses UNREAD)
                url = urlparse(self.path)
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    if n < 0:
                        raise ValueError("negative Content-Length")
                except ValueError:
                    self.close_connection = True
                    self._answer_error(400, "malformed Content-Length")
                    return
                if n > MAX_BODY_BYTES:
                    self.close_connection = True
                    self._answer_error(413, "body too large")
                    return
                try:
                    raw = self.rfile.read(n) if n else b""
                    doc = json.loads(raw.decode()) if raw else {}
                    if not isinstance(doc, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, UnicodeDecodeError):
                    self._answer_error(400, "malformed JSON body")
                    return
                self._answer(url.path, {}, doc)

            def do_OPTIONS(self):  # noqa: N802 — Grafana CORS preflight
                self.send_response(204)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Headers", "Content-Type"
                )
                self.send_header(
                    "Access-Control-Allow-Methods", "GET, POST, OPTIONS"
                )
                self.end_headers()

            def _answer_error(self, status: int, msg: str) -> None:
                body = json.dumps({"error": msg}).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Access-Control-Allow-Origin", "*")
                if self.close_connection:
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)
                service._observe(
                    urlparse(self.path).path, status, 0.0
                )

            def log_message(self, *args):
                pass

        self._handler = Handler
        self._server = None
        self._thread = None
        self._started = False

    def _observe(self, endpoint: str, status: int, seconds: float) -> None:
        if self.registry is None:
            return
        label = (
            endpoint
            if endpoint in AGG_ENDPOINTS or endpoint in GRAFANA_ENDPOINTS
            else "other"
        )
        self.registry.counter_add(
            tele_metrics.ANOMALY_QUERY_REQUESTS, 1.0,
            endpoint=f"agg:{label}", code=str(status),
        )
        self.registry.histogram_observe(
            tele_metrics.ANOMALY_QUERY_LATENCY, seconds,
            LATENCY_BUCKETS,
        )

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port_req

    def start(self) -> None:
        self._server = ThreadingHTTPServer(
            (self._host, self._port_req), self._handler
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="aggregator-http",
            daemon=True,
        )
        self._thread.start()
        self._started = True

    def alive(self) -> bool:
        return not self._started or self._thread.is_alive()

    def stop(self) -> None:
        if self._server is None:
            return
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()
        self.aggregator.close()


def main() -> None:
    """Standalone aggregator tier (the compose/k8s
    ``anomaly-aggregator`` service entry point)."""
    from ..utils.config import fleet_config, fleet_tenant_map
    from .fleet import parse_peer_list

    fl = fleet_config()
    shards = int(fl["ANOMALY_FLEET_SHARDS"])
    port = int(fl["ANOMALY_AGGREGATOR_PORT"])
    if shards < 2 or port < 0:
        raise SystemExit(
            "aggregator needs ANOMALY_FLEET_SHARDS >= 2 and "
            "ANOMALY_AGGREGATOR_PORT >= 0"
        )
    # Index-aligned query addresses; the aggregator is NOT a shard, so
    # self_index=-1 keeps every slot.
    addrs = parse_peer_list(
        str(fl["ANOMALY_FLEET_QUERY_PEERS"]), shards, self_index=-1
    )
    # Heartbeat (/healthz) addresses feed the ring-staleness repair:
    # placement refreshes from the shard fleet blocks when the
    # boot-time ring routes a read to a shard that no longer owns it.
    health_addrs = parse_peer_list(
        str(fl["ANOMALY_FLEET_PEERS"]), shards, self_index=-1
    )
    ring = HashRing(
        [f"shard-{i}" for i in range(shards)],
        vnodes=int(fl["ANOMALY_FLEET_VNODES"]),
    )
    aggregator = FleetAggregator(
        addrs,
        timeout_s=float(fl["ANOMALY_AGGREGATOR_TIMEOUT_S"]),
        ring=ring,
        tenant_map=fleet_tenant_map(fl["ANOMALY_FLEET_TENANTS"]),
        health_addrs=health_addrs,
    )
    service = AggregatorService(aggregator, port=port)
    service.start()
    print(
        f"anomaly-aggregator: query :{service.port} "
        f"shards {sorted(addrs)} ring {ring.version():#x}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        service.stop()


if __name__ == "__main__":
    main()
