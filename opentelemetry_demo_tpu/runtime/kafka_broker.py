"""In-repo Kafka broker: the compose topology's broker as a test double.

A real TCP server speaking the wire subset in ``kafka_wire`` — Produce,
Fetch, ListOffsets, Metadata, FindCoordinator, OffsetCommit and
OffsetFetch — with append-only partition logs and durable-for-the-run
consumer-group offset storage. It exists so the orders leg can be
exercised the way the reference exercises fraud-detection/accounting
against its broker (docker-compose.yml kafka service): bytes over a
socket, committed offsets, resume; NOT to replace a production broker
in deployment (the compose overlay points ``KAFKA_ADDR`` at the real
one; the client speaks the same protocol either way).

Thread model: acceptor thread + one thread per connection; all state
behind one lock (the broker serves tests and local sims — correctness
over concurrency-cleverness).
"""

from __future__ import annotations

import socket
import threading
from typing import NamedTuple

from . import kafka_wire as kw


class StoredMessage(NamedTuple):
    key: bytes | None
    value: bytes | None
    headers: tuple  # ((str, bytes|None), ...) — v2 record headers
    timestamp_ms: int


class _PartitionLog:
    def __init__(self):
        self.messages: list[StoredMessage] = []

    @property
    def high_watermark(self) -> int:
        return len(self.messages)


class KafkaBroker:
    """Single-node broker; node id 0, coordinator for every group."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, num_partitions: int = 1):
        self.host = host
        self.num_partitions = num_partitions
        self._lock = threading.Lock()
        self._topics: dict[str, list[_PartitionLog]] = {}
        self._group_offsets: dict[tuple[str, str, int], int] = {}
        # Commit metadata strings beside the offsets (real Kafka stores
        # them together): the epoch-tag channel for fenced commits.
        self._group_meta: dict[tuple[str, str, int], str] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="kafka-broker-accept", daemon=True
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._acceptor.start()

    def stop(self) -> None:
        self._stop = True
        # close() alone does NOT wake a thread blocked in accept() — the
        # kernel socket survives the fd close while the syscall holds it
        # and keeps accepting (the port then never frees). shutdown()
        # interrupts the accept deterministically.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._acceptor.join(timeout=2.0)
        # Close accepted connections too: a conn thread blocked in recv
        # would otherwise hold the port against a broker restart.
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)

    # -- test/sim conveniences -----------------------------------------

    def ensure_topic(self, name: str) -> None:
        with self._lock:
            self._topics.setdefault(
                name, [_PartitionLog() for _ in range(self.num_partitions)]
            )

    def append(self, topic: str, value: bytes, key: bytes | None = None,
               partition: int = 0, headers=()) -> int:
        """Direct append (producer-side shortcut for sims); returns offset."""
        self.ensure_topic(topic)
        with self._lock:
            log = self._topics[topic][partition]
            log.messages.append(StoredMessage(key, value, tuple(headers), 0))
            return log.high_watermark - 1

    def committed(self, group: str, topic: str, partition: int = 0) -> int:
        with self._lock:
            return self._group_offsets.get((group, topic, partition), -1)

    # -- server loops ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="kafka-broker-conn", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop:
                frame = kw.read_frame(conn)
                if frame is None:
                    return
                reader = kw.Reader(frame)
                header = kw.decode_request_header(reader)
                body = self._dispatch(header, reader)
                conn.sendall(kw.encode_response(header.correlation_id, body))
        except (kw.KafkaWireError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- request handlers ----------------------------------------------

    def _dispatch(self, header: kw.RequestHeader, r: kw.Reader) -> bytes:
        handlers = {
            (kw.PRODUCE, 0): self._produce_v0,
            (kw.PRODUCE, 3): self._produce_v3,
            (kw.FETCH, 0): self._fetch_v0,
            (kw.FETCH, 4): self._fetch_v4,
            (kw.LIST_OFFSETS, 0): self._list_offsets_v0,
            (kw.METADATA, 0): self._metadata_v0,
            (kw.FIND_COORDINATOR, 0): self._find_coordinator_v0,
            (kw.OFFSET_COMMIT, 2): self._offset_commit_v2,
            (kw.OFFSET_FETCH, 1): self._offset_fetch_v1,
        }
        handler = handlers.get((header.api_key, header.api_version))
        if handler is None:
            # Protocol-correct refusal (error body shapes vary per API,
            # so close after a header-only error frame).
            raise kw.KafkaWireError(
                f"unsupported api {header.api_key} v{header.api_version}"
            )
        return handler(r)

    def _metadata_v0(self, r: kw.Reader) -> bytes:
        topics = r.array(r.string)
        with self._lock:
            if not topics:
                topics = list(self._topics)
            for t in topics:
                self._topics.setdefault(
                    t, [_PartitionLog() for _ in range(self.num_partitions)]
                )  # auto-create, the dev-broker default
            out = kw.enc_array(
                [(0, self.host, self.port)],
                lambda b: kw.enc_int32(b[0]) + kw.enc_string(b[1]) + kw.enc_int32(b[2]),
            )

            def enc_partition(p):
                return (
                    kw.enc_int16(kw.NO_ERROR)
                    + kw.enc_int32(p)
                    + kw.enc_int32(0)  # leader = node 0
                    + kw.enc_array([0], kw.enc_int32)  # replicas
                    + kw.enc_array([0], kw.enc_int32)  # isr
                )

            def enc_topic(t):
                parts = range(len(self._topics[t]))
                return (
                    kw.enc_int16(kw.NO_ERROR)
                    + kw.enc_string(t)
                    + kw.enc_array(list(parts), enc_partition)
                )

            out += kw.enc_array(topics, enc_topic)
        return out

    def _produce_v0(self, r: kw.Reader) -> bytes:
        r.int16()  # required_acks (always ack here)
        r.int32()  # timeout

        def read_partition():
            partition = r.int32()
            size = r.int32()
            mset = r.buf[r.pos : r.pos + size]
            r.pos += size
            return partition, mset

        def read_topic():
            name = r.string()
            return name, r.array(read_partition)

        topics = r.array(read_topic)
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                self._topics.setdefault(
                    name, [_PartitionLog() for _ in range(self.num_partitions)]
                )
                resp_parts = []
                for partition, mset in parts:
                    if partition >= len(self._topics[name]):
                        resp_parts.append(
                            (partition, kw.UNKNOWN_TOPIC_OR_PARTITION, -1)
                        )
                        continue
                    log = self._topics[name][partition]
                    base = log.high_watermark
                    for msg in kw.decode_message_set(mset):
                        log.messages.append(
                            StoredMessage(msg.key, msg.value, (), 0)
                        )
                    resp_parts.append((partition, kw.NO_ERROR, base))
                resp_topics.append((name, resp_parts))
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1],
                lambda p: kw.enc_int32(p[0]) + kw.enc_int16(p[1]) + kw.enc_int64(p[2]),
            ),
        )

    def _produce_v3(self, r: kw.Reader) -> bytes:
        """Produce v3: transactional_id + v2 RecordBatch payloads (the
        modern minimum — Kafka ≥3.0 accepts nothing older). Headers
        survive into the log."""
        r.string()  # transactional_id (nullable; transactions unsupported)
        r.int16()  # required_acks
        r.int32()  # timeout

        def read_partition():
            partition = r.int32()
            size = r.int32()
            batches = r.buf[r.pos : r.pos + size]
            r.pos += size
            return partition, batches

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                self._topics.setdefault(
                    name, [_PartitionLog() for _ in range(self.num_partitions)]
                )
                resp_parts = []
                for partition, batches in parts:
                    if partition >= len(self._topics[name]):
                        resp_parts.append(
                            (partition, kw.UNKNOWN_TOPIC_OR_PARTITION, -1)
                        )
                        continue
                    log = self._topics[name][partition]
                    base = log.high_watermark
                    for rec in kw.decode_record_batches(batches):
                        log.messages.append(
                            StoredMessage(
                                rec.key, rec.value, rec.headers,
                                rec.timestamp_ms,
                            )
                        )
                    resp_parts.append((partition, kw.NO_ERROR, base))
                resp_topics.append((name, resp_parts))
        # v3 partition response carries log_append_time (-1: CREATE_TIME
        # logs); throttle_time_ms trails the response.
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1],
                lambda p: kw.enc_int32(p[0]) + kw.enc_int16(p[1])
                + kw.enc_int64(p[2]) + kw.enc_int64(-1),
            ),
        ) + kw.enc_int32(0)

    def _fetch_v0(self, r: kw.Reader) -> bytes:
        r.int32()  # replica_id
        r.int32()  # max_wait_ms (no long-poll in the test double)
        r.int32()  # min_bytes

        def read_partition():
            return r.int32(), r.int64(), r.int32()  # partition, offset, max_bytes

        def read_topic():
            return r.string(), r.array(read_partition)

        topics = r.array(read_topic)
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                logs = self._topics.get(name)
                resp_parts = []
                for partition, offset, max_bytes in parts:
                    if logs is None or partition >= len(logs):
                        resp_parts.append(
                            (partition, kw.UNKNOWN_TOPIC_OR_PARTITION, -1, b"")
                        )
                        continue
                    log = logs[partition]
                    hw = log.high_watermark
                    if offset > hw or offset < 0:
                        resp_parts.append(
                            (partition, kw.OFFSET_OUT_OF_RANGE, hw, b"")
                        )
                        continue
                    mset = b""
                    pos = offset
                    while pos < hw and len(mset) < max_bytes:
                        msg = log.messages[pos]
                        # v0 fetch serves magic-0 messages: headers have
                        # no slot in that format and are dropped.
                        mset += kw.encode_message_set(
                            [(msg.key, msg.value)], base_offset=pos
                        )
                        pos += 1
                    resp_parts.append((partition, kw.NO_ERROR, hw, mset))
                resp_topics.append((name, resp_parts))
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1],
                lambda p: kw.enc_int32(p[0])
                + kw.enc_int16(p[1])
                + kw.enc_int64(p[2])
                + kw.enc_int32(len(p[3]))
                + p[3],
            ),
        )

    def _fetch_v4(self, r: kw.Reader) -> bytes:
        """Fetch v4: isolation level + v2 RecordBatch record sets (the
        modern minimum), headers intact."""
        r.int32()  # replica_id
        r.int32()  # max_wait_ms (no long-poll in the test double)
        r.int32()  # min_bytes
        r.int32()  # max_bytes (whole response; per-partition cap below)
        r.int8()  # isolation_level (no transactions: read_uncommitted)

        def read_partition():
            return r.int32(), r.int64(), r.int32()

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                logs = self._topics.get(name)
                resp_parts = []
                for partition, offset, max_bytes in parts:
                    if logs is None or partition >= len(logs):
                        resp_parts.append(
                            (partition, kw.UNKNOWN_TOPIC_OR_PARTITION, -1, b"")
                        )
                        continue
                    log = logs[partition]
                    hw = log.high_watermark
                    if offset > hw or offset < 0:
                        resp_parts.append(
                            (partition, kw.OFFSET_OUT_OF_RANGE, hw, b"")
                        )
                        continue
                    # One batch per stored message keeps the cut-at-
                    # byte-limit semantics identical to the v0 path.
                    batches = b""
                    pos = offset
                    while pos < hw and len(batches) < max_bytes:
                        msg = log.messages[pos]
                        batches += kw.encode_record_batch(
                            [(msg.key, msg.value, msg.headers)],
                            base_offset=pos,
                            base_timestamp_ms=msg.timestamp_ms,
                        )
                        pos += 1
                    resp_parts.append((partition, kw.NO_ERROR, hw, batches))
                resp_topics.append((name, resp_parts))

        def enc_partition(p):
            partition, error, hw, batches = p
            return (
                kw.enc_int32(partition)
                + kw.enc_int16(error)
                + kw.enc_int64(hw)
                + kw.enc_int64(hw)  # last_stable_offset (no txns)
                + kw.enc_int32(0)  # aborted_transactions: none
                + kw.enc_int32(len(batches))
                + batches
            )

        return kw.enc_int32(0) + kw.enc_array(  # throttle_time_ms first
            resp_topics,
            lambda t: kw.enc_string(t[0]) + kw.enc_array(t[1], enc_partition),
        )

    def _list_offsets_v0(self, r: kw.Reader) -> bytes:
        r.int32()  # replica_id

        def read_partition():
            return r.int32(), r.int64(), r.int32()  # partition, ts, max_offsets

        def read_topic():
            return r.string(), r.array(read_partition)

        topics = r.array(read_topic)
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                logs = self._topics.get(name)
                resp_parts = []
                for partition, ts, _max_offsets in parts:
                    if logs is None or partition >= len(logs):
                        resp_parts.append(
                            (partition, kw.UNKNOWN_TOPIC_OR_PARTITION, [])
                        )
                        continue
                    hw = logs[partition].high_watermark
                    # -1 = latest, -2 = earliest (log start is always 0
                    # here; the double never truncates).
                    offsets = [hw] if ts == -1 else [0]
                    resp_parts.append((partition, kw.NO_ERROR, offsets))
                resp_topics.append((name, resp_parts))
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1],
                lambda p: kw.enc_int32(p[0])
                + kw.enc_int16(p[1])
                + kw.enc_array(p[2], kw.enc_int64),
            ),
        )

    def _find_coordinator_v0(self, r: kw.Reader) -> bytes:
        r.string()  # group id — this node coordinates every group
        return (
            kw.enc_int16(kw.NO_ERROR)
            + kw.enc_int32(0)
            + kw.enc_string(self.host)
            + kw.enc_int32(self.port)
        )

    def _offset_commit_v2(self, r: kw.Reader) -> bytes:
        group = r.string()
        r.int32()  # generation (-1: simple consumer)
        r.string()  # member id
        r.int64()  # retention

        def read_partition():
            partition = r.int32()
            offset = r.int64()
            metadata = r.string()  # stored + served back (epoch tags)
            return partition, offset, metadata

        def read_topic():
            return r.string(), r.array(read_partition)

        topics = r.array(read_topic)
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                resp_parts = []
                for partition, offset, metadata in parts:
                    self._group_offsets[(group, name, partition)] = offset
                    self._group_meta[(group, name, partition)] = metadata or ""
                    resp_parts.append((partition, kw.NO_ERROR))
                resp_topics.append((name, resp_parts))
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1], lambda p: kw.enc_int32(p[0]) + kw.enc_int16(p[1])
            ),
        )

    def _offset_fetch_v1(self, r: kw.Reader) -> bytes:
        group = r.string()

        def read_topic():
            return r.string(), r.array(r.int32)

        topics = r.array(read_topic)
        resp_topics = []
        with self._lock:
            for name, parts in topics:
                resp_parts = []
                for partition in parts:
                    offset = self._group_offsets.get((group, name, partition), -1)
                    meta = self._group_meta.get((group, name, partition), "")
                    resp_parts.append((partition, offset, meta))
                resp_topics.append((name, resp_parts))
        return kw.enc_array(
            resp_topics,
            lambda t: kw.enc_string(t[0])
            + kw.enc_array(
                t[1],
                lambda p: kw.enc_int32(p[0])
                + kw.enc_int64(p[1])
                + kw.enc_string(p[2])
                + kw.enc_int16(kw.NO_ERROR),
            ),
        )
