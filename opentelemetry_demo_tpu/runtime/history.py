"""Time-travel tier: frame-native history store + retention ladder.

The query plane (runtime.query) answers "now"; production
observability needs "last Tuesday 3am" and a way to regression-test
detection quality against RECORDED incidents instead of synthetic-only
drives. The verified columnar frame (runtime.frame) makes both nearly
free: history is append + monoid merge + seek, with the same CRC and
fencing guarantees the live path already enforces.

Three pieces:

- :class:`HistoryStore` — an mmap-able on-disk **segment log** of v2
  frames with a header-only time index. Each record is a fixed
  36+4-byte header (kind, rung, epoch, time bounds, frame length,
  header CRC32C) followed by one ``runtime.frame`` blob, so index
  builds read headers only (seek past every payload) and a range read
  is seek + memcpy + :func:`frame.decode` + merge — no re-encode
  anywhere. Segments seal by flush + fsync + ``os.replace`` (the
  checkpoint crash-safety discipline); the writer is **epoch-fenced**:
  every append checks the process fence and stamps its epoch, and
  opening a store observes the largest epoch already on disk — a
  resurrected stale primary sharing the volume cannot append (the
  three-path fencing story gains its fourth path).

- :class:`HistoryWriter` — the supervised compaction thread. Each tick
  it snapshots state through the SAME dispatch-lock helper replication
  uses (reads never touch live buffers) and, when the shortest
  tumbling window has rotated, folds the expiring bank into a
  **retention ladder**: rung 0 records each completed shortest-window
  bank; rung k folds rung k-1 records by the existing sketch monoids —
  HLL max-merge, CMS add-merge, span totals add — while the EWMA/CUSUM
  heads keep last-value-per-rung (they are decayed statistics, not
  monoids). Folding N fine records into one coarse record is
  bit-identical to having merged the same deltas directly at the
  coarse resolution (tests/test_history.py pins it property-style).
  Optionally (``ANOMALY_HISTORY_SPANS``) the writer also captures
  every dispatched span batch as a frame — the replay corpus
  ``runtime.replaybench`` re-feeds through the real pipeline.

- :class:`HistoryReader` — the query plane's range backend:
  ``range_state(t_from, t_to)`` picks the finest rung that covers the
  range in a bounded record count, merges the in-range records into
  one (arrays, meta) pair shaped for runtime.query's pure-numpy read
  functions, and collects the anomaly events / top-k candidates the
  record metas carried. A corrupt record is QUARANTINED with evidence
  (``anomaly_frame_corrupt_total{hop=history}``) and skipped — a range
  query never crashes on bit rot, and live state is never touched
  (the reader is disk-only by construction).

Corruption contract (the frame module's, applied to a log): the frame
trailer/column CRCs catch payload rot (skip one record); a record
HEADER that fails its own CRC means the scan cannot resync, so the
remainder of that segment is quarantined and scanning stops there — a
torn tail from a crash looks identical and is simply where the log
ends.
"""

from __future__ import annotations

import logging
import math
import os
import struct
import threading
import time
from collections import deque
from typing import Callable, NamedTuple

import numpy as np

from . import frame

log = logging.getLogger(__name__)

RECORD_MAGIC = b"OTDH"
KIND_BANK = 0    # one retention-ladder rung record (sketch banks + heads)
KIND_SPANS = 1   # one dispatched span batch (the replay corpus)
KIND_EXPLAIN = 2  # one evidence bundle (runtime.provenance, meta-only frame)

# Record header: magic, kind, rung, reserved, epoch, t_start, t_end,
# frame length — then a CRC32C over those 36 bytes. The header is the
# TIME INDEX: building it never touches a frame payload.
_REC = struct.Struct("<4sBBHQddI")
_REC_CRC = struct.Struct("<I")
HEADER_SIZE = _REC.size + _REC_CRC.size  # 40

_OPEN_SUFFIX = ".open"
_SEG_SUFFIX = ".seg"

# Bounded index scan cache entries per store (segments are few; this
# caps pathological dirs, not normal operation).
_SCAN_CACHE_MAX = 512

# Default cap on records merged per range answer: the finest rung whose
# record count over [from, to] stays under this is chosen, so a month
# query reads hundreds of 1h records instead of millions of 1s ones.
RANGE_MAX_RECORDS = 720

# Fold semantics per state array (the DetectorState names): sketch
# banks merge by their monoids, span totals add, everything else —
# EWMA/CUSUM heads, observation counters, step_idx — is
# last-value-per-rung (decayed statistics have no merge; the newest
# value IS the rung's value).
MERGE_MAX = frozenset({"hll_bank"})
MERGE_ADD = frozenset({"cms_bank", "span_total"})

# The state arrays a bank record carries beside the two banks.
HEAD_ARRAYS = (
    "lat_mean", "lat_var", "err_mean", "rate_mean", "rate_var",
    "card_mean", "card_var", "obs_batches", "obs_windows", "cusum",
    "step_idx",
)

# The span-capture column set (tensorize.SpanColumns fields): enough to
# rebuild the exact batch the pipeline dispatched.
SPAN_CAPTURE_COLUMNS = ("svc", "lat_us", "is_error", "trace_key", "attr_crc")


class HistoryRecord(NamedTuple):
    """One time-index entry: everything the header knows, plus where
    the frame bytes live."""

    path: str
    offset: int  # of the frame payload
    length: int  # frame payload bytes
    kind: int
    rung: int
    epoch: int
    t_start: float
    t_end: float


def merge_record_arrays(acc: dict | None, arrays: dict) -> dict:
    """Fold one record's arrays into an accumulator (monoid merge).

    HLL registers max-merge, CMS counters and span totals add —
    bit-identical to the device merges (integer monoids; pinned by the
    ladder property test) — and every head/counter array replaces
    (last value wins). ``acc=None`` starts a fresh accumulator with
    copies, so record views (possibly into an mmap) never escape."""
    if acc is None:
        return {k: np.array(v, copy=True) for k, v in arrays.items()}
    for k, v in arrays.items():
        if k in MERGE_MAX and k in acc:
            np.maximum(acc[k], v, out=acc[k])
        elif k in MERGE_ADD and k in acc:
            # In place: acc is already a private copy, and a range read
            # folds up to RANGE_MAX_RECORDS banks — one allocation per
            # record would dominate the read-latency histogram.
            np.add(acc[k], v, out=acc[k])
        else:
            acc[k] = np.array(v, copy=True)
    return acc


class HistoryStore:
    """The on-disk segment log: append, seal, scan, read, retire.

    One instance owns a directory. Writers and readers share it (the
    reader side is pure seeks over sealed + active segments); cross-
    process safety comes from the epoch fence, not file locks — the
    same single-writer-per-epoch discipline as the checkpoint volume.
    """

    def __init__(
        self,
        directory: str,
        segment_bytes: int = 8 << 20,
        fence=None,
        retention_s: tuple[float, ...] = (),
    ):
        self.directory = directory
        self.segment_bytes = int(segment_bytes)
        self.fence = fence
        self.retention_s = tuple(float(r) for r in retention_s)
        self._lock = threading.Lock()
        self._active: dict[tuple[int, int], tuple[str, object, int]] = {}
        self._next_seq = 0
        # Counters the daemon exports (monotonic; read via stats()).
        self.appends = 0
        self.sealed = 0
        self.frames_corrupt = 0
        self.segments_retired = 0
        self._scan_cache: dict[str, tuple[int, list[HistoryRecord]]] = {}
        os.makedirs(directory, exist_ok=True)
        self._recover()
        if self.fence is not None:
            observed = self.max_epoch()
            if observed is not None:
                # The log is fencing evidence like the checkpoint
                # volume: records stamped by a later epoch outrank this
                # process before its first append.
                self.fence.observe(observed)

    # -- file naming ----------------------------------------------------

    @staticmethod
    def _basename(kind: int, rung: int, seq: int) -> str:
        prefix = (
            "b" if kind == KIND_BANK
            else "e" if kind == KIND_EXPLAIN
            else "s"
        )
        return f"{prefix}{rung}-{seq:010d}"

    def _recover(self) -> None:
        """Adopt an existing directory: seal stray ``.open`` segments a
        crashed writer left (their torn tail, if any, is where the
        scan stops) and resume the segment sequence past everything
        present."""
        max_seq = -1
        for name in os.listdir(self.directory):
            stem, ext = os.path.splitext(name)
            if ext not in (_OPEN_SUFFIX, _SEG_SUFFIX):
                continue
            try:
                max_seq = max(max_seq, int(stem.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                continue
            if ext == _OPEN_SUFFIX:
                src = os.path.join(self.directory, name)
                os.replace(src, os.path.join(self.directory, stem + _SEG_SUFFIX))
        self._next_seq = max_seq + 1

    def _segment_files(self) -> list[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.endswith((_SEG_SUFFIX, _OPEN_SUFFIX))
            )
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    # -- append side ----------------------------------------------------

    def append(
        self,
        kind: int,
        rung: int,
        t_start: float,
        t_end: float,
        payload: bytes,
    ) -> None:
        """Append one frame blob under a header; epoch-fenced.

        Raises :class:`checkpoint.StaleEpochError` (via the fence) when
        a newer epoch has been observed on any channel — a stale
        ex-primary cannot extend the log its successor now owns."""
        epoch = 0
        if self.fence is not None:
            self.fence.check(path="history")
            epoch = int(self.fence.epoch)
        header = _REC.pack(
            RECORD_MAGIC, kind, rung, 0, epoch,
            float(t_start), float(t_end), len(payload),
        )
        header += _REC_CRC.pack(frame.crc32c(header))
        with self._lock:
            key = (kind, rung)
            entry = self._active.get(key)
            if entry is None:
                path = os.path.join(
                    self.directory,
                    self._basename(kind, rung, self._next_seq) + _OPEN_SUFFIX,
                )
                self._next_seq += 1
                entry = (path, open(path, "ab"), 0)
            path, fh, written = entry
            fh.write(header)
            fh.write(payload)
            fh.flush()  # visible to readers; durable only at seal
            written += len(header) + len(payload)
            self.appends += 1
            self._scan_cache.pop(path, None)
            if written >= self.segment_bytes:
                self._seal_locked(key, (path, fh, written))
            else:
                self._active[key] = (path, fh, written)

    def _seal_locked(self, key, entry) -> None:
        path, fh, _written = entry
        fh.flush()
        os.fsync(fh.fileno())
        fh.close()
        sealed = path[: -len(_OPEN_SUFFIX)] + _SEG_SUFFIX
        os.replace(path, sealed)
        self._scan_cache.pop(path, None)
        self.sealed += 1
        self._active.pop(key, None)

    def seal_all(self) -> None:
        """fsync + rename every active segment (shutdown / barrier)."""
        with self._lock:
            for key, entry in list(self._active.items()):
                self._seal_locked(key, entry)

    def close(self) -> None:
        self.seal_all()

    # -- index / read side ----------------------------------------------

    def _scan(self, path: str) -> list[HistoryRecord]:
        """Header-only index of one segment (cached by file size).

        Stops at a torn tail silently; a header whose own CRC fails
        mid-file is corruption — the remainder cannot be resynced, so
        it is quarantined with evidence and the scan ends there."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return []
        cached = self._scan_cache.get(path)
        if cached is not None and cached[0] == size:
            return cached[1]
        records: list[HistoryRecord] = []
        try:
            with open(path, "rb") as f:
                pos = 0
                while pos + HEADER_SIZE <= size:
                    raw = f.read(HEADER_SIZE)
                    if len(raw) < HEADER_SIZE:
                        break  # torn tail: the log simply ends here
                    header, stored = raw[: _REC.size], raw[_REC.size:]
                    (magic, kind, rung, _resv, epoch, t_start, t_end,
                     flen) = _REC.unpack(header)
                    if (
                        magic != RECORD_MAGIC
                        or _REC_CRC.unpack(stored)[0] != frame.crc32c(header)
                    ):
                        self.frames_corrupt += 1
                        rest = header + stored + f.read()
                        frame.quarantine(rest, hop="history")
                        log.error(
                            "history segment %s: unresyncable record "
                            "header at %d — remainder quarantined",
                            path, pos,
                        )
                        break
                    if pos + HEADER_SIZE + flen > size:
                        break  # record body torn mid-write: end of log
                    records.append(HistoryRecord(
                        path, pos + HEADER_SIZE, flen, kind, rung,
                        epoch, t_start, t_end,
                    ))
                    f.seek(flen, os.SEEK_CUR)
                    pos += HEADER_SIZE + flen
        except OSError:
            return []
        if len(self._scan_cache) >= _SCAN_CACHE_MAX:
            self._scan_cache.clear()
        self._scan_cache[path] = (size, records)
        return records

    def records(
        self,
        kind: int = KIND_BANK,
        rung: int | None = None,
        t_from: float | None = None,
        t_to: float | None = None,
    ) -> list[HistoryRecord]:
        """Time-index lookup: matching records across all segments, in
        append (= time) order — built from headers only."""
        out: list[HistoryRecord] = []
        for path in self._segment_files():
            for rec in self._scan(path):
                if rec.kind != kind:
                    continue
                if rung is not None and rec.rung != rung:
                    continue
                if t_to is not None and rec.t_start > t_to:
                    continue
                if t_from is not None and rec.t_end < t_from:
                    continue
                out.append(rec)
        out.sort(key=lambda r: (r.t_start, r.t_end))
        return out

    def read_frame(self, rec: HistoryRecord) -> frame.Frame:
        """seek + memcpy + verified decode of ONE record's frame.

        A failed trailer/column CRC counts, quarantines the bytes with
        evidence, and re-raises :class:`frame.FrameCorrupt` — callers
        skip the record; nothing here can touch live state."""
        with open(rec.path, "rb") as f:
            f.seek(rec.offset)
            buf = f.read(rec.length)
        try:
            return frame.decode(buf)
        except frame.FrameCorrupt:
            self.frames_corrupt += 1
            frame.quarantine(buf, hop="history")
            raise

    def read_meta(self, rec: HistoryRecord) -> dict:
        """Meta-only read of one record's frame (seek + header JSON —
        frame.peek_stream_meta, never the columns): how annotation/
        anomaly range queries walk hours of records without decoding
        megabytes of sketch banks per record. Unreadable = {} (peek
        callers treat any failure as 'no evidence')."""
        try:
            with open(rec.path, "rb") as f:
                f.seek(rec.offset)
                return frame.peek_stream_meta(f).meta
        except (OSError, frame.FrameError):
            return {}

    def max_epoch(self) -> int | None:
        """Largest epoch stamped on any record (fencing evidence), or
        None for an empty log — a header-only scan."""
        best: int | None = None
        for path in self._segment_files():
            for rec in self._scan(path):
                best = rec.epoch if best is None else max(best, rec.epoch)
        return best

    # -- retention ------------------------------------------------------

    def enforce_retention(self, now: float | None = None) -> int:
        """Delete sealed segments every record of which has aged past
        its rung's cap (span-capture records share rung 0's cap).
        Returns the number of files retired."""
        if not self.retention_s:
            return 0
        now = time.time() if now is None else now
        retired = 0
        with self._lock:
            active_paths = {e[0] for e in self._active.values()}
        for path in self._segment_files():
            if path.endswith(_OPEN_SUFFIX) or path in active_paths:
                continue
            recs = self._scan(path)
            if not recs:
                continue
            expired = True
            for rec in recs:
                idx = rec.rung if rec.kind == KIND_BANK else 0
                cap = self.retention_s[min(idx, len(self.retention_s) - 1)]
                if rec.t_end >= now - cap:
                    expired = False
                    break
            if expired:
                try:
                    os.remove(path)
                except OSError:
                    continue
                self._scan_cache.pop(path, None)
                self.segments_retired += 1
                retired += 1
        return retired

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        files = self._segment_files()
        total = 0
        oldest: float | None = None
        for path in files:
            try:
                total += os.path.getsize(path)
            except OSError:
                continue
            recs = self._scan(path)
            if recs:
                first = recs[0].t_start
                oldest = first if oldest is None else min(oldest, first)
        return {
            "segments": len(files),
            "bytes": total,
            "oldest_t": oldest,
            "appends": self.appends,
            "sealed": self.sealed,
            "frames_corrupt": self.frames_corrupt,
            "segments_retired": self.segments_retired,
        }


class HistoryWriter:
    """The supervised compaction thread: window banks → ladder → log.

    ``snapshot_fn() -> (arrays, meta)`` is the daemon's replication
    snapshot helper — state copies are taken under the pipeline
    dispatch lock, never here, so the writer can never race a donated
    buffer. Rung 0 captures each completed shortest-window bank as it
    expires (detected by the window clock's boundary advancing between
    ticks); rung k folds ``rungs[k]/rungs[k-1]`` child records into
    one parent by :func:`merge_record_arrays`. The writer is the ONLY
    frame producer outside the live path (sanitycheck pins it).
    """

    def __init__(
        self,
        store: HistoryStore,
        snapshot_fn: Callable[[], tuple[dict, dict]],
        rungs: tuple[float, ...] = (1.0, 60.0, 3600.0),
        interval_s: float = 0.5,
        now_fn: Callable[[], float] = time.time,
        capture_spans: bool = False,
        span_queue_max: int = 64,
        retention_every: int = 60,
        span_sample: dict[str, float] | None = None,
        service_names_fn: Callable[[], list[str]] | None = None,
    ):
        self.store = store
        self._snapshot_fn = snapshot_fn
        self.rungs = tuple(float(r) for r in rungs)
        self.interval_s = float(interval_s)
        self.now_fn = now_fn
        self.capture_spans = bool(capture_spans)
        # Per-service capture policy ({name: rate, '*': default-rate};
        # None/{'*': 1.0} = record everything, today's behavior). Set
        # at boot from ANOMALY_HISTORY_SPANS' map form and re-published
        # live by the remediation sampling actuator (flagged service →
        # 1.0) — swapped atomically under the span lock.
        self._span_sample = dict(span_sample) if span_sample else None
        self._service_names_fn = service_names_fn
        self._span_queue: deque = deque(maxlen=max(int(span_queue_max), 1))
        self._span_lock = threading.Lock()
        self.spans_dropped = 0
        self.spans_recorded = 0
        self.spans_sampled_out = 0
        # Evidence bundles (runtime.provenance) awaiting persistence:
        # same bounded drop-oldest handoff as the span queue — the
        # harvester enqueues a JSON-able dict, the compaction thread
        # encodes it as a META-ONLY frame (no columns) so ranged
        # explain reads stay header-only. Flags are rare; the span
        # queue's cap is plenty.
        self._explain_queue: deque = deque(
            maxlen=max(int(span_queue_max), 1)
        )
        self.explains_recorded = 0
        self.explains_dropped = 0
        # Ladder state: per coarse rung, an (accumulator, t_start,
        # child count) triple; rung 0 feeds from the window clock.
        self._acc: list[dict | None] = [None] * len(self.rungs)
        self._acc_start: list[float | None] = [None] * len(self.rungs)
        self._acc_children: list[int] = [0] * len(self.rungs)
        self._last_boundary: float | None = None
        self._clock_offset: float | None = None  # window clock → wall
        self._last_anomaly_t = 0.0
        self.compactions = 0
        self.windows_recorded = 0
        self.windows_missed = 0
        self.evictions_recorded = 0
        self.fenced = False
        self._ticks = 0
        self._retention_every = max(int(retention_every), 1)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Spawn the compaction thread (idempotent while it lives)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="history-writer", daemon=True
        )
        self._thread.start()

    def alive(self) -> bool:
        return self._thread is None or self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # Final drain outside the dead thread, then seal: shutdown must
        # not strand captured batches in the queue.
        try:
            self.tick()
        except Exception:  # noqa: BLE001 — teardown races (a snapshot
            pass  # source mid-stop) must not block close
        self.store.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — one bad tick (disk
                # hiccup, snapshot raced teardown) is a skipped
                # compaction, never a dead thread; fencing sets its own
                # flag below and real crash loops surface through the
                # supervisor's probe on the daemon side.
                log.exception("history compaction tick failed")

    # -- span capture (the replay corpus) --------------------------------

    def set_span_sample(self, policy: dict[str, float] | None) -> None:
        """Swap the per-service capture policy live (the remediation
        sampling actuator's publish target; any thread)."""
        with self._span_lock:
            self._span_sample = dict(policy) if policy else None

    def span_sample_policy(self) -> dict[str, float] | None:
        with self._span_lock:
            return dict(self._span_sample) if self._span_sample else None

    def _sample_mask(self, cols, policy: dict[str, float]):
        """Per-row keep mask under the per-service policy. Rows sample
        DETERMINISTICALLY by trace key (splitmix64 threshold — the
        selftrace head-sampling trick), so a replayed recording and a
        rerun recording keep the same spans, and all spans of one
        trace land or drop together."""
        svc = np.asarray(cols.svc)
        names = (
            self._service_names_fn()
            if self._service_names_fn is not None else []
        )
        default = float(policy.get("*", 0.0))
        rates = np.full(max(len(names), int(svc.max(initial=-1)) + 1, 1),
                        default, np.float64)
        for i, name in enumerate(names[: rates.shape[0]]):
            rates[i] = float(policy.get(name, default))
        row_rate = rates[np.clip(svc, 0, rates.shape[0] - 1)]
        # splitmix64 finalizer over the trace key → uniform in [0, 2^64).
        x = (np.asarray(cols.trace_key, np.uint64)
             + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
        threshold = (np.clip(row_rate, 0.0, 1.0) * float(2**64)).astype(
            np.float64
        )
        return x.astype(np.float64) < threshold

    def capture(self, cols, t_batch: float) -> None:
        """Remember one dispatched batch (pump thread; bounded, never
        blocks). Columns are COPIED here: in the zero-copy ingest path
        they are views into pooled decode scratch that recycles the
        moment the pipeline drops them. A per-service sample policy
        (``set_span_sample``) keeps only the sampled rows — the
        mitigation-drill recorder that skips the quiet firehose."""
        if not self.capture_spans:
            return
        with self._span_lock:
            policy = self._span_sample
        mask = None
        if policy is not None and policy != {"*": 1.0}:
            mask = self._sample_mask(cols, policy)
            if not mask.any():
                with self._span_lock:
                    self.spans_sampled_out += int(mask.shape[0])
                return
        arrays = {}
        for name in SPAN_CAPTURE_COLUMNS:
            col = np.asarray(getattr(cols, name))
            arrays[name] = (
                np.array(col[mask], copy=True) if mask is not None
                else np.array(col, copy=True)
            )
        with self._span_lock:
            if mask is not None:
                self.spans_sampled_out += int(
                    mask.shape[0] - mask.sum()
                )
            if len(self._span_queue) == self._span_queue.maxlen:
                self.spans_dropped += 1
            self._span_queue.append((arrays, float(t_batch)))

    def _drain_spans(self, now: float) -> None:
        while True:
            with self._span_lock:
                if not self._span_queue:
                    return
                arrays, t_batch = self._span_queue.popleft()
            blob = frame.encode(arrays, meta={"t_batch": t_batch})
            self.store.append(KIND_SPANS, 0, now, now, blob)
            self.spans_recorded += 1

    # -- evidence-bundle capture (runtime.provenance) --------------------

    def capture_explain(self, bundle: dict) -> None:
        """Remember one evidence bundle (harvester thread; bounded,
        never blocks). The bundle is already a plain JSON-able dict —
        no copy-out needed, it is never mutated after build."""
        with self._span_lock:
            if len(self._explain_queue) == self._explain_queue.maxlen:
                self.explains_dropped += 1
            self._explain_queue.append(bundle)

    def _drain_explains(self, now: float) -> None:
        while True:
            with self._span_lock:
                if not self._explain_queue:
                    return
                bundle = self._explain_queue.popleft()
            # Meta-only frame: the bundle IS the header JSON, so the
            # ranged explain read (read_meta) never decodes columns.
            blob = frame.encode({}, meta=dict(bundle))
            t = float(bundle.get("t") or now)
            self.store.append(KIND_EXPLAIN, 0, t, t, blob)
            self.explains_recorded += 1

    # -- compaction ------------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        """One compaction step (the thread's body; callable directly
        with a virtual clock from tests and replaybench)."""
        from .checkpoint import StaleEpochError

        now = self.now_fn() if now is None else now
        if self.fenced:
            return  # a stale writer stays quiet until restart/redeploy
        try:
            self._drain_spans(now)
            self._drain_explains(now)
            self._tick_banks(now)
        except StaleEpochError as e:
            # Fourth fencing path: the epoch moved past us — stop
            # appending (visibly: anomaly_replication_fenced_total
            # {path=history} counts every refused write).
            self.fenced = True
            log.error("history writer fenced: %s", e)
            return
        self._ticks += 1
        if self._ticks % self._retention_every == 0:
            self.store.enforce_retention(now)

    def _tick_banks(self, now: float) -> None:
        try:
            arrays, meta = self._snapshot_fn()
        except Exception:  # noqa: BLE001 — snapshot source mid-restart:
            return  # skip the tick, the next one retries
        if not arrays:
            return
        t_clock = meta.get("clock_t_prev")
        if t_clock is None:
            return
        w0 = self.rungs[0]
        boundary = math.floor(float(t_clock) / w0) * w0
        if self._last_boundary is None:
            # First observation: remember the phase and the window-
            # clock→wall offset; the current prev bank's provenance is
            # unknown (it may predate this writer), so don't record it.
            self._last_boundary = boundary
            self._clock_offset = now - float(t_clock)
            return
        if boundary <= self._last_boundary:
            return
        missed = int(round((boundary - self._last_boundary) / w0)) - 1
        if missed > 0:
            # Rotations we never saw (a stalled tick, a long GC): the
            # banks for those windows are gone — count, never fake.
            self.windows_missed += missed
        self._last_boundary = boundary
        offset = self._clock_offset if self._clock_offset is not None else 0.0
        t_end = boundary + offset
        t_start = t_end - w0
        record = self._bank_record(arrays)
        rec_meta = self._record_meta(arrays, meta, t_start, t_end)
        self._emit(0, t_start, t_end, record, rec_meta)
        self.windows_recorded += 1

    @staticmethod
    def _bank_record(arrays: dict) -> dict:
        """The rung-record array set from one state snapshot: the
        EXPIRING shortest-window banks (slot [0, 1] — just rotated to
        'previous') plus the head/counter arrays as-of now."""
        record = {
            "hll_bank": np.array(arrays["hll_bank"][0, 1], copy=True),
            "cms_bank": np.array(arrays["cms_bank"][0, 1], copy=True),
            "span_total": np.array(arrays["span_total"][0, 1], copy=True),
        }
        for name in HEAD_ARRAYS:
            if name in arrays:
                record[name] = np.array(arrays[name], copy=True)
        return record

    def _record_meta(
        self, arrays: dict, meta: dict, t_start: float, t_end: float
    ) -> dict:
        """JSON meta block for a rung record: identity (seq/epoch via
        the header too — these ride where peek_meta sees them), the
        intern table + config the query fns need, and the query-plane
        evidence captured during this window (anomaly events new since
        the last record, the current top-k candidate rings)."""
        q = meta.get("query") or {}
        events = [
            dict(ev) for ev in (q.get("anomalies") or [])
            if float(ev.get("t") or 0.0) > self._last_anomaly_t
        ]
        if events:
            self._last_anomaly_t = max(float(e["t"]) for e in events)
        return {
            "seq": int(np.asarray(arrays.get("step_idx", 0))),
            "t_start": t_start,
            "t_end": t_end,
            "service_names": list(meta.get("service_names") or []),
            "config": list(meta.get("config") or []),
            # Keyspace generation at capture time: range reads refuse
            # to merge records across an eviction sweep's id recycling
            # (the drift-refusal contract, runtime/keyspace.py).
            "generation": int(meta.get("generation") or 0),
            "query": {
                "anomalies": events,
                "hh_candidates": dict(q.get("hh_candidates") or {}),
            },
        }

    # -- eviction folds (runtime/keyspace.py) ----------------------------

    def record_eviction(
        self, record: dict, rec_meta: dict, now: float | None = None
    ) -> None:
        """Append one eviction fold record: the evicted keys' final
        head rows + in-progress window bank, captured by the keyspace
        evictor UNDER the dispatch lock before it zeroed them. Rung 0,
        appended directly (no upward cascade — the ladder accumulators
        count window children, and this record is not a window). The
        record carries the PRE-bump generation: its rows are
        attributed under the OLD id assignment, exactly the records it
        may merge with."""
        from .checkpoint import StaleEpochError

        if self.fenced:
            return
        now = self.now_fn() if now is None else now
        t_start = now - self.rungs[0]
        blob = frame.encode(
            record,
            meta=dict(rec_meta, rung=0, t_start=t_start, t_end=now),
        )
        try:
            self.store.append(KIND_BANK, 0, t_start, now, blob)
        except StaleEpochError as e:
            self.fenced = True
            log.error("history writer fenced: %s", e)
            return
        self.evictions_recorded += 1

    def _emit(
        self, rung_idx: int, t_start: float, t_end: float,
        record: dict, rec_meta: dict,
    ) -> None:
        """Append one rung record, then fold it upward: when a coarse
        rung's accumulator has absorbed a full span of children it
        emits its own record and cascades."""
        blob = frame.encode(
            record,
            meta=dict(
                rec_meta, rung=rung_idx, t_start=t_start, t_end=t_end
            ),
        )
        self.store.append(KIND_BANK, rung_idx, t_start, t_end, blob)
        parent = rung_idx + 1
        if parent >= len(self.rungs):
            return
        if self._acc[parent] is None:
            self._acc_start[parent] = t_start
            self._acc_children[parent] = 0
        self._acc[parent] = merge_record_arrays(self._acc[parent], record)
        self._acc_children[parent] += 1
        fanout = int(round(self.rungs[parent] / self.rungs[rung_idx]))
        if self._acc_children[parent] >= fanout:
            acc = self._acc[parent]
            start = self._acc_start[parent]
            self._acc[parent] = None
            self.compactions += 1
            self._emit(parent, start, t_end, acc, rec_meta)

    def stats(self) -> dict:
        return {
            "compactions": self.compactions,
            "windows_recorded": self.windows_recorded,
            "windows_missed": self.windows_missed,
            "evictions_recorded": self.evictions_recorded,
            "spans_recorded": self.spans_recorded,
            "spans_dropped": self.spans_dropped,
            "explains_recorded": self.explains_recorded,
            "explains_dropped": self.explains_dropped,
            "fenced": self.fenced,
        }


# DetectorConfig's windows tuple rides positionally in the persisted
# config list (the checkpoint/replication convention runtime.query also
# relies on).
_CFG_WINDOWS = 4


class HistoryReader:
    """Range reads over a :class:`HistoryStore` for the query plane.

    Every answer is (arrays, meta) shaped for runtime.query's pure
    read functions — the SAME numpy path live answers take, so a
    historical top-k and a live top-k are the same arithmetic over
    different banks. Disk-only: no reference to any live object."""

    def __init__(
        self,
        store: HistoryStore,
        rungs: tuple[float, ...] = (1.0, 60.0, 3600.0),
        max_records: int = RANGE_MAX_RECORDS,
    ):
        self.store = store
        self.rungs = tuple(float(r) for r in rungs)
        self.max_records = int(max_records)

    def pick_rung(
        self, t_from: float, t_to: float, resolution: float | None = None
    ) -> int:
        """Finest rung that answers the range in a bounded record
        count (or the rung matching an explicit resolution)."""
        if resolution is not None:
            for i, r in enumerate(self.rungs):
                if r >= float(resolution):
                    return i
            return len(self.rungs) - 1
        span = max(t_to - t_from, 0.0)
        for i, r in enumerate(self.rungs):
            if span / r <= self.max_records:
                return i
        return len(self.rungs) - 1

    def range_state(
        self,
        t_from: float,
        t_to: float,
        resolution: float | None = None,
        generation: int | None = None,
    ) -> tuple[dict, dict] | None:
        """Merged (arrays, meta) over [t_from, t_to], or None when no
        record overlaps. Corrupt records are skipped (counted +
        quarantined by the store) — the merge is over what survives.

        Records are merged within ONE keyspace generation only: an
        eviction sweep recycles intern ids, so two records across a
        generation bump may attribute the same row to different
        services — refused, never mis-merged (the ShardMergeError
        discipline applied to disk). ``generation=None`` merges the
        NEWEST generation in range (header-only pre-scan) and counts
        the rest in ``skipped_generation``."""
        rung_idx = self.pick_rung(t_from, t_to, resolution)
        recs = self.store.records(
            kind=KIND_BANK, rung=rung_idx, t_from=t_from, t_to=t_to
        )
        target_gen = generation
        if target_gen is None:
            for rec in reversed(recs):
                m = self.store.read_meta(rec)
                if m is not None:
                    target_gen = int(m.get("generation") or 0)
                    break
        merged: dict | None = None
        last_meta: dict = {}
        anomalies: list = []
        candidates: dict[str, list] = {}
        skipped = 0
        skipped_gen = 0
        cover_from: float | None = None
        cover_to: float | None = None
        for rec in recs:
            try:
                fr = self.store.read_frame(rec)
            except frame.FrameCorrupt:
                skipped += 1
                continue
            if int(fr.meta.get("generation") or 0) != (target_gen or 0):
                skipped_gen += 1
                continue
            merged = merge_record_arrays(merged, fr.arrays)
            last_meta = fr.meta
            for ev in (fr.meta.get("query") or {}).get("anomalies") or []:
                t = float(ev.get("t") or 0.0)
                if t_from <= t <= t_to:
                    anomalies.append(dict(ev))
            for svc, crcs in (
                (fr.meta.get("query") or {}).get("hh_candidates") or {}
            ).items():
                seen = candidates.setdefault(svc, [])
                for c in crcs:
                    if c not in seen:
                        seen.append(c)
            cover_from = rec.t_start if cover_from is None else min(
                cover_from, rec.t_start
            )
            cover_to = rec.t_end if cover_to is None else max(
                cover_to, rec.t_end
            )
        if merged is None:
            return None
        arrays = self._as_query_arrays(merged)
        span = (
            (cover_to - cover_from)
            if cover_from is not None and cover_to is not None
            else self.rungs[rung_idx]
        )
        native_config = list(last_meta.get("config") or [])
        config = list(native_config)
        if len(config) > _CFG_WINDOWS:
            # The merged bank is ONE window spanning the covered range;
            # the read fns take windows_s from this positional slot.
            # The untouched original rides beside it ("native_config")
            # for answers about the head state, whose window axis keeps
            # the detector's own geometry.
            config[_CFG_WINDOWS] = (float(span),)
        meta = {
            "service_names": list(last_meta.get("service_names") or []),
            "config": config,
            "native_config": native_config,
            "query": {
                "anomalies": anomalies,
                "hh_candidates": candidates,
                "exemplars": {},
            },
            "seq": int(last_meta.get("seq") or 0),
            "generation": int(target_gen or 0),
            "resolution_s": self.rungs[rung_idx],
            "records": len(recs) - skipped - skipped_gen,
            "skipped_corrupt": skipped,
            "skipped_generation": skipped_gen,
            "coverage": [cover_from, cover_to],
        }
        return arrays, meta

    def service_range_state(
        self,
        name: str,
        t_from: float,
        t_to: float,
        resolution: float | None = None,
    ) -> tuple[dict, dict] | None:
        """Merged state for the NEWEST generation that still knows
        ``name`` — the evicted-key query fallback: a key retired from
        the live table answers from the records minted while it owned
        its id (the eviction fold rode in with the same generation, so
        its final head rows are the last-value winners). Header-only
        scans locate the generation; None when no record in range ever
        interned the name."""
        for rung_idx in (
            self.pick_rung(t_from, t_to, resolution), 0
        ):
            found = None
            for rec in reversed(self.store.records(
                kind=KIND_BANK, rung=rung_idx, t_from=t_from, t_to=t_to
            )):
                m = self.store.read_meta(rec)
                if m and name in (m.get("service_names") or []):
                    found = int(m.get("generation") or 0)
                    break
            if found is not None:
                return self.range_state(
                    t_from, t_to,
                    resolution=self.rungs[rung_idx],
                    generation=found,
                )
        return None

    @staticmethod
    def _as_query_arrays(merged: dict) -> dict:
        """Shape a merged record like the live state snapshot the
        query read fns expect: one-window banks in the [W#, 2, ...]
        bank layout (slot 0 = the merged 'current', slot 1 zeroed),
        heads at their native shapes."""
        arrays = dict(merged)
        hll = np.asarray(merged["hll_bank"])
        cms_t = np.asarray(merged["cms_bank"])
        total = np.asarray(merged["span_total"], dtype=np.float32)
        arrays["hll_bank"] = np.stack(
            [hll, np.zeros_like(hll)], axis=0
        )[None]
        arrays["cms_bank"] = np.stack(
            [cms_t, np.zeros_like(cms_t)], axis=0
        )[None]
        arrays["span_total"] = np.asarray(
            [[float(total), 0.0]], dtype=np.float32
        )
        return arrays

    def timeline(
        self,
        t_from: float,
        t_to: float,
        resolution: float | None = None,
    ) -> list[dict]:
        """Per-record datapoints over the range (the Grafana true-range
        backend): one entry per surviving record with its per-service
        HLL estimate and max CUSUM — seek + decode + estimate, live
        state untouched."""
        from ..ops.hll import hll_estimate_np

        rung_idx = self.pick_rung(t_from, t_to, resolution)
        points: list[dict] = []
        for rec in self.store.records(
            kind=KIND_BANK, rung=rung_idx, t_from=t_from, t_to=t_to
        ):
            try:
                fr = self.store.read_frame(rec)
            except frame.FrameCorrupt:
                continue
            est = hll_estimate_np(np.asarray(fr.arrays["hll_bank"]))
            cusum = np.asarray(fr.arrays.get("cusum"))
            points.append({
                "t": rec.t_end,
                "seq": int(fr.meta.get("seq") or 0),
                "card": [float(x) for x in est],
                "cusum_max": (
                    [float(x) for x in cusum.max(axis=1)]
                    if cusum is not None and cusum.ndim == 2 else []
                ),
                "service_names": list(
                    fr.meta.get("service_names") or []
                ),
                "resolution_s": self.rungs[rung_idx],
            })
        return points

    def anomaly_events(
        self, t_from: float, t_to: float
    ) -> tuple[list[dict], list[str]]:
        """(events, service_names) over the range from record META
        blocks alone — header-only reads (peek_stream_meta), no bank
        decode: the /query/anomalies and Grafana annotation range
        backend. Finest rung only (events are recorded once, at
        rung 0; coarser rungs carry the same fold's meta)."""
        events: list[dict] = []
        names: list[str] = []
        for rec in self.store.records(
            kind=KIND_BANK, rung=0, t_from=t_from, t_to=t_to
        ):
            meta = self.store.read_meta(rec)
            if not meta:
                continue
            if meta.get("service_names"):
                names = list(meta["service_names"])
            for ev in (meta.get("query") or {}).get("anomalies") or []:
                t = float(ev.get("t") or 0.0)
                if t_from <= t <= t_to:
                    events.append(dict(ev))
        return events, names

    def explain_events(self, t_from: float, t_to: float) -> list[dict]:
        """Evidence bundles over the range, oldest first — meta-only
        reads over the KIND_EXPLAIN log (the bundle IS the frame's
        header JSON; no columns exist to decode). The ranged
        /query/explain backend, and the restart-survival half of the
        provenance contract: a bundle recorded before a daemon restart
        answers from disk here."""
        bundles: list[dict] = []
        for rec in self.store.records(
            kind=KIND_EXPLAIN, t_from=t_from, t_to=t_to
        ):
            meta = self.store.read_meta(rec)
            if not meta:
                continue
            t = float(meta.get("t") or rec.t_start)
            if t_from <= t <= t_to:
                bundles.append(meta)
        return bundles

    def span_records(
        self,
        from_ts: float | None = None,
        to_ts: float | None = None,
    ) -> list[HistoryRecord]:
        """The explicit "recent window" read API over the span-capture
        log: KIND_SPANS record headers whose [t_start, t_end] overlaps
        [from_ts, to_ts], in log order — a header-only time filter (no
        frame decode), so the shadow pre-flight and other windowed
        consumers stop re-scanning whole segments. Decode each record
        with :meth:`read_span_record`."""
        return self.store.records(
            kind=KIND_SPANS, t_from=from_ts, t_to=to_ts
        )

    def read_span_record(self, rec: HistoryRecord):
        """Decode ONE span-capture record: (arrays, t_batch), or
        (None, None) when corrupt — counted + quarantined by the store
        per the existing hop contract, skipped by the caller."""
        try:
            fr = self.store.read_frame(rec)
        except frame.FrameCorrupt:
            return None, None
        t_batch = fr.meta.get("t_batch")
        # 0.0 is a legitimate virtual timebase — only ABSENT falls
        # back to the record's wall stamp.
        return fr.arrays, float(
            rec.t_start if t_batch is None else t_batch
        )

    def span_batches(
        self, t_from: float | None = None, t_to: float | None = None
    ):
        """The replay corpus: (arrays, t_batch) per recorded span
        batch in log order; corrupt records are skipped (counted)."""
        for rec in self.span_records(t_from, t_to):
            arrays, t_batch = self.read_span_record(rec)
            if arrays is None:
                continue
            yield arrays, t_batch
