"""N-way sharded detector fleet: the robustness tier above hot standby.

Deployment so far was 1 primary + 1 hot standby — one detector process
was a single point of total blindness. This module partitions the
keyspace across N detector shards and makes losing ANY shard brown out
only its keyspace slice:

- **Consistent-hash ring** (:class:`HashRing`): (tenant/service) keys
  → shard members via vnode points hashed with a process-stable
  64-bit digest (``blake2b`` — NEVER Python ``hash()``, whose
  per-process randomization would give every restart a different
  placement). N-1/N of the keyspace does not move when one member
  joins or leaves; the fleet suite property-pins balance, minimal
  movement and cross-process determinism.
- **Membership + liveness** (:class:`FleetMembership`): heartbeat
  table over the peers with two-edge hysteresis — a peer is declared
  dead only after ``dead_after_s`` of silence AND a failed health
  double-check (the PR 13 primary-health pattern: a
  compile-stalled-but-serving shard is NOT dead, so CI suite load
  cannot trigger a spurious reshard), and a dead peer rejoins only
  after ``rejoin_after_s`` of sustained heartbeats. Every membership
  change spends a token from a reshard budget
  (:class:`~.remediation.TokenBucket`, the PR 2/PR 13 guardrail
  construction): a flapping shard exhausts the bucket and the ring
  FREEZES in its last state — reshards refused and counted, the
  keyspace never thrashes.
- **Reshard merge** (:func:`merge_shard_arrays`): a dead shard's key
  range is reassigned to survivors by shipping the victim's latest
  replicated frame to the inheriting shard(s) and monoid-merging it
  in — HLL registers max-merge, CMS/span-total add-merge, the
  victim's per-service head rows (EWMA/CUSUM) copied over the
  survivor's virgin rows. Disjoint keyspaces make the merge bit-exact
  by construction (the PR 4 anti-entropy property, property-pinned
  again here through the reshard path) — PROVIDED the shards share
  one interned service-id table: CMS cells fold the service id into
  the key hash, so fleet mode pre-interns ``ANOMALY_FLEET_SERVICES``
  in the same order on every shard, and :func:`merge_shard_arrays`
  refuses tables that drifted instead of mis-attributing cells.
- **Per-tenant namespaces**: ring keys are :func:`shard_key`
  ``tenant/service`` (``ANOMALY_FLEET_TENANTS`` maps services to
  tenants); the per-tenant admission quota itself lives in
  ``runtime.pipeline`` (folded into the PR 2 backpressure ladder) and
  sheds one noisy tenant's rows alone.

The scatter-gather READ tier over the shard query planes lives in
``runtime.aggregator`` (it speaks only HTTP to shards — never detector
state). ``runtime.replbench --fleet`` is the chaos drill: SIGKILL a
shard under live load, measure ``shard_reshard_ttd_s``, pin the
post-reshard answers bit-exact against an unkilled witness fleet.

Everything here is stdlib + numpy — no jax import, so the membership
thread and the aggregator tier never pay device initialization.
"""

from __future__ import annotations

import hashlib
import threading
import time
from bisect import bisect_left
from typing import Callable, Iterable

import numpy as np

# The reshard budget reuses remediation's TokenBucket VERBATIM — the
# "flap-proof by construction" guardrail is one implementation, not
# three lookalikes that could drift.
from .remediation import TokenBucket
from .tensorize import EVICTED_SLOT

DEFAULT_TENANT = "default"

# Peer liveness states (the membership table's vocabulary).
PEER_ALIVE = "alive"
PEER_DEAD = "dead"

# Merge policy per state array (the sketch monoids replication proved
# bit-exact through missed deltas; reshard reuses them unchanged).
MERGE_MAX = ("hll_bank",)          # HLL registers: max-merge
MERGE_ADD = ("cms_bank", "span_total")  # CMS counters / span totals: add
# Per-service head rows (EWMA/CUSUM baselines; [S, ...] leading axis):
# the victim's rows copy over the inheriting survivor's virgin rows —
# keyspaces are disjoint, so the survivor never observed those
# services. step_idx (scalar) takes the max so the merged seq cursor
# never regresses.
MERGE_HEAD_ROWS = (
    "lat_mean", "lat_var", "err_mean", "rate_mean", "rate_var",
    "card_mean", "card_var", "obs_batches", "obs_windows", "cusum",
)


def key_hash64(key: str) -> int:
    """Process-stable 64-bit hash of a ring key.

    blake2b, not ``hash()``: CPython randomizes str hashing per process
    (PYTHONHASHSEED), and a ring whose placement changes across
    restarts would reshard the whole keyspace on every deploy. The
    fleet suite pins placement equality across processes with
    different hash seeds."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(),
        "big",
    )


def shard_key(service: str, tenant: str = DEFAULT_TENANT) -> str:
    """THE ring key for one (service × tenant) keyspace cell."""
    return f"{tenant}/{service}"


def tenant_of(service: str, tenant_map: dict[str, str]) -> str:
    """Service → tenant under the ANOMALY_FLEET_TENANTS map ('*' is
    the default for unlisted services; no map = tenant 'default')."""
    return tenant_map.get(
        service, tenant_map.get("*", DEFAULT_TENANT)
    )


class HashRing:
    """Consistent-hash ring over shard member ids.

    ``vnodes`` virtual points per member smooth the balance (more
    vnodes = tighter spread at O(members × vnodes) rebuild cost).
    Deterministic by construction: points come from
    :func:`key_hash64`, so every process — and every restart — builds
    the identical ring from the identical member set.
    """

    def __init__(
        self,
        members: Iterable[str],
        vnodes: int = 128,
        adopted: dict[str, str] | None = None,
    ):
        self.vnodes = max(int(vnodes), 1)
        self._members: set[str] = set()
        self._adopted: dict[str, str] = dict(adopted or {})
        self._points: list[int] = []
        self._owners: list[str] = []
        self._lock = threading.Lock()
        for m in members:
            self._members.add(str(m))
        self._rebuild()

    def _heir_of(self, victim: str) -> str | None:
        """Resolve an adoption chain to a LIVE heir (a heir that died
        and was itself adopted hands the whole arc onward)."""
        seen = set()
        cur = victim
        while cur in self._adopted and cur not in seen:
            seen.add(cur)
            cur = self._adopted[cur]
        return cur if cur in self._members else None

    def _rebuild(self) -> None:
        pairs = sorted(
            (key_hash64(f"{member}#{v}"), owner)
            for member, owner in (
                [(m, m) for m in self._members]
                + [
                    (v, self._heir_of(v))
                    for v in self._adopted
                    if v not in self._members
                ]
            )
            if owner is not None
            for v in range(self.vnodes)
        )
        self._points = [p for p, _ in pairs]
        self._owners = [m for _, m in pairs]

    def members(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._members))

    def adopted(self) -> dict[str, str]:
        """victim → heir arc transfers currently in force (the block
        /healthz publishes so a refreshing aggregator can rebuild the
        IDENTICAL ring, adoption arcs included)."""
        with self._lock:
            return dict(self._adopted)

    def version(self) -> int:
        """Stable ring-content digest: equal member sets (and vnode
        counts, and adoption arcs) hash equal in every process — the
        value /healthz and the aggregator compare to detect a ring
        split. The adoption suffix only appears when arcs are in
        force, so pre-adoption rings keep their historical digests."""
        with self._lock:
            text = ",".join(sorted(self._members)) + f"|{self.vnodes}"
            if self._adopted:
                text += "|" + ",".join(
                    f"{v}>{h}" for v, h in sorted(self._adopted.items())
                )
            return key_hash64(text)

    def add(self, member: str) -> bool:
        with self._lock:
            changed = member in self._adopted
            self._adopted.pop(member, None)  # rejoin reclaims the arc
            if member in self._members:
                if changed:
                    self._rebuild()
                return changed
            self._members.add(member)
            self._rebuild()
            return True

    def remove(self, member: str) -> bool:
        with self._lock:
            if member not in self._members:
                return False
            self._members.discard(member)
            self._rebuild()
            return True

    def adopt(self, victim: str, heir: str) -> bool:
        """Transfer ``victim``'s ENTIRE arc to ``heir`` and drop it
        from membership: unlike :meth:`remove` (which redistributes
        the victim's vnode arcs across all survivors by hash), every
        key the victim owned now belongs to the one shard that holds
        its replicated frame — the ownership shape that makes
        automatic frame adoption answer bit-exact reads."""
        with self._lock:
            if victim not in self._members or heir == victim:
                return False
            if heir not in self._members:
                return False
            self._members.discard(victim)
            self._adopted[victim] = heir
            self._rebuild()
            return True

    def owner(self, key: str) -> str:
        """The member owning ``key`` (first vnode point clockwise)."""
        with self._lock:
            if not self._points:
                raise RuntimeError("empty ring: no members")
            i = bisect_left(self._points, key_hash64(key))
            if i == len(self._points):
                i = 0  # wrap
            return self._owners[i]

    def owner_of(self, service: str, tenant: str = DEFAULT_TENANT) -> str:
        return self.owner(shard_key(service, tenant))

    def assignments(self, keys: Iterable[str]) -> dict[str, str]:
        """key → owning member for a key set (one lock round)."""
        return {k: self.owner(k) for k in keys}

    def spread(self, keys: Iterable[str]) -> dict[str, int]:
        """member → owned-key count (the balance the suite pins)."""
        out: dict[str, int] = {m: 0 for m in self.members()}
        for k in keys:
            out[self.owner(k)] += 1
        return out


def ring_successor(members: Iterable[str], self_id: str) -> str | None:
    """The member after ``self_id`` in sorted member order (wrapping)
    — the peer whose replication stream this shard mirrors so it can
    adopt the keyspace if that peer dies. Deterministic from the
    member list alone: every shard computes the same pairing with no
    coordination. ``None`` when alone (nothing to mirror)."""
    ordered = sorted({str(m) for m in members})
    if self_id not in ordered or len(ordered) < 2:
        return None
    i = ordered.index(self_id)
    return ordered[(i + 1) % len(ordered)]


def ring_heir(members: Iterable[str], victim: str) -> str | None:
    """The survivor that adopts ``victim``'s arc: the member whose
    :func:`ring_successor` is (was) the victim — its predecessor in
    sorted order over the full member set. Every member computes the
    identical heir from the identical list, so the adoption lands on
    exactly one shard. ``None`` when no survivor exists."""
    full = sorted({str(m) for m in members} | {victim})
    if len(full) < 2:
        return None
    i = full.index(victim)
    return full[i - 1]


# -- reshard state merge ------------------------------------------------


class ShardMergeError(RuntimeError):
    """A reshard frame that CANNOT merge bit-exactly (drifted service
    tables / mismatched geometry) — refused, never mis-attributed."""


def service_row_mask(
    src_names: list[str],
    dst_names: list[str],
    num_rows: int,
    owned: Iterable[str] | None = None,
) -> np.ndarray:
    """bool[num_rows] of head rows to adopt from a victim frame.

    The tables must AGREE on every overlapping position (the shared
    ``ANOMALY_FLEET_SERVICES`` pre-intern contract): CMS cells bake
    the service id into the key hash, so a drifted table cannot be
    fixed up after the fact — it is refused.

    ``owned``: restrict adoption to these service names (the victim's
    keyspace slice); None adopts every row the victim ever interned.
    """
    overlap = min(len(src_names), len(dst_names))
    for i in range(overlap):
        if src_names[i] != dst_names[i]:
            raise ShardMergeError(
                f"service tables drifted at id {i}: "
                f"{src_names[i]!r} != {dst_names[i]!r} — shards must "
                "share ANOMALY_FLEET_SERVICES to exchange frames"
            )
    mask = np.zeros(num_rows, dtype=bool)
    allowed = None if owned is None else set(owned)
    for i, name in enumerate(src_names):
        if i >= num_rows:
            break
        if name == EVICTED_SLOT:
            continue  # freed slot: no service owns the row anymore
        if allowed is None or name in allowed:
            mask[i] = True
    return mask


def merge_shard_arrays(
    dst: dict,
    src: dict,
    head_rows: np.ndarray | None = None,
    *,
    dst_generation: int | None = None,
    src_generation: int | None = None,
) -> dict:
    """Monoid-merge a victim shard's replicated arrays into a
    survivor's — the reshard adoption step.

    HLL banks max-merge and CMS banks / span totals add-merge (exact
    for disjoint keyspaces: merged sketch == sketch of the union
    stream, the PR 4 property); per-service head rows in ``head_rows``
    (bool [S]) copy from the victim — the survivor's rows for a
    keyspace it never observed are virgin. Returns NEW arrays; neither
    input is mutated (the caller swaps under its own dispatch lock).

    ``dst_generation``/``src_generation`` extend the drift-refusal
    contract to the key lifecycle plane: a keyspace eviction sweep
    recycles intern ids behind a generation bump, so two frames whose
    generations disagree may use the SAME id for DIFFERENT services —
    merging them would mis-attribute sketch rows with no way to tell.
    When both are provided they must match; ``None`` (a frame minted
    before the lifecycle plane) skips the check for compatibility.
    """
    if (
        dst_generation is not None
        and src_generation is not None
        and int(dst_generation) != int(src_generation)
    ):
        raise ShardMergeError(
            f"keyspace generation drift: dst gen {dst_generation} vs "
            f"src gen {src_generation} — recycled intern ids cannot "
            "merge across an eviction sweep"
        )
    out = {k: np.array(v, copy=True) for k, v in dst.items()}
    for name in MERGE_MAX:
        if name in out and name in src:
            a, b = out[name], np.asarray(src[name])
            if a.shape != b.shape:
                raise ShardMergeError(
                    f"{name} geometry mismatch {a.shape} vs {b.shape}"
                )
            np.maximum(a, b, out=a)
    for name in MERGE_ADD:
        if name in out and name in src:
            a, b = out[name], np.asarray(src[name])
            if a.shape != b.shape:
                raise ShardMergeError(
                    f"{name} geometry mismatch {a.shape} vs {b.shape}"
                )
            a += b.astype(a.dtype, copy=False)
    if head_rows is not None:
        for name in MERGE_HEAD_ROWS:
            if name not in out or name not in src:
                continue
            a, b = out[name], np.asarray(src[name])
            if a.shape != b.shape:
                raise ShardMergeError(
                    f"{name} geometry mismatch {a.shape} vs {b.shape}"
                )
            rows = head_rows[: a.shape[0]]
            a[rows] = b[rows]
    if "step_idx" in out and "step_idx" in src:
        out["step_idx"] = np.maximum(
            np.asarray(out["step_idx"]), np.asarray(src["step_idx"])
        )
    return out


# -- membership + guardrailed reshard -----------------------------------


class _PeerState:
    __slots__ = (
        "last_beat", "alive", "beats_since", "in_ring",
    )

    def __init__(self, now: float):
        self.last_beat = now
        self.alive = True
        self.beats_since = now  # start of the current sustained-beat run
        self.in_ring = True


class FleetMembership:
    """Heartbeat liveness + hysteresis + budgeted ring membership.

    Drive it with ``observe(peer)`` on every successful heartbeat and
    ``tick()`` on a cadence; it returns the reshard events it APPLIED
    to the ring (leave/join), already guardrailed:

    - down edge: silence > ``dead_after_s`` AND the optional
      ``health_check(peer)`` double-check fails (a serving-but-slow
      shard gets its watchdog credited instead — the flake guard);
    - up edge: sustained beats for ``rejoin_after_s``;
    - every applied change spends a reshard-budget token; an empty
      bucket freezes the ring (refusals counted, state unchanged).
    """

    def __init__(
        self,
        self_id: str,
        peers: Iterable[str],
        *,
        vnodes: int = 128,
        dead_after_s: float = 3.0,
        rejoin_after_s: float = 5.0,
        reshard_budget: int = 4,
        reshard_refill_s: float = 60.0,
        health_check: Callable[[str], bool] | None = None,
        on_reshard: Callable[[dict], None] | None = None,
        adoptive: bool = False,
    ):
        self.self_id = str(self_id)
        peer_ids = [str(p) for p in peers if str(p) != self.self_id]
        # Adoptive mode: a declared-dead peer's arc TRANSFERS whole to
        # its deterministic heir (ring.adopt) instead of rehashing
        # across all survivors — the heir is the shard mirroring the
        # victim's replication stream, so ownership lands exactly
        # where the replicated frame already lives.
        self.adoptive = bool(adoptive)
        self.ring = HashRing([self.self_id, *peer_ids], vnodes=vnodes)
        self.dead_after_s = float(dead_after_s)
        self.rejoin_after_s = float(rejoin_after_s)
        self._health_check = health_check
        self._on_reshard = on_reshard
        self._bucket = TokenBucket(reshard_budget, reshard_refill_s)
        self._lock = threading.Lock()
        now = time.monotonic()
        self._peers: dict[str, _PeerState] = {
            p: _PeerState(now) for p in peer_ids
        }
        self.reshards_total = 0
        self.reshards_refused = 0
        # One refusal is counted per WANTED transition, not per tick —
        # a frozen ring under a still-dead peer logs once, not 100 Hz.
        self._refused_pending: set[str] = set()

    # -- heartbeats -----------------------------------------------------

    def observe(self, peer: str, t: float | None = None) -> None:
        """A successful heartbeat from ``peer`` (any evidence of life:
        a /healthz answer, a replication frame, a query response)."""
        now = time.monotonic() if t is None else t
        with self._lock:
            st = self._peers.get(peer)
            if st is None:
                return
            if not st.alive:
                # First beat of a comeback run starts the rejoin clock.
                if now - st.last_beat > self.dead_after_s:
                    st.beats_since = now
            st.last_beat = now

    # -- the guardrailed tick -------------------------------------------

    def tick(self, t: float | None = None) -> list[dict]:
        """Advance liveness; returns the reshard events APPLIED.

        Two-phase so the health double-check — a blocking HTTP probe
        that can take seconds against a dead host — NEVER runs under
        the membership lock: snapshot()/observe() callers (the daemon
        pump, /healthz handlers) must not stall behind a probe, or one
        dead shard would make healthy shards look silent to each other
        (the exact cascade the double-check exists to prevent)."""
        now = time.monotonic() if t is None else t
        # Phase 1 (lock): who crossed the dead edge this tick?
        with self._lock:
            suspects = [
                peer for peer, st in self._peers.items()
                if st.alive and now - st.last_beat > self.dead_after_s
            ]
        # Probe OUTSIDE the lock, and CONCURRENTLY across suspects
        # (bounded join): a sequential sweep of 6 s double-checks
        # would let one dead peer delay every other suspect's verdict
        # — per-peer degradation, never collective. A suspect whose
        # probe misses the bound simply gets no verdict this tick
        # (stays alive; the next tick retries). Flake guard (the
        # PR 13 primary-health pattern): a peer whose heartbeats
        # stalled but whose health surface still ANSWERS is
        # compile-stalled or suite-starved, not dead — credit the
        # watchdog, never reshard a serving shard's keyspace away.
        verdicts: dict[str, bool] = {}
        if self._health_check is not None and suspects:
            def check(peer: str) -> None:
                verdicts[peer] = self._safe_health(peer)

            checkers = [
                threading.Thread(
                    target=check, args=(peer,),
                    name=f"fleet-check-{peer}", daemon=True,
                )
                for peer in suspects
            ]
            for th in checkers:
                th.start()
            deadline = time.monotonic() + 8.0
            for th in checkers:
                th.join(max(deadline - time.monotonic(), 0.0))
        events: list[dict] = []
        with self._lock:
            self._bucket.advance(now)
            for peer, st in self._peers.items():
                silent = now - st.last_beat
                if st.alive and silent > self.dead_after_s:
                    if self._health_check is not None:
                        if verdicts.get(peer, False):
                            st.last_beat = now
                            continue
                        if peer not in verdicts:
                            # Crossed the edge between the phases:
                            # no verdict yet — next tick decides.
                            continue
                    st.alive = False
                    st.beats_since = float("inf")
                    if st.in_ring:
                        ev = self._apply_locked("leave", peer, now)
                        if ev is not None:
                            events.append(ev)
                elif not st.alive:
                    if silent > self.dead_after_s:
                        # Still silent: any rejoin run is broken.
                        st.beats_since = float("inf")
                        if st.in_ring:
                            # An earlier leave was REFUSED by the
                            # exhausted budget: retry once tokens
                            # refill — a permanently dead shard must
                            # not keep its keyspace forever (the
                            # refusal counter moved once; retries
                            # are silent until one lands).
                            ev = self._apply_locked("leave", peer, now)
                            if ev is not None:
                                events.append(ev)
                    elif (
                        now - st.beats_since >= self.rejoin_after_s
                        and not st.in_ring
                    ):
                        st.alive = True
                        ev = self._apply_locked("join", peer, now)
                        if ev is not None:
                            events.append(ev)
                    elif st.in_ring and silent <= self.dead_after_s:
                        # The ring froze while this peer was declared
                        # dead (refused leave) and it came back: it is
                        # simply alive again, no ring change needed.
                        st.alive = True
                elif not st.in_ring:
                    # Alive, beating, but OUT of the ring: its join
                    # was REFUSED by the exhausted budget (alive
                    # flipped before the refusal landed) — retry once
                    # tokens refill, symmetric with the refused-leave
                    # retry above: a healthy shard must not stay
                    # keyspace-less forever while /healthz calls it
                    # alive.
                    ev = self._apply_locked("join", peer, now)
                    if ev is not None:
                        events.append(ev)
        for ev in events:
            if self._on_reshard is not None:
                self._on_reshard(ev)
        return events

    def _safe_health(self, peer: str) -> bool:
        try:
            return bool(self._health_check(peer))
        except Exception:  # noqa: BLE001 — an unreachable health
            return False  # surface IS the dead signal

    def _apply_locked(self, op: str, peer: str, now: float) -> dict | None:
        if not self._bucket.take():
            if peer not in self._refused_pending:
                self.reshards_refused += 1
                self._refused_pending.add(peer)
            return None
        self._refused_pending.discard(peer)
        st = self._peers[peer]
        heir = None
        if op == "leave":
            if self.adoptive:
                heir = ring_heir(self.ring.members(), peer)
            if heir is not None:
                self.ring.adopt(peer, heir)
            else:
                self.ring.remove(peer)
            st.in_ring = False
        else:
            self.ring.add(peer)
            st.in_ring = True
        self.reshards_total += 1
        ev = {
            "op": op,
            "shard": peer,
            "t": now,
            "ring_version": self.ring.version(),
            "members": list(self.ring.members()),
        }
        if heir is not None:
            ev["heir"] = heir
        return ev

    # -- surfaces -------------------------------------------------------

    @property
    def frozen(self) -> bool:
        """True while the reshard budget is exhausted — the ring holds
        its last state and refuses changes (counted)."""
        return self._bucket.tokens < 1.0

    def live_count(self) -> int:
        with self._lock:
            return 1 + sum(1 for s in self._peers.values() if s.alive)

    def snapshot(self) -> dict:
        """The /healthz fleet block (and health_probe --shard body)."""
        with self._lock:
            peers = {
                p: {
                    "alive": st.alive,
                    "in_ring": st.in_ring,
                    "silence_s": round(
                        time.monotonic() - st.last_beat, 3
                    ),
                }
                for p, st in self._peers.items()
            }
        members = self.ring.members()
        return {
            "shard": self.self_id,
            "ring_version": self.ring.version(),
            "members": list(members),
            "adopted": self.ring.adopted(),
            "shards_live": self.live_count(),
            "shards_total": 1 + len(peers),
            "owned_vnodes": self.ring.vnodes,
            "peers": peers,
            "reshards_total": self.reshards_total,
            "reshards_refused": self.reshards_refused,
            "frozen": self.frozen,
        }


# -- the daemon-embedded member (heartbeat loop over HTTP health) -------


def http_health_alive(addr: str, timeout_s: float = 2.0) -> bool:
    """One /healthz poll against a peer's metrics address — the
    heartbeat AND the double-check probe (the double-check simply
    retries with a longer timeout). Any parseable answer counts:
    a saturated/degraded shard is ALIVE (shedding, not gone), and
    resharding its keyspace away would turn a brownout into data
    loss."""
    import http.client

    host, _, port = addr.rpartition(":")
    try:
        conn = http.client.HTTPConnection(
            host or "127.0.0.1", int(port), timeout=timeout_s
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            resp.read()
            return resp.status in (200, 503)
        finally:
            conn.close()
    except Exception:  # noqa: BLE001 — any transport failure is "no beat"
        return False


class FleetMember:
    """The daemon's fleet leg: a supervised heartbeat loop polling
    every peer's /healthz, feeding :class:`FleetMembership`.

    ``peer_addrs``: shard-id → health address (host:metrics_port).
    The loop thread is daemonized and owned here (start/stop/alive —
    the supervision tree probes ``alive()``)."""

    def __init__(
        self,
        self_id: str,
        peer_addrs: dict[str, str],
        *,
        heartbeat_s: float = 1.0,
        vnodes: int = 128,
        dead_after_s: float = 3.0,
        rejoin_after_s: float = 5.0,
        reshard_budget: int = 4,
        reshard_refill_s: float = 60.0,
        on_reshard: Callable[[dict], None] | None = None,
        probe: Callable[[str], bool] | None = None,
        adoptive: bool = False,
    ):
        self._addrs = dict(peer_addrs)
        self._probe = probe or (
            lambda shard: http_health_alive(self._addrs[shard])
        )
        # The death double-check gets MORE patience than the routine
        # poll: a shard mid-compile (or starved by suite load) answers
        # slowly, not never — the slow answer must count as life.
        self._double_check = probe or (
            lambda shard: http_health_alive(
                self._addrs[shard], timeout_s=6.0
            )
        )
        self.membership = FleetMembership(
            self_id,
            self._addrs.keys(),
            vnodes=vnodes,
            dead_after_s=dead_after_s,
            rejoin_after_s=rejoin_after_s,
            reshard_budget=reshard_budget,
            reshard_refill_s=reshard_refill_s,
            health_check=lambda shard: self._safe_double_check(shard),
            on_reshard=on_reshard,
            adoptive=adoptive,
        )
        self.heartbeat_s = float(heartbeat_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _safe_probe(self, shard: str) -> bool:
        try:
            return bool(self._probe(shard))
        except Exception:  # noqa: BLE001 — unreachable = not alive
            return False

    def _safe_double_check(self, shard: str) -> bool:
        try:
            return bool(self._double_check(shard))
        except Exception:  # noqa: BLE001 — unreachable = not alive
            return False

    def _loop(self) -> None:
        # Peers are probed CONCURRENTLY and WITHOUT joining: each beat
        # lands its observe() from its own daemon thread, so the cycle
        # cadence is heartbeat_s regardless of how many peers are
        # blackholed — a 2 s probe timeout on one peer must never
        # stretch another peer's observation interval past the dead
        # edge (liveness degrades per peer, never collectively). A
        # per-shard in-flight guard bounds the threads: a peer slower
        # than the cadence has exactly ONE probe outstanding.
        inflight: set[str] = set()
        guard = threading.Lock()

        def beat(shard: str) -> None:
            try:
                if self._safe_probe(shard):
                    self.membership.observe(shard)
            finally:
                with guard:
                    inflight.discard(shard)

        while not self._stop.is_set():
            for shard in list(self._addrs):
                with guard:
                    if shard in inflight:
                        continue
                    inflight.add(shard)
                threading.Thread(
                    target=beat, args=(shard,),
                    name=f"fleet-beat-{shard}", daemon=True,
                ).start()
            self.membership.tick()
            self._stop.wait(self.heartbeat_s)

    def start(self) -> None:
        # A supervised restart calls stop() then start(): the stop
        # event must reset or the fresh thread exits immediately and
        # the supervisor restart-loops forever.
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-heartbeat", daemon=True
        )
        self._thread.start()

    def alive(self) -> bool:
        return self._thread is None or self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def snapshot(self) -> dict:
        return self.membership.snapshot()


def parse_peer_list(
    raw: str, shards: int, self_index: int, prefix: str = "shard-"
) -> dict[str, str]:
    """ANOMALY_FLEET_PEERS / _QUERY_PEERS → {shard-<i>: addr}, the
    index-aligned contract (this shard's own slot, when present, is
    skipped — a member does not heartbeat itself)."""
    addrs = [a.strip() for a in str(raw).split(",") if a.strip()]
    out: dict[str, str] = {}
    for i, addr in enumerate(addrs):
        if i >= shards:
            break
        if i == self_index:
            continue
        out[f"{prefix}{i}"] = addr
    return out
