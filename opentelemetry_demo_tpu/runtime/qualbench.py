"""Detection-quality measurement: per-fault time-to-detect + FP rate.

The fault-matrix e2e tests (tests/test_shop_e2e.py,
tests/test_e2e_detection.py) prove detection *happens*; this module
measures how *well*, producing the ``ttd_s`` / ``fp_rate`` fields of
the bench artifact. Each fault shape mirrors one of the reference's
flagd failure scenarios (SURVEY.md §5 fault-injection inventory —
demo.flagd.json:4-108) projected onto the synthetic span stream:

- ``paymentFailure``            → error-rate burst on one service,
  PLUS a percentage sweep over the reference's variant ladder
  (demo.flagd.json: 10/25/50/75/90/100%) — TTD as a function of rate
- ``cartFailure``               → total error burst (every op fails —
  the bad-store swap, CartService.cs:83-90)
- ``productCatalogFailure``     → partial error burst (only requests
  for the one flagged product fail, main.go:339-349)
- ``adFailure``                 → 1-in-10 error burst (AdService.java)
- ``paymentUnreachable``        → service vanishes (full rate collapse)
- ``adHighCpu``                 → step latency degradation
- ``imageSlowLoad``             → step latency degradation on the
  image-serving tier (the flag's 5/10-second variants dwarf the base)
- ``adManualGc``                → PERIODIC latency spikes (full GC
  pauses every few seconds, normal between them)
- ``recommendationCacheFailure``  → gradual latency ramp (cache leak)
- ``kafkaQueueProblems``        → throughput collapse (consumer stall)
- ``loadGeneratorFloodHomepage``  → traffic redistribution: the flood
  multiplies one service's span rate while starving the rest
- ``errorTrickle``              → sustained small error shift, below
  any single-batch threshold (the CUSUM-integration case)
- ``traceCardinalityExplosion`` → session/trace-id churn at constant
  span rate — only the HLL cardinality head can see it (the signal
  family the other shapes never exercise)

Time-to-detect is virtual seconds from fault onset to the first batch
whose report flags the faulted service; the false-positive rate is
flagged-batches / batches over a long clean run after warmup. Both are
detector *math*, independent of which backend executes it — bench.py
runs this in a CPU subprocess so per-step report fetches don't pay the
tunneled-TPU round trip ~1000 times.
"""

from __future__ import annotations

import numpy as np

from ..models import AnomalyDetector, DetectorConfig

S = 8
B = 256
DT_S = 0.25  # virtual seconds per batch (~1k spans/s at B=256)
WARM_STEPS = 120
FAULT_WINDOW_STEPS = 120  # give-up horizon after onset
QUIET_STEPS = 600


def _quality_config() -> DetectorConfig:
    """Reduced CMS width (fast compile), PRODUCTION thresholds/warmups
    AND production HLL precision — quality numbers with detuned
    thresholds would be fiction, and p=8's ~3% estimator noise alone
    can graze the 6σ cardinality threshold (measured: one card_z=6.1
    warmup spike that p=12's ~0.8% noise does not produce)."""
    return DetectorConfig(num_services=S, hll_p=12, cms_width=512)


def _batch(rng, tz, mutate=None, step: int = 0):
    lat = rng.gamma(4.0, 250.0, size=B).astype(np.float32)
    svc = rng.integers(0, S, size=B)
    err = (rng.random(B) < 0.01).astype(np.float32)
    keep = np.ones(B, bool)
    # Baseline trace-id pool: sessions REUSE ids (browse traffic fans
    # several spans out of one trace), so per-window distinct counts sit
    # well below span counts — the decoupling that lets a cardinality
    # fault exist at constant throughput. 64 concurrent sessions across
    # ~128 spans/svc/window puts baseline distinct ≈ 55 with tight
    # variance; the explosion to ~128 unique ids is then an
    # unmistakable HLL jump at unchanged span rate.
    trace = rng.integers(0, 64, size=B, dtype=np.uint64) * 2654435761 + 1
    if mutate is not None:
        svc, lat, err, keep, trace = mutate(step, svc, lat, err, keep, trace)
    return tz.pack_arrays(
        svc=svc[keep],
        lat_us=lat[keep],
        trace_id=trace[keep],
        is_error=err[keep],
        attr_key=rng.zipf(1.5, size=int(keep.sum())).astype(np.uint64),
    )


def error_burst(rng, target: int, p: float):
    """Error-rate burst shape: fraction ``p`` of the target service's
    requests fail — paymentFailure's variant ladder, cartFailure at
    p=1.0 (the bad-store swap fails every op), productCatalogFailure
    at the flagged product's traffic share, adFailure at 1-in-10."""

    def mutate(step, svc, lat, err, keep, trace):
        hit = (rng.random(B) < p).astype(np.float32)
        return svc, lat, np.where(
            svc == target, np.maximum(err, hit), err
        ).astype(np.float32), keep, trace

    return mutate


def fault_shapes(rng):
    """name → (faulted service index,
    mutate(step, svc, lat, err, keep, trace) → same tuple)."""

    def latency_step(step, svc, lat, err, keep, trace):
        return (svc, np.where(svc == 1, lat * 3.0, lat).astype(np.float32),
                err, keep, trace)

    def image_slow_load(step, svc, lat, err, keep, trace):
        # imageSlowLoad's variants are 5000/10000 ms flat adds — vs a
        # ~1 ms base that is a ~10x latency step on the image tier.
        return (svc, np.where(svc == 7, lat * 10.0, lat).astype(np.float32),
                err, keep, trace)

    def manual_gc(step, svc, lat, err, keep, trace):
        # adManualGc: full collections every ~2s (8 batches at dt=0.25)
        # freeze the service for the batch; between pauses it is normal.
        if step % 8 < 2:
            lat = np.where(svc == 1, lat * 8.0, lat).astype(np.float32)
        return svc, lat, err, keep, trace

    def cache_ramp(step, svc, lat, err, keep, trace):
        scale = 1.10 ** min(step, 60)  # unbounded cache growth shape
        return (svc, np.where(svc == 2, lat * scale, lat).astype(np.float32),
                err, keep, trace)

    def rate_drop(step, svc, lat, err, keep, trace):
        # Consumer stall: 90% of the service's spans stop arriving.
        return (svc, lat, err,
                keep & ~((svc == 3) & (rng.random(B) < 0.9)), trace)

    def unreachable(step, svc, lat, err, keep, trace):
        # paymentUnreachable: the service VANISHES — checkout reroutes
        # to a dead address (main.go:475-479), so the payment span
        # stream stops entirely (full rate collapse, not errors).
        return svc, lat, err, keep & (svc != 7), trace

    def trickle(step, svc, lat, err, keep, trace):
        hit = (rng.random(B) < 0.06).astype(np.float32)
        return svc, lat, np.where(svc == 4, np.maximum(err, hit), err).astype(
            np.float32
        ), keep, trace

    def card_explosion(step, svc, lat, err, keep, trace):
        # Session/trace-id churn at CONSTANT throughput: the faulted
        # service's spans stop sharing the session pool and arrive with
        # unique trace ids — span rate unchanged, per-window distinct
        # count explodes. Only the HLL cardinality head can see this.
        fresh = rng.integers(1 << 32, 1 << 62, size=B, dtype=np.uint64)
        return svc, lat, err, keep, np.where(svc == 6, fresh, trace)

    def flood(step, svc, lat, err, keep, trace):
        # loadGeneratorFloodHomepage: the flood multiplies the
        # frontend's request rate; within a fixed-width batch that is a
        # traffic REDISTRIBUTION — most spans become frontend spans
        # (svc 0), its per-dt rate jumping ~5× while the rest starve.
        return (np.where(rng.random(B) < 0.6, 0, svc),
                lat, err, keep, trace)

    return {
        "paymentFailure": (5, error_burst(rng, 5, 0.25)),
        "cartFailure": (0, error_burst(rng, 0, 1.0)),
        # The reference fails exactly one product id; the featured
        # product draws ~1/8 of GetProduct traffic in the shop's mix.
        "productCatalogFailure": (2, error_burst(rng, 2, 0.125)),
        "adFailure": (1, error_burst(rng, 1, 0.10)),
        "paymentUnreachable": (7, unreachable),
        "adHighCpu": (1, latency_step),
        "adManualGc": (1, manual_gc),
        "imageSlowLoad": (7, image_slow_load),
        "recommendationCacheFailure": (2, cache_ramp),
        "kafkaQueueProblems": (3, rate_drop),
        "loadGeneratorFloodHomepage": (0, flood),
        "errorTrickle": (4, trickle),
        "traceCardinalityExplosion": (6, card_explosion),
    }


def measure_time_to_detect(name: str, fault_svc: int, mutate, seed: int = 0):
    """One fault scenario: clean warmup, onset, first correct flag."""
    from .tensorize import SpanTensorizer

    rng = np.random.default_rng(seed)
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    false_before = 0
    for step in range(WARM_STEPS):
        report = det.observe(_batch(rng, tz), step * DT_S)
        if np.asarray(report.flags).any():
            false_before += 1
    for k in range(FAULT_WINDOW_STEPS):
        step = WARM_STEPS + k
        report = det.observe(
            _batch(rng, tz, mutate=mutate, step=k), step * DT_S
        )
        flags = np.asarray(report.flags)
        if flags[fault_svc]:
            return {
                "ttd_s": round((k + 1) * DT_S, 3),
                "ttd_batches": k + 1,
                "false_flags_warmup": false_before,
            }
    return {"ttd_s": None, "ttd_batches": None, "false_flags_warmup": false_before}


def measure_fp_rate(seed: int = 1):
    """Long clean run: flagged-batch fraction after warmup."""
    from .tensorize import SpanTensorizer

    rng = np.random.default_rng(seed)
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    flagged = 0
    for step in range(WARM_STEPS + QUIET_STEPS):
        report = det.observe(_batch(rng, tz), step * DT_S)
        if step >= WARM_STEPS and np.asarray(report.flags).any():
            flagged += 1
    return {
        "fp_rate": round(flagged / QUIET_STEPS, 5),
        "fp_batches": flagged,
        "quiet_batches": QUIET_STEPS,
    }


# The reference paymentFailure flag's variant ladder
# (demo.flagd.json: '10%' … '100%') — TTD is measured per rate.
PAYMENT_SWEEP = (0.10, 0.25, 0.50, 0.75, 0.90, 1.00)


def measure_payment_sweep(seed: int = 0) -> dict:
    """TTD as a function of the paymentFailure rate: the detector's
    sensitivity curve over the flag's own variant ladder."""
    out = {}
    for p in PAYMENT_SWEEP:
        rng = np.random.default_rng(seed)
        res = measure_time_to_detect(
            f"paymentFailure@{p:.0%}", 5, error_burst(rng, 5, p), seed=seed
        )
        out[f"{p:.0%}"] = res["ttd_s"]
    return out


def measure_detection_quality(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    ttd = {}
    for name, (svc, mutate) in fault_shapes(rng).items():
        ttd[name] = measure_time_to_detect(name, svc, mutate, seed=seed)
    out = {"dt_s": DT_S, "batch": B, "ttd": ttd}
    out["paymentFailure_ttd_by_rate"] = measure_payment_sweep(seed=seed)
    out.update(measure_fp_rate(seed=seed + 1))
    return out


def main() -> None:
    import json

    print(json.dumps(measure_detection_quality()))


if __name__ == "__main__":
    main()
