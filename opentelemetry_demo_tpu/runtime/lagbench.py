"""Detection-lag measurement: one methodology, two entry points.

The p99 submit→harvest lag through the REAL DetectorPipeline at a paced
span rate — the second BASELINE north star ("<100 ms p99 detection lag
under the default Locust load profile"). Both ``bench.py`` (the driver
artifact) and ``scripts/bench_lag.py`` (the standalone CLI) call this,
so the reported numbers can never silently diverge.

Timing integrity: every harvest ends in a real device→host fetch (the
packed report), so the lag samples are fetch-terminated — the only
honest synchronization on tunneled PJRT topologies where
``block_until_ready`` can return early.
"""

from __future__ import annotations

import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .pipeline import DetectorPipeline
from .tensorize import SpanColumns

BASELINE_LAG_MS = 100.0


def make_columns(rng, rows: int) -> SpanColumns:
    return SpanColumns(
        svc=rng.integers(0, 20, size=rows).astype(np.int32),
        lat_us=rng.gamma(4.0, 250.0, size=rows).astype(np.float32),
        is_error=(rng.random(rows) < 0.02).astype(np.float32),
        trace_key=rng.integers(0, 2**63, size=rows, dtype=np.uint64),
        attr_crc=rng.zipf(1.5, size=rows).astype(np.uint64),
    )


def measure_lag(
    rate: float = 2_000.0,
    seconds: float = 12.0,
    batch: int = 256,
    harvest_interval_s: float = 0.0,
    harvest_async: bool = False,
    rtt_probe: bool = True,
    seed: int = 0,
    config: DetectorConfig | None = None,
    adaptive: bool = False,
    max_batch_growth: int = 8,
    settle_s: float = 3.0,
) -> dict:
    """Drive the pipeline at ``rate`` spans/s; return lag statistics.

    The default rate models the north star's own config — the default
    Locust profile is 5 users with 1-10 s waits (~10²-10³ spans/s), not
    the 200k/s throughput stress config (pass ``rate=200_000`` +
    ``harvest_async=True`` for that regime).

    With ``rtt_probe`` (default), every harvest launches one timed
    1-scalar fetch CONCURRENT with its report fetch (same tunnel moment,
    same congestion), and the result carries ``p99_net_ms`` = p99 of
    elementwise lag−RTT — what a locally attached chip (no tunnel round
    trip per readback) would show — beside the gross number, plus the
    RTT distribution itself so the gross p99 can be judged against the
    topology's own floor and jitter.
    """
    detector = AnomalyDetector(config or DetectorConfig())
    pipe = DetectorPipeline(
        detector,
        batch_size=batch,
        harvest_interval_s=harvest_interval_s,
        harvest_async=harvest_async,
        rtt_probe=rtt_probe,
        adaptive_batching=adaptive,
        max_batch_growth=max_batch_growth,
    )
    rng = np.random.default_rng(seed)
    # Pre-build chunks so generation cost stays off the timed path.
    chunks = [make_columns(rng, batch) for _ in range(16)]
    interval = batch / rate

    # Warmup compiles the step; scrub it from every reported stat.
    # Adaptive mode precompiles the whole width ladder here so a
    # mid-run escalation never pays a compile on the timed path.
    pipe.submit_columns(chunks[0])
    pipe.pump(time.monotonic())
    pipe.drain()
    pipe.warm_widths()

    def paced_loop(duration_s: float, i0: int = 0) -> int:
        end = time.monotonic() + duration_s
        next_at = time.monotonic()
        i = i0
        while time.monotonic() < end:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, interval))
                continue
            next_at += interval
            pipe.submit_columns(chunks[i % len(chunks)])
            pipe.pump(time.monotonic())
            i += 1
        return i

    # Settle phase (adaptive only): let the width controller find its
    # operating point before measurement — the same warmup-scrub policy
    # as the compile warmup above. The controller's transient (a few
    # hundred ms of skips while it jumps to target) is real but
    # one-time per stress onset; the reported numbers are the sustained
    # regime an operator lives in. ``final_batch_width`` +
    # ``settle_s`` in the output keep the transient auditable.
    i = 0
    if adaptive and settle_s > 0:
        i = paced_loop(settle_s)
        # Barrier before the stats reset: under harvest_async the
        # settle phase's last dispatches are still in flight, and the
        # harvester would otherwise attribute their lag samples and
        # controller-transient skips to the measured window.
        pipe.drain()

    pipe.stats.lag_ms.clear()
    pipe.stats.rtt_ms.clear()
    base_batches = pipe.stats.batches
    base_spans = pipe.stats.spans
    base_skipped = pipe.stats.reports_skipped

    paced_loop(seconds, i)
    pipe.close()

    batches = pipe.stats.batches - base_batches
    skipped = pipe.stats.reports_skipped - base_skipped
    out = {
        "p99_ms": round(pipe.stats.lag_p99_ms(), 3),
        "rate": rate,
        "batches": batches,
        "spans": pipe.stats.spans - base_spans,
        "reports_skipped": skipped,
        # Skip *rate* beside the raw count: a skipped-report tally is
        # only judgeable against the batch denominator it came from.
        "skip_rate": round(skipped / batches, 4) if batches else None,
        # Where the adaptive controller settled (== batch unless it
        # widened under skip pressure) and how long it was given to
        # settle before the measured window.
        "final_batch_width": pipe.batch_width,
        "settle_s": settle_s if adaptive else None,
    }
    net = pipe.stats.lag_net_samples()
    rtt = np.asarray(pipe.stats.rtt_ms, dtype=np.float64)
    rtt = rtt[~np.isnan(rtt)]  # timed-out probes append NaN sentinels
    if net.size and rtt.size:
        out.update(
            p99_net_ms=round(float(np.percentile(net, 99)), 3),
            p50_net_ms=round(float(np.percentile(net, 50)), 3),
            rtt_p50_ms=round(float(np.percentile(rtt, 50)), 3),
            rtt_p99_ms=round(float(np.percentile(rtt, 99)), 3),
            rtt_pairs=int(net.size),
        )
    return out
