"""OTLP/HTTP metrics ingestion + export: the collector's second leg.

The reference collector runs a full metrics pipeline beside traces
(/root/reference/src/otel-collector/otelcol-config.yml:124-126, receivers
:4-23) and every SDK exports OTLP metrics into it. The detector sidecar
therefore consumes BOTH streams: ``POST /v1/traces`` (runtime.otlp) and
``POST /v1/metrics`` (this module), turning metric points into per-service
rate/level observations for the metrics detection head
(models.metrics_head).

Field numbers follow the public OTLP protocol (opentelemetry-proto
metrics/v1): ExportMetricsServiceRequest{resource_metrics=1},
ResourceMetrics{resource=1, scope_metrics=2}, Resource{attributes=1},
ScopeMetrics{metrics=2}, Metric{name=1, unit=3, gauge=5, sum=7,
histogram=9}, Gauge{data_points=1}, Sum{data_points=1,
aggregation_temporality=2, is_monotonic=3}, Histogram{data_points=1,
aggregation_temporality=2}, NumberDataPoint{start_time_unix_nano=2,
time_unix_nano=3, as_double=4, as_int=6},
HistogramDataPoint{start_time_unix_nano=2, time_unix_nano=3, count=4,
sum=5, bucket_counts=6, explicit_bounds=7}.

The module also *encodes* ``ExportMetricsServiceRequest`` from a
:class:`~..telemetry.metrics.MetricRegistry` snapshot — that is the
collector-side ``otlphttp`` metrics exporter (otelcol-config.yml:124-126
wires `otlphttp/prometheus`; here the registry IS the metric source), so
the sidecar's wire e2e is collector registry → protobuf → HTTP →
receiver → detector head.
"""

from __future__ import annotations

import json
import struct
from typing import Iterable, NamedTuple

from . import wire
from .otlp_export import _ExporterBase

# AggregationTemporality enum (metrics/v1).
TEMPORALITY_UNSPECIFIED = 0
TEMPORALITY_DELTA = 1
TEMPORALITY_CUMULATIVE = 2


class MetricRecord(NamedTuple):
    """One ingested metric data point, projected to the detector's needs.

    ``kind`` ∈ {"gauge", "sum"}; histogram points are projected to two
    sum records (``{name}_count``, ``{name}_sum``) matching the
    Prometheus naming the rest of the stack uses.
    """

    service: str
    name: str
    value: float
    kind: str = "sum"
    monotonic: bool = True
    temporality: int = TEMPORALITY_CUMULATIVE
    time_unix_nano: int = 0


def _u64_to_double(raw: int) -> float:
    return struct.unpack("<d", raw.to_bytes(8, "little"))[0]


def _u64_to_i64(raw: int) -> int:
    return raw - (1 << 64) if raw >= (1 << 63) else raw


def _number_point_value(buf: bytes) -> tuple[float | None, int]:
    """NumberDataPoint → (value, time_unix_nano); value None if absent."""
    dp = wire.scan_fields(buf)
    t = int(wire.first(dp, 3, 0) or 0)
    raw_d = wire.first(dp, 4)
    if raw_d is not None:
        return _u64_to_double(int(raw_d)), t
    raw_i = wire.first(dp, 6)
    if raw_i is not None:
        return float(_u64_to_i64(int(raw_i))), t
    return None, t


def _service_of_resource(rm: dict) -> str:
    res_buf = wire.first(rm, 1)
    if res_buf:
        res = wire.scan_fields(res_buf)
        for kv_buf in res.get(1, []):
            kv = wire.scan_fields(kv_buf)
            if wire.first(kv, 1) == b"service.name":
                val_buf = wire.first(kv, 2)
                if isinstance(val_buf, bytes):
                    sv = wire.first(wire.scan_fields(val_buf), 1)
                    if isinstance(sv, bytes):
                        return sv.decode("utf-8", "replace")
    return "unknown"


def decode_metrics_request(payload: bytes) -> list[MetricRecord]:
    """ExportMetricsServiceRequest protobuf → MetricRecords."""
    records: list[MetricRecord] = []
    req = wire.scan_fields(payload)
    for rm_buf in req.get(1, []):
        rm = wire.scan_fields(rm_buf)
        service = _service_of_resource(rm)
        for sm_buf in rm.get(2, []):
            sm = wire.scan_fields(sm_buf)
            for m_buf in sm.get(2, []):
                _decode_metric(m_buf, service, records)
    return records


def _decode_metric(m_buf: bytes, service: str, out: list[MetricRecord]) -> None:
    m = wire.scan_fields(m_buf)
    name_raw = wire.first(m, 1, b"")
    name = name_raw.decode("utf-8", "replace") if isinstance(name_raw, bytes) else ""
    gauge_buf = wire.first(m, 5)
    sum_buf = wire.first(m, 7)
    hist_buf = wire.first(m, 9)
    if gauge_buf:
        g = wire.scan_fields(gauge_buf)
        for dp_buf in g.get(1, []):
            val, t = _number_point_value(dp_buf)
            if val is not None:
                out.append(MetricRecord(service, name, val, kind="gauge",
                                        monotonic=False,
                                        temporality=TEMPORALITY_UNSPECIFIED,
                                        time_unix_nano=t))
    elif sum_buf:
        s = wire.scan_fields(sum_buf)
        temporality = int(wire.first(s, 2, 0) or 0)
        monotonic = bool(wire.first(s, 3, 0) or 0)
        for dp_buf in s.get(1, []):
            val, t = _number_point_value(dp_buf)
            if val is not None:
                out.append(MetricRecord(service, name, val, kind="sum",
                                        monotonic=monotonic,
                                        temporality=temporality,
                                        time_unix_nano=t))
    elif hist_buf:
        h = wire.scan_fields(hist_buf)
        temporality = int(wire.first(h, 2, 0) or 0)
        for dp_buf in h.get(1, []):
            dp = wire.scan_fields(dp_buf)
            t = int(wire.first(dp, 3, 0) or 0)
            count = wire.first(dp, 4)
            total = wire.first(dp, 5)
            if count is not None:
                out.append(MetricRecord(service, name + "_count", float(int(count)),
                                        kind="sum", monotonic=True,
                                        temporality=temporality,
                                        time_unix_nano=t))
            if total is not None:
                out.append(MetricRecord(service, name + "_sum",
                                        _u64_to_double(int(total)),
                                        kind="sum", monotonic=True,
                                        temporality=temporality,
                                        time_unix_nano=t))


def decode_metrics_request_json(payload: bytes) -> list[MetricRecord]:
    """JSON-encoded OTLP metrics (the collector's otlphttp json mode)."""
    doc = json.loads(payload)
    records: list[MetricRecord] = []
    temp_enum = {
        "AGGREGATION_TEMPORALITY_DELTA": TEMPORALITY_DELTA,
        "AGGREGATION_TEMPORALITY_CUMULATIVE": TEMPORALITY_CUMULATIVE,
    }

    def point_value(dp: dict) -> float | None:
        if "asDouble" in dp:
            return float(dp["asDouble"])
        if "asInt" in dp:
            return float(int(dp["asInt"]))
        return None

    for rm in doc.get("resourceMetrics", []):
        service = "unknown"
        for attr in rm.get("resource", {}).get("attributes", []):
            if attr.get("key") == "service.name":
                service = attr.get("value", {}).get("stringValue", service)
        for sm in rm.get("scopeMetrics", []):
            for m in sm.get("metrics", []):
                name = m.get("name", "")
                if "gauge" in m:
                    for dp in m["gauge"].get("dataPoints", []):
                        val = point_value(dp)
                        if val is not None:
                            records.append(MetricRecord(
                                service, name, val, kind="gauge",
                                monotonic=False,
                                temporality=TEMPORALITY_UNSPECIFIED,
                                time_unix_nano=int(dp.get("timeUnixNano", 0))))
                elif "sum" in m:
                    s = m["sum"]
                    raw_t = s.get("aggregationTemporality", 0)
                    temporality = (
                        int(raw_t) if isinstance(raw_t, int)
                        else temp_enum.get(raw_t, 0)
                    )
                    for dp in s.get("dataPoints", []):
                        val = point_value(dp)
                        if val is not None:
                            records.append(MetricRecord(
                                service, name, val, kind="sum",
                                monotonic=bool(s.get("isMonotonic", False)),
                                temporality=temporality,
                                time_unix_nano=int(dp.get("timeUnixNano", 0))))
                elif "histogram" in m:
                    h = m["histogram"]
                    raw_t = h.get("aggregationTemporality", 0)
                    temporality = (
                        int(raw_t) if isinstance(raw_t, int)
                        else temp_enum.get(raw_t, 0)
                    )
                    for dp in h.get("dataPoints", []):
                        t = int(dp.get("timeUnixNano", 0))
                        if "count" in dp:
                            records.append(MetricRecord(
                                service, name + "_count",
                                float(int(dp["count"])), kind="sum",
                                monotonic=True, temporality=temporality,
                                time_unix_nano=t))
                        if "sum" in dp:
                            records.append(MetricRecord(
                                service, name + "_sum", float(dp["sum"]),
                                kind="sum", monotonic=True,
                                temporality=temporality, time_unix_nano=t))
    return records


# --- encoding: registry snapshot → ExportMetricsServiceRequest ---------


def _encode_string_attr(field_no: int, key: str, value: str) -> bytes:
    any_value = wire.encode_len(1, value.encode())
    kv = wire.encode_len(1, key.encode()) + wire.encode_len(2, any_value)
    return wire.encode_len(field_no, kv)


def _encode_number_point(value: float, t_ns: int, start_ns: int = 0) -> bytes:
    dp = b""
    if start_ns:
        dp += wire.encode_fixed64(2, start_ns)
    dp += wire.encode_fixed64(3, t_ns)
    dp += wire.encode_double(4, float(value))
    return dp


def encode_metrics_request(
    service_metrics: Iterable[tuple[str, Iterable[tuple[str, float, bool]]]],
    t_ns: int,
    start_ns: int = 0,
) -> bytes:
    """Build an ExportMetricsServiceRequest.

    ``service_metrics`` yields ``(service_name, [(metric_name, value,
    is_counter), ...])``; counters encode as cumulative monotonic Sums,
    the rest as Gauges. One resource per service, one scope per
    resource — the shape every OTLP SDK produces.
    """
    rms = b""
    for service, metrics in service_metrics:
        resource = _encode_string_attr(1, "service.name", service)
        ms = b""
        for name, value, is_counter in metrics:
            point = wire.encode_len(1, _encode_number_point(value, t_ns, start_ns))
            if is_counter:
                body = (
                    point
                    + wire.encode_int(2, TEMPORALITY_CUMULATIVE)
                    + wire.encode_int(3, 1)  # is_monotonic
                )
                metric = wire.encode_len(1, name.encode()) + wire.encode_len(7, body)
            else:
                metric = wire.encode_len(1, name.encode()) + wire.encode_len(
                    5, point
                )
            ms += wire.encode_len(2, metric)
        rm = wire.encode_len(1, resource)
        if ms:
            # One ScopeMetrics submessage whose repeated `metrics`
            # fields are ``ms``.
            rm += wire.encode_len(2, ms)
        rms += wire.encode_len(1, rm)
    return rms


def registry_to_request(
    jobs: Iterable[tuple[str, "object"]], t_ns: int, start_ns: int = 0
) -> bytes:
    """Encode (job, MetricRegistry) pairs — label sets fold by summing.

    Per-label-set series of one counter collapse into one per-service
    total (counter rates are what the detection head consumes; label
    cardinality stays host-side in the TSDB). Gauges fold by max — for
    up/status gauges a max is the natural disjunction.
    """
    payload = []
    for job, registry in jobs:
        counters, gauges = registry.snapshot()
        folded: dict[str, float] = {}
        for (name, _labels), value in counters.items():
            folded[name] = folded.get(name, 0.0) + value
        rows = [(name, value, True) for name, value in sorted(folded.items())]
        gfold: dict[str, float] = {}
        for (name, _labels), value in gauges.items():
            gfold[name] = max(gfold.get(name, float("-inf")), value)
        rows += [(name, value, False) for name, value in sorted(gfold.items())]
        payload.append((job, rows))
    return encode_metrics_request(payload, t_ns, start_ns)


class OtlpHttpMetricsExporter(_ExporterBase):
    """POSTs registry snapshots to an OTLP/HTTP ``/v1/metrics`` endpoint.

    Subscribe on ``Collector.metrics_exporters``: called after each
    scrape cycle with the scraped (job, registry) pairs, it serialises
    one ExportMetricsServiceRequest and enqueues it on the shared
    background poster — ``Collector.pump`` often runs under the
    gateway's request lock, so the network POST must never block the
    caller (see ``otlp_export.BackgroundPoster`` for the queue/drop
    semantics). Failures count, not raise.
    """

    def __init__(self, endpoint: str, timeout_s: float = 2.0, queue_max: int = 16):
        from .otlp_export import BackgroundPoster, grpc_send, split_endpoint

        scheme, target = split_endpoint(endpoint)
        if scheme == "grpc":
            # OTLP/gRPC (the collector exporter default); same sender.
            self._poster = BackgroundPoster(
                target, "application/grpc", timeout_s, queue_max,
                send=grpc_send(target, "metrics", timeout_s),
            )
        else:
            target = target.rstrip("/")
            if not target.endswith("/v1/metrics"):
                target += "/v1/metrics"
            self._poster = BackgroundPoster(
                target, "application/x-protobuf", timeout_s, queue_max
            )

    def __call__(self, now: float, jobs: list) -> None:
        self._poster.submit(registry_to_request(jobs, t_ns=int(now * 1e9)))
