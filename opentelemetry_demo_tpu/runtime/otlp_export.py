"""OTLP/HTTP export: spans (and the shared background poster).

The compose topology runs the shop and the anomaly detector as separate
processes wired by the collector's ``otlphttp`` exporters
(/root/reference/docker-compose.yml:226-256 fraud-detection pattern;
otelcol-config.yml:85-92 exporter blocks). This module is the shop-side
half of that seam: encode SpanRecords into ExportTraceServiceRequest
protobuf and POST them to the sidecar's ``/v1/traces`` — from a
background thread, because exporters get invoked under the gateway's
request lock and the network must never stall it (the same rule as
``otlp_metrics.OtlpHttpMetricsExporter``).
"""

from __future__ import annotations

import collections
import random
import threading
import time
import urllib.error
import urllib.request

from . import wire
from .tensorize import SpanRecord


class RetryLater(Exception):
    """The sink said "not now" — a RETRYABLE refusal, not an error.

    Raised by send hooks on HTTP 429 / gRPC ``RESOURCE_EXHAUSTED`` (the
    saturated receiver's refusal). The poster keeps the body, backs off
    (honoring ``retry_after_s`` when the server sent one), and retries —
    instead of counting an error and hammering a peer that just asked
    for air.
    """

    def __init__(self, retry_after_s: float | None = None):
        super().__init__(
            f"sink saturated (retry after {retry_after_s or 'unspecified'}s)"
        )
        self.retry_after_s = retry_after_s


def _parse_retry_after(value: str | None) -> float | None:
    """Retry-After header → seconds (delta-seconds form only; an
    HTTP-date from a saturated peer isn't worth a date parser here)."""
    if not value:
        return None
    try:
        return max(float(value), 0.0)
    except ValueError:
        return None


class BackgroundPoster:
    """Bounded queue + one sender thread; drop-OLDEST on overflow.

    Drop-oldest matches exporter semantics for both signals: metric
    snapshots are cumulative (a later export supersedes a lost one) and
    span batches are telemetry, where freshness beats completeness when
    the sink cannot keep up (the reference collector's sending_queue
    drops the same way).

    A sink that answers 429/``RESOURCE_EXHAUSTED`` (see
    :class:`RetryLater`) is NOT an error: the body goes back to the
    queue head and the sender backs off — capped exponential with full
    jitter, floored at the server's Retry-After hint — while the
    bounded queue keeps absorbing (and drop-oldest keeps bounding)
    producer traffic. ``retries`` counts the refusals;
    ``queue_high_water`` records the deepest backlog since last read
    (``take_high_water``).
    """

    BACKOFF_BASE_S = 0.1
    BACKOFF_CAP_S = 5.0

    def __init__(self, endpoint: str, content_type: str,
                 timeout_s: float = 2.0, queue_max: int = 16,
                 send=None):
        """``send(body)`` overrides the default HTTP POST (e.g. a gRPC
        unary call); it runs on the sender thread and signals failure by
        raising (``RetryLater`` for a saturated sink)."""
        self.endpoint = endpoint
        self.content_type = content_type
        self.timeout_s = timeout_s
        self.sent = 0
        self.errors = 0
        self.dropped = 0
        self.retries = 0  # retryable refusals (429/RESOURCE_EXHAUSTED)
        self.queue_high_water = 0
        self._send = send or self._http_send
        self._queue: "collections.deque[bytes]" = collections.deque()
        self._queue_max = queue_max
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        # Backoff sleeps wait on THIS event (set by close()) so a
        # saturated sink never pins shutdown for a full backoff window.
        self._stop_event = threading.Event()
        self._consecutive_retries = 0
        self._thread: threading.Thread | None = None

    def _http_send(self, body: bytes) -> None:
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": self.content_type},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise RetryLater(
                    _parse_retry_after(e.headers.get("Retry-After"))
                ) from e
            raise

    def submit(self, body: bytes) -> None:
        with self._lock:
            if self._stop:
                # After close() the sender thread has exited (or is
                # exiting); enqueueing would black-hole the body while
                # the counters report healthy. Count it as dropped so a
                # misused exporter is visible in its own stats.
                self.dropped += 1
                return
            self._queue.append(body)
            while len(self._queue) > self._queue_max:
                self._queue.popleft()
                self.dropped += 1
            self.queue_high_water = max(
                self.queue_high_water, len(self._queue)
            )
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._send_loop, name="otlp-export", daemon=True
                )
                self._thread.start()
        self._wake.set()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def take_high_water(self) -> int:
        """Deepest backlog since the last call (window-reset read)."""
        with self._lock:
            peak = self.queue_high_water
            self.queue_high_water = len(self._queue)
            return peak

    def _retry_delay(self, hint: float | None) -> float:
        """Capped exponential with full jitter, floored at the server's
        Retry-After hint — never shorter than asked, never unbounded."""
        n = self._consecutive_retries
        self._consecutive_retries += 1
        base = min(self.BACKOFF_BASE_S * (2.0 ** min(n, 8)), self.BACKOFF_CAP_S)
        delay = base * (0.5 + random.random())  # jitter in [0.5, 1.5)
        if hint:
            delay = max(delay, min(hint, self.BACKOFF_CAP_S))
        return delay

    def _send_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._queue:
                        self._idle.set()
                        if self._stop:
                            return
                        break
                    self._idle.clear()
                    body = self._queue.popleft()
                try:
                    self._send(body)
                    self.sent += 1
                    self._consecutive_retries = 0
                except RetryLater as e:
                    self.retries += 1
                    with self._lock:
                        stop = self._stop
                        if stop or len(self._queue) >= self._queue_max:
                            # Shutting down, or the queue refilled while
                            # we were refused: the body has nowhere to
                            # wait — same drop-oldest outcome.
                            self.dropped += 1
                        else:
                            self._queue.appendleft(body)
                    if not stop:
                        self._stop_event.wait(
                            self._retry_delay(e.retry_after_s)
                        )
                except Exception:  # noqa: BLE001 — the sender loop is
                    # the only drain of the queue: any transport fault
                    # is counted and the next batch retried, never a
                    # dead exporter thread.
                    self.errors += 1

    def flush(self, timeout_s: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                empty = not self._queue
            if empty and self._idle.is_set():
                return True
            self._wake.set()
            time.sleep(0.005)
        return False

    def close(self) -> None:
        with self._lock:
            self._stop = True
            thread = self._thread
        self._wake.set()
        self._stop_event.set()  # abort any in-progress backoff sleep
        if thread is not None:
            thread.join(timeout=self.timeout_s + 1.0)
        closer = getattr(self._send, "close", None)
        if closer is not None:
            closer()


def _norm_trace_id(trace_id: bytes | int) -> bytes:
    if isinstance(trace_id, int):
        return trace_id.to_bytes(16, "big", signed=False)
    raw = bytes(trace_id)
    return (raw + b"\0" * 16)[:16]


def _kv_str(key: str, value: str) -> bytes:
    any_value = wire.encode_len(1, value.encode())
    return wire.encode_len(1, key.encode()) + wire.encode_len(2, any_value)


def encode_export_request(
    records: list[SpanRecord], t_ns: int | None = None
) -> bytes:
    """SpanRecords → ExportTraceServiceRequest protobuf.

    The inverse of ``otlp.decode_export_request`` over the fields this
    framework carries (service → resource attr, duration → start/end,
    error → status code 2, monitored attr → ``app.product.id``) —
    round-trip pinned by tests. One resource block per service, spans in
    input order within each.
    """
    if t_ns is None:
        t_ns = int(time.time() * 1e9)
    by_service: dict[str, list[SpanRecord]] = {}
    for rec in records:
        by_service.setdefault(rec.service, []).append(rec)
    out = b""
    for service, recs in by_service.items():
        resource = wire.encode_len(1, _kv_str("service.name", service))
        spans = b""
        for rec in recs:
            end = t_ns
            start = end - int(max(rec.duration_us, 0.0) * 1000.0)
            span = (
                wire.encode_len(1, _norm_trace_id(rec.trace_id))
                + wire.encode_len(5, (rec.name or "span").encode())
                + wire.encode_fixed64(7, start)
                + wire.encode_fixed64(8, end)
            )
            if rec.attr:
                span += wire.encode_len(9, _kv_str("app.product.id", rec.attr))
            # Span.events (field 11): Event{time_unix_nano=1, name=2,
            # attributes=3} per opentelemetry-proto trace/v1. Offsets
            # are span-start-relative in SpanRecord; the wire wants
            # absolute nanos.
            for ev in rec.events:
                ev_body = (
                    wire.encode_fixed64(
                        1, start + int(max(ev.ts_offset_us, 0.0) * 1000.0)
                    )
                    + wire.encode_len(2, ev.name.encode())
                )
                for k, v in ev.attrs:
                    ev_body += wire.encode_len(3, _kv_str(k, str(v)))
                span += wire.encode_len(11, ev_body)
            if rec.is_error:
                span += wire.encode_len(15, wire.encode_int(3, 2))  # ERROR
            spans += wire.encode_len(2, span)
        # One ScopeSpans submessage whose repeated `spans` fields are
        # ``spans`` (field 2 of ScopeSpans == field 2 of ResourceSpans'
        # entry — wrap ONCE).
        rs = wire.encode_len(1, resource) + wire.encode_len(2, spans)
        out += wire.encode_len(1, rs)
    return out


class grpc_send:
    """A ``send`` hook for :class:`BackgroundPoster` that ships bodies
    over OTLP/gRPC (the collector exporter default) instead of HTTP.
    ``signal`` ∈ {"traces", "metrics", "logs"}. Lazily opens the channel on the
    sender thread's first call; :meth:`close` (invoked by the poster's
    ``close``) shuts the channel down — grpcio channels are not
    reliably collected by GC and would leak sockets/poller threads."""

    def __init__(self, target: str, signal: str, timeout_s: float = 2.0):
        self._target = target
        self._signal = signal
        self._timeout_s = timeout_s
        self._channel = None
        self._fn = None

    def __call__(self, body: bytes) -> None:
        import grpc

        if self._fn is None:
            from .otlp_grpc import LOGS_EXPORT, METRICS_EXPORT, TRACE_EXPORT

            self._channel = grpc.insecure_channel(self._target)
            path = {
                "traces": TRACE_EXPORT,
                "metrics": METRICS_EXPORT,
                "logs": LOGS_EXPORT,
            }[self._signal]
            self._fn = self._channel.unary_unary(
                path, request_serializer=None, response_deserializer=None
            )
        try:
            self._fn(body, timeout=self._timeout_s)
        except grpc.RpcError as e:
            code = e.code() if callable(getattr(e, "code", None)) else None
            if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                # The receiver's saturation refusal (otlp_grpc puts the
                # hint in trailing metadata): retryable, back off.
                hint = None
                md = getattr(e, "trailing_metadata", None)
                for key, value in (md() if callable(md) else ()) or ():
                    if key == "retry-after-s":
                        hint = _parse_retry_after(value)
                raise RetryLater(hint) from e
            raise

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._fn = None


def split_endpoint(endpoint: str) -> tuple[str, str]:
    """("grpc"|"http", target) from an exporter endpoint string.

    ``grpc://host:4317`` selects the gRPC transport; anything else is
    OTLP/HTTP (scheme defaulted to http:// when absent)."""
    if endpoint.startswith("grpc://"):
        # A gRPC channel target is host:port — tolerate the trailing
        # slash endpoint env vars commonly carry.
        return "grpc", endpoint[len("grpc://"):].rstrip("/")
    if "://" not in endpoint:
        endpoint = "http://" + endpoint
    return "http", endpoint


class _ExporterBase:
    """Counters/flush/close surface shared by the concrete exporters."""

    _poster: BackgroundPoster

    @property
    def sent(self) -> int:
        return self._poster.sent

    @property
    def errors(self) -> int:
        return self._poster.errors

    @property
    def dropped(self) -> int:
        return self._poster.dropped

    @property
    def retries(self) -> int:
        return self._poster.retries

    def queue_depth(self) -> int:
        return self._poster.queue_depth()

    def publish_stats(self, registry, signal: str = "traces") -> None:
        """Mirror the sender-queue counters into a MetricRegistry:
        ``anomaly_export_dropped_total{signal=}`` (drop-oldest losses —
        the path PR 1 documented but left invisible) and
        ``anomaly_export_queue_depth{signal=}`` (the high-water mark of
        the backlog since the last publish, so a between-scrapes burst
        still shows). Call on any periodic cadence — delta tracking is
        internal, double publishing never double counts."""
        from ..telemetry import metrics as tm

        dropped = self._poster.dropped
        delta = dropped - getattr(self, "_dropped_published", 0)
        if delta:
            registry.counter_add(
                tm.ANOMALY_EXPORT_DROPPED, float(delta), signal=signal
            )
        self._dropped_published = dropped
        registry.gauge_set(
            tm.ANOMALY_EXPORT_QUEUE_DEPTH,
            float(self._poster.take_high_water()),
            signal=signal,
        )

    def flush(self, timeout_s: float = 5.0) -> bool:
        return self._poster.flush(timeout_s)

    def close(self) -> None:
        self._poster.close()


def make_traces_poster(
    endpoint: str, timeout_s: float = 2.0, queue_max: int = 64
) -> BackgroundPoster:
    """A BackgroundPoster shipping ExportTraceServiceRequest bodies to
    an OTLP endpoint — ``grpc://host:port`` selects the gRPC
    transport, anything else posts to ``/v1/traces``. The ONE
    trace-transport selection, shared by the shop-side span exporter
    and the detector's self-tracer (runtime.selftrace)."""
    scheme, target = split_endpoint(endpoint)
    if scheme == "grpc":
        return BackgroundPoster(
            target, "application/grpc", timeout_s, queue_max,
            send=grpc_send(target, "traces", timeout_s),
        )
    target = target.rstrip("/")
    if not target.endswith("/v1/traces"):
        target += "/v1/traces"
    return BackgroundPoster(
        target, "application/x-protobuf", timeout_s, queue_max
    )


class OtlpHttpSpanExporter(_ExporterBase):
    """Subscribe on ``Collector.trace_exporters`` (or a gateway's
    ``on_spans``): ships each span batch to an OTLP ``/v1/traces``
    endpoint from the background sender. ``grpc://host:port`` endpoints
    ship over OTLP/gRPC instead (same callable surface)."""

    def __init__(self, endpoint: str, timeout_s: float = 2.0, queue_max: int = 64):
        self._poster = make_traces_poster(endpoint, timeout_s, queue_max)

    def __call__(self, now: float, records: list[SpanRecord]) -> None:
        if records:
            self._poster.submit(encode_export_request(records))


def encode_logs_request(docs, t_ns: int | None = None) -> bytes:
    """LogDocs → ExportLogsServiceRequest protobuf.

    The inverse of ``otlp.decode_logs_request`` over the fields the
    framework's log pipeline carries (otelcol-config.yml:128-131 is the
    reference leg this crosses): one ResourceLogs block per service,
    LogRecord{time_unix_nano=1, severity_number=2, severity_text=3,
    body=5, attributes=6, trace_id=9}. ``doc.ts`` is virtual-clock seconds; the wire wants
    wall nanos, so ``t_ns`` (default now) stamps the batch and per-doc
    ts rides as the relative offset from the newest doc.
    """
    if t_ns is None:
        t_ns = int(time.time() * 1e9)
    by_service: dict[str, list] = {}
    for doc in docs:
        by_service.setdefault(doc.service, []).append(doc)
    # One anchor across the whole batch (not per service): the newest
    # doc maps to t_ns and every other doc keeps its relative offset,
    # so cross-service ordering survives the wall-clock re-stamping.
    newest = max((d.ts for d in docs), default=0.0)
    # SeverityNumber (field 2) is the spec's PRIMARY severity field —
    # a backend keying on it must not see UNSPECIFIED; the store's
    # 5-level scale maps to the canonical band floors.
    sev_num = {"DEBUG": 5, "INFO": 9, "WARN": 13, "ERROR": 17, "FATAL": 21}
    out = b""
    for service, items in by_service.items():
        resource = wire.encode_len(1, _kv_str("service.name", service))
        records = b""
        for doc in items:
            sev = doc.severity or "INFO"
            rec = (
                wire.encode_fixed64(1, max(t_ns + int((doc.ts - newest) * 1e9), 0))
                + wire.encode_int(2, sev_num.get(sev, 9))
                + wire.encode_len(3, sev.encode())
                + wire.encode_len(
                    5, wire.encode_len(1, (doc.body or "").encode())
                )
            )
            for k, v in (doc.attrs or {}).items():
                rec += wire.encode_len(6, _kv_str(k, str(v)))
            if doc.trace_id:
                rec += wire.encode_len(9, _norm_trace_id(doc.trace_id))
            records += wire.encode_len(2, rec)
        rl = wire.encode_len(1, resource) + wire.encode_len(2, records)
        out += wire.encode_len(1, rl)
    return out


class OtlpHttpLogsExporter(_ExporterBase):
    """Subscribe on ``Collector.log_exporters``: ships log batches to an
    OTLP ``/v1/logs`` endpoint — the collector's third-signal leg
    (otelcol-config.yml:128-131; in-proc the shop's collector indexes
    into its own LogStore, this exporter extends the same flow across
    process boundaries to the sidecar daemon). ``grpc://`` endpoints
    ship over OTLP/gRPC."""

    def __init__(self, endpoint: str, timeout_s: float = 2.0, queue_max: int = 64):
        scheme, target = split_endpoint(endpoint)
        if scheme == "grpc":
            self._poster = BackgroundPoster(
                target, "application/grpc", timeout_s, queue_max,
                send=grpc_send(target, "logs", timeout_s),
            )
        else:
            target = target.rstrip("/")
            if not target.endswith("/v1/logs"):
                target += "/v1/logs"
            self._poster = BackgroundPoster(
                target, "application/x-protobuf", timeout_s, queue_max
            )

    def __call__(self, now: float, docs: list) -> None:
        if docs:
            self._poster.submit(encode_logs_request(docs))
