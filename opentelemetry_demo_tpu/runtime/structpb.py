"""google.protobuf.Struct codec over the hand-rolled wire scanner.

flagd's evaluation protocol carries the evaluation context and flag
metadata as ``google.protobuf.Struct`` (schemas.flagd.dev evaluation
service — the :8013 surface every OpenFeature flagd provider dials).
This codec maps Struct ⇄ plain Python (dict/list/str/float/bool/None),
the same JSON model ``json.loads`` produces, so the flag evaluator
works on native values.

Wire shapes (struct.proto):
  Struct    { map<string, Value> fields = 1; }  — map entry: key=1, value=2
  Value     { null_value=1 | number_value=2(double) | string_value=3 |
              bool_value=4 | struct_value=5 | list_value=6 }
  ListValue { repeated Value values = 1; }
"""

from __future__ import annotations

import struct as _struct

from . import wire


def decode_struct(buf: bytes) -> dict:
    out: dict = {}
    if not buf:
        return out
    f = wire.scan_fields(buf)
    for entry in f.get(1, []):
        if not isinstance(entry, bytes):
            continue
        ef = wire.scan_fields(entry)
        key = wire.first(ef, 1, b"")
        val = wire.first(ef, 2, b"")
        if isinstance(key, bytes):
            out[key.decode("utf-8", "replace")] = decode_value(
                val if isinstance(val, bytes) else b""
            )
    return out


def decode_value(buf: bytes):
    f = wire.scan_fields(buf)
    # proto3 oneof: last set field wins; scan in declaration order and
    # keep the highest-numbered occurrence present.
    if 6 in f:
        lv = f[6][-1]
        lf = wire.scan_fields(lv if isinstance(lv, bytes) else b"")
        return [
            decode_value(v) for v in lf.get(1, []) if isinstance(v, bytes)
        ]
    if 5 in f:
        sv = f[5][-1]
        return decode_struct(sv if isinstance(sv, bytes) else b"")
    if 4 in f:
        return bool(f[4][-1])
    if 3 in f:
        raw = f[3][-1]
        return raw.decode("utf-8", "replace") if isinstance(raw, bytes) else ""
    if 2 in f:
        raw = f[2][-1]
        if isinstance(raw, int):  # fixed64 little-endian bits
            return _struct.unpack("<d", raw.to_bytes(8, "little"))[0]
        return 0.0
    return None  # null_value or empty


def encode_value(v) -> bytes:
    if v is None:
        return wire.encode_int(1, 0)
    if isinstance(v, bool):  # before int: bool subclasses int
        return wire.encode_int(4, 1 if v else 0)
    if isinstance(v, (int, float)):
        return wire.encode_double(2, float(v))  # oneof: always emitted
    if isinstance(v, str):
        return wire.encode_len(3, v.encode("utf-8"))
    if isinstance(v, dict):
        return wire.encode_len(5, encode_struct(v))
    if isinstance(v, (list, tuple)):
        body = b"".join(wire.encode_len(1, encode_value(x)) for x in v)
        return wire.encode_len(6, body)
    raise TypeError(f"unmappable Struct value type {type(v).__name__}")


def encode_struct(d: dict) -> bytes:
    out = b""
    for key, val in d.items():
        entry = wire.encode_len(1, str(key).encode("utf-8"))
        entry += wire.encode_len(2, encode_value(val))
        out += wire.encode_len(1, entry)
    return out
