"""Counterfactual pre-flight: prove a mitigation on replayed history.

PR 13's controller acts the moment hysteresis clears — it cannot know
whether the mitigation it is about to fire would actually have helped.
This module composes three shipped tiers into the control loop ROADMAP
item 4 calls for: before :class:`~.remediation.RemediationController`
lets an actuator write, a :class:`ShadowVerifier` replays the last N
minutes of recorded span frames (the PR 11 ``HistoryStore`` span
capture, read through the new header-only
``HistoryReader.span_records`` window API) through a FRESH, real
``DetectorPipeline`` at replay speed (virtual-time clock injection,
the replaybench machinery) **with the proposed mitigation applied as a
transform on the replayed stream**, and only releases the act if the
shadow's own EWMA/CUSUM + cardinality heads clear in the verification
tail. A mitigation that would NOT have helped is refused — with
flight-recorder evidence (``kind=preflight_refused``), the episode
parked back in PENDING, and the budget token refunded.

Contracts, in the order they are pinned:

- **One pipeline builder.** :func:`build_shadow_pipeline` is the
  single constructor both this verifier and ``runtime.replaybench``
  use, so a shadow replay of a recorded window is bit-identical to
  ``replaybench`` verdicts *by construction* (same admission, same
  tensorize/pack, same donated device step, same
  ``round(t_batch, 6)``-keyed flag tuples) — any future drift breaks
  both surfaces at once, loudly.
- **Live-state isolation.** The shadow pipeline runs concurrently
  with the live daemon and must never touch live detector state: this
  module consumes ONLY a disk-backed ``HistoryReader`` plus a static
  ``DetectorConfig`` — the query.py discipline (no detector state, no
  dispatch lock), pinned by sanitycheck and the suite's AST scan.
- **Compile off the clock.** A throwaway pipeline at the same
  geometry warms the XLA executable cache before the timed loop, so
  the measured speedup (gated ≥ ``ANOMALY_SHADOW_RATE``, the
  replaybench ≥10× wall discipline) and the verification deadline
  both measure REPLAY, not one-time jit.
- **Fail closed.** Too few recorded frames, a wall-deadline miss, or
  any replay error all refuse the act (reason-coded): a verifier that
  cannot prove the mitigation helps must not release it.

Knob registry: ``utils.config.SHADOW_KNOBS`` (ENABLE defaults OFF —
pre-flight gating is strictly opt-in like every controller tier).
Bench: the shadow leg of ``runtime/mitigbench.py`` (``make
shadowbench``) proves both verdict directions live and pins the
bit-identity + speedup gates. Suite: tests/test_shadow.py.
"""

from __future__ import annotations

import time
from typing import Callable, NamedTuple

import numpy as np

from ..models.detector import AnomalyDetector, DetectorConfig
from .history import SPAN_CAPTURE_COLUMNS, HistoryReader
from .pipeline import DetectorPipeline
from .tensorize import SpanColumns

# Refusal reason vocabulary (the flight evidence's ``reason=`` label).
REASON_CLEARED = "cleared"
REASON_STILL_FLAGGED = "still_flagged"
REASON_DEADLINE = "deadline"
REASON_INSUFFICIENT = "insufficient_records"
REASON_ERROR = "error"

# Pre-flight act→verdict histogram ladder (seconds): a warm shadow
# replay of a few-minute window costs tens of milliseconds to a few
# seconds; the deadline knob caps the far end.
PREFLIGHT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0)


class PreflightVerdict(NamedTuple):
    """One shadow replay's answer: would this mitigation have helped?

    ``verdicts`` carries the replayed per-batch flag tuples keyed by
    ``round(t_batch, 6)`` — the bit-identity pinning surface (stripped
    from flight evidence; the scalars tell the postmortem story)."""

    would_help: bool
    reason: str
    batches: int
    records: int
    corrupt: int
    virtual_s: float
    wall_s: float
    speedup: float
    flagged_tail: int
    clear_tail: int
    verdicts: dict


def refused(reason: str, **kw) -> PreflightVerdict:
    """A fail-closed verdict (no replay numbers beyond what's known)."""
    base = dict(
        would_help=False, reason=reason, batches=0, records=0,
        corrupt=0, virtual_s=0.0, wall_s=0.0, speedup=0.0,
        flagged_tail=0, clear_tail=0, verdicts={},
    )
    base.update(kw)
    return PreflightVerdict(**base)


def build_shadow_pipeline(
    config: DetectorConfig, batch_size: int, collect: dict,
) -> tuple[AnomalyDetector, DetectorPipeline]:
    """THE pipeline constructor for replayed frames — shared with
    ``runtime.replaybench`` so shadow and replaybench verdicts can
    never drift: a fresh detector + pipeline whose ``on_report``
    stores ``round(t_batch, 6) → tuple(bool flags)``."""
    det = AnomalyDetector(config)

    def on_report(t_batch, report, flagged):
        collect[round(float(t_batch), 6)] = tuple(
            bool(f) for f in np.asarray(report.flags)
        )

    pipe = DetectorPipeline(det, on_report=on_report, batch_size=batch_size)
    return det, pipe


def suppress_transform(
    service_idx: int,
) -> Callable[[SpanColumns], SpanColumns]:
    """The mitigation-as-transform for a fault-flag disable: suppress
    the target service's fault columns on the replayed stream — errors
    zeroed, latency pulled to the batch's cross-service baseline (the
    other services' median) — modeling what the stream would have
    looked like had the faulty code path been off. Rows of every other
    service pass through untouched (a transform that edited healthy
    services could fake a clear)."""

    idx = int(service_idx)

    def transform(cols: SpanColumns) -> SpanColumns:
        svc = np.asarray(cols.svc)
        hit = svc == idx
        if not hit.any():
            return cols
        lat = np.asarray(cols.lat_us, dtype=np.float32).copy()
        err = np.asarray(cols.is_error, dtype=np.float32).copy()
        others = lat[~hit]
        baseline = float(np.median(others)) if others.size else float(
            np.median(lat)
        )
        lat[hit] = baseline
        err[hit] = 0.0
        return SpanColumns(
            svc=svc, lat_us=lat, is_error=err,
            trace_key=np.asarray(cols.trace_key),
            attr_crc=np.asarray(cols.attr_crc),
        )

    return transform


class ShadowVerifier:
    """Replays the recorded recent window through a fresh shadow
    pipeline with a proposed mitigation applied, and answers
    :class:`PreflightVerdict` — the controller's pre-flight gate.

    Disk-only by construction: reads frames through a
    :class:`~.history.HistoryReader` (corrupt records counted +
    skipped per the store's hop contract) and builds its own detector
    from the passed static config. Never names live state.
    """

    def __init__(
        self,
        reader: HistoryReader,
        config: DetectorConfig,
        batch_size: int = 256,
        window_s: float = 120.0,
        deadline_s: float = 5.0,
        rate_target: float = 10.0,
        min_records: int = 20,
        clear_tail: int = 4,
        flight=None,
        bundle_fn: Callable[[int], str | None] | None = None,
        now_fn: Callable[[], float] = time.time,
    ):
        self.reader = reader
        self.config = config
        self.batch_size = int(batch_size)
        self.window_s = float(window_s)
        self.deadline_s = float(deadline_s)
        self.rate_target = float(rate_target)
        self.min_records = max(int(min_records), 1)
        self.clear_tail = max(int(clear_tail), 1)
        self._flight = flight
        # Provenance citation hook (service index → newest evidence-
        # bundle id via the daemon): every refusal/verdict record names
        # the verdict it judged. Single-call discipline: verify() runs
        # on the controller's one worker thread, so the per-call stamp
        # below needs no lock.
        self._bundle_fn = bundle_fn
        self._bundle: str | None = None
        self._now_fn = now_fn
        self._warmed = False
        # Verifier-side tallies (the daemon exports the controller's;
        # these feed /healthz + tests).
        self.runs = 0
        self.refusals = 0

    # -- internals -----------------------------------------------------

    def _record(self, **detail) -> None:
        if self._flight is not None:
            self._flight.record("preflight", bundle=self._bundle, **detail)

    def _cols_of(self, arrays: dict) -> SpanColumns:
        return SpanColumns(**{
            name: np.asarray(arrays[name]) for name in SPAN_CAPTURE_COLUMNS
        })

    def _warm(self, sample: SpanColumns) -> None:
        """Populate the XLA executable cache off the clock with a
        throwaway pipeline at the same geometry (the repo's
        warmup-before-timing rule; the shadow detector proper starts
        cold and untouched)."""
        if self._warmed:
            return
        _det, pipe = build_shadow_pipeline(
            self.config, self.batch_size, {}
        )
        pipe.submit_columns(sample)
        pipe.pump(0.0)
        pipe.close()
        self._warmed = True

    # -- the gate ------------------------------------------------------

    def verify(
        self,
        service_idx: int,
        transform: Callable[[SpanColumns], SpanColumns] | None,
        now: float | None = None,
    ) -> PreflightVerdict:
        """Replay the last ``window_s`` of recorded frames with the
        mitigation transform applied; the act is releasable iff the
        flagged service's heads clear for the final ``clear_tail``
        replayed batches within the wall deadline."""
        self.runs += 1
        self._bundle = self._cite(int(service_idx))
        try:
            verdict = self._verify(int(service_idx), transform, now)
        except Exception as e:  # noqa: BLE001 — ANY replay fault
            # refuses the act (fail closed): a verifier that crashed
            # mid-replay has proven nothing about the mitigation.
            verdict = refused(REASON_ERROR)
            self._record(
                op="error", service_idx=int(service_idx),
                error=f"{type(e).__name__}: {e}",
            )
        if not verdict.would_help:
            self.refusals += 1
        return verdict

    def _cite(self, service_idx: int) -> str | None:
        if self._bundle_fn is None:
            return None
        try:
            return self._bundle_fn(service_idx)
        except Exception:  # noqa: BLE001 — citation is best-effort
            return None

    def _verify(
        self,
        service_idx: int,
        transform: Callable[[SpanColumns], SpanColumns] | None,
        now: float | None,
    ) -> PreflightVerdict:
        t_now = self._now_fn() if now is None else float(now)
        corrupt0 = self.reader.store.frames_corrupt
        recs = self.reader.span_records(t_now - self.window_s, t_now)
        if len(recs) < self.min_records:
            self._record(
                op="refused", reason=REASON_INSUFFICIENT,
                service_idx=service_idx, records=len(recs),
                min_records=self.min_records,
            )
            return refused(REASON_INSUFFICIENT, records=len(recs))

        # First decodable record warms the compile cache off-clock.
        sample = None
        for rec in recs:
            arrays, _t = self.reader.read_span_record(rec)
            if arrays is not None:
                sample = self._cols_of(arrays)
                break
        if sample is None:
            return refused(
                REASON_INSUFFICIENT, records=len(recs),
                corrupt=self.reader.store.frames_corrupt - corrupt0,
            )
        self._warm(sample)

        verdicts: dict = {}
        _det, pipe = build_shadow_pipeline(
            self.config, self.batch_size, verdicts
        )
        batches = 0
        t_first = t_last = None
        pending_t: float | None = None
        deadline_missed = False
        wall0 = time.perf_counter()
        try:
            # One-batch lookahead (the replaybench overlap regime):
            # batch k pumps while batch k+1 already sits in the queue.
            for rec in recs:
                if time.perf_counter() - wall0 > self.deadline_s:
                    deadline_missed = True
                    break
                arrays, t_batch = self.reader.read_span_record(rec)
                if arrays is None:
                    continue  # corrupt: counted by the store, skipped
                cols = self._cols_of(arrays)
                if transform is not None:
                    cols = transform(cols)
                pipe.submit_columns(cols)
                if pending_t is not None:
                    pipe.pump(pending_t)
                    batches += 1
                pending_t = t_batch
                t_first = t_batch if t_first is None else t_first
                t_last = t_batch
            if not deadline_missed and pending_t is not None:
                pipe.pump(pending_t)
                batches += 1
            pipe.drain()
        finally:
            pipe.close()
        wall = time.perf_counter() - wall0
        virtual = (
            (t_last - t_first) if t_first is not None and batches > 1
            else 0.0
        )
        speedup = virtual / max(wall, 1e-9)
        corrupt = self.reader.store.frames_corrupt - corrupt0

        if deadline_missed:
            self._record(
                op="refused", reason=REASON_DEADLINE,
                service_idx=service_idx, batches=batches,
                wall_s=round(wall, 4), deadline_s=self.deadline_s,
            )
            return refused(
                REASON_DEADLINE, batches=batches, records=len(recs),
                corrupt=corrupt, virtual_s=round(virtual, 3),
                wall_s=round(wall, 4), speedup=round(speedup, 2),
            )

        tail = sorted(verdicts)[-self.clear_tail:]
        flagged_tail = sum(
            1 for t in tail
            if service_idx < len(verdicts[t]) and verdicts[t][service_idx]
        )
        would_help = bool(tail) and flagged_tail == 0
        reason = REASON_CLEARED if would_help else REASON_STILL_FLAGGED
        self._record(
            op="verdict", reason=reason, service_idx=service_idx,
            batches=batches, flagged_tail=flagged_tail,
            speedup=round(speedup, 2), wall_s=round(wall, 4),
        )
        return PreflightVerdict(
            would_help=would_help, reason=reason, batches=batches,
            records=len(recs), corrupt=corrupt,
            virtual_s=round(virtual, 3), wall_s=round(wall, 4),
            speedup=round(speedup, 2), flagged_tail=flagged_tail,
            clear_tail=len(tail), verdicts=verdicts,
        )
