"""Host streaming runtime: span → tensor ingestion and device feeding.

The reference system's ingest seams are (a) the Kafka ``orders`` topic
consumed the way src/fraud-detection does
(/root/reference/src/fraud-detection/src/main/kotlin/frauddetection/main.kt:54-69)
and (b) the OTel collector's OTLP export pipeline
(/root/reference/src/otel-collector/otelcol-config.yml:120-131). Both
ultimately deliver *span-shaped records*; this package turns them into
fixed-width tensor batches (``tensorize``), feeds the device without
host syncs (``pipeline``), decodes at line rate through the parallel
ingest engine (``ingest_pool``: sharded decode workers, pooled
buffers, coalesced tensorize), and snapshots sketch state keyed to
stream offsets for resume (``checkpoint``).
"""

from .tensorize import SpanRecord, SpanTensorizer, TensorBatch

__all__ = ["SpanRecord", "SpanTensorizer", "TensorBatch"]
