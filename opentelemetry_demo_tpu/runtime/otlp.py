"""OTLP/HTTP span ingestion: the collector-export seam.

The reference collector fans traces out to exporters
(/root/reference/src/otel-collector/otelcol-config.yml:120-123); wiring
the detector in means adding one more ``otlphttp`` exporter pointing at
this receiver (deploy/otelcol-config-anomaly.yml does exactly that, the
pattern of the Jaeger exporter at :85-88). The receiver accepts
``POST /v1/traces`` with either protobuf (``application/x-protobuf``,
decoded by the schema-projection below) or JSON OTLP bodies, and turns
every span into a :class:`~..runtime.tensorize.SpanRecord`.

Field numbers follow the public OTLP protocol (opentelemetry-proto
trace/v1): ExportTraceServiceRequest{resource_spans=1},
ResourceSpans{resource=1, scope_spans=2}, Resource{attributes=1},
KeyValue{key=1, value=2}, AnyValue{string_value=1},
ScopeSpans{spans=2}, Span{trace_id=1, name=5, start_time_unix_nano=7,
end_time_unix_nano=8, attributes=9, events=11, status=15},
Span.Event{time_unix_nano=1, name=2, attributes=3}, Status{code=3}.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from . import native, wire
from .tensorize import SpanEvent, SpanRecord

_STATUS_ERROR = 2  # opentelemetry.proto.trace.v1.Status.StatusCode.ERROR

# Attribute keys worth monitoring for heavy hitters, in priority order —
# the ids the shop attaches to its spans (e.g. checkout's app.product.id,
# session ids from baggage; SURVEY.md §5 "Tracing").
MONITORED_ATTR_KEYS = (
    "app.product.id",
    "app.order.id",
    "app.session.id",
    "session.id",
)


def _as_msg(val) -> bytes:
    """An embedded-message field must arrive length-delimited: a
    corrupted tag can flip its wire type so the scanner hands back an
    int where sub-scan expects bytes. That is malformed wire data — a
    clean 400 verdict (WireError is a ValueError) — not a TypeError
    crash in the receiver; the fuzz suite pins this."""
    if not isinstance(val, bytes):
        raise wire.WireError(
            f"embedded message field carries wire type of {type(val).__name__}"
        )
    return val


def _anyvalue_str(buf: bytes) -> str | None:
    f = wire.scan_fields(buf)
    sv = wire.first(f, 1)
    if isinstance(sv, bytes):
        return sv.decode("utf-8", "replace")
    return None


def _attrs_to_dict(attr_bufs: list[bytes]) -> dict[str, str]:
    out: dict[str, str] = {}
    for kv_buf in attr_bufs:
        kv = wire.scan_fields(_as_msg(kv_buf))
        key = wire.first(kv, 1, b"")
        val_buf = wire.first(kv, 2)
        if key and isinstance(key, bytes) and isinstance(val_buf, bytes):
            sval = _anyvalue_str(val_buf)
            if sval is not None:
                out[key.decode("utf-8", "replace")] = sval
    return out


def _pick_attr(attrs: dict[str, str]) -> str | None:
    for key in MONITORED_ATTR_KEYS:
        if key in attrs:
            return attrs[key]
    return None


def decode_export_request(payload: bytes) -> list[SpanRecord]:
    """ExportTraceServiceRequest protobuf → SpanRecords."""
    records: list[SpanRecord] = []
    req = wire.scan_fields(payload)
    for rs_buf in req.get(1, []):
        rs = wire.scan_fields(_as_msg(rs_buf))
        service = "unknown"
        res_buf = wire.first(rs, 1)
        if res_buf:
            res = wire.scan_fields(_as_msg(res_buf))
            res_attrs = _attrs_to_dict(res.get(1, []))
            service = res_attrs.get("service.name", service)
        for ss_buf in rs.get(2, []):
            ss = wire.scan_fields(_as_msg(ss_buf))
            for span_buf in ss.get(2, []):
                records.append(_decode_span(_as_msg(span_buf), service))
    return records


def _decode_event(ev_buf: bytes, span_start_ns: int) -> SpanEvent:
    ev = wire.scan_fields(_as_msg(ev_buf))
    t_ns = int(wire.first(ev, 1, 0) or 0)
    name_raw = wire.first(ev, 2)
    name = (
        name_raw.decode("utf-8", "replace")
        if isinstance(name_raw, bytes) else ""
    )
    attrs = _attrs_to_dict(ev.get(3, []))
    return SpanEvent(
        name=name,
        ts_offset_us=max(t_ns - span_start_ns, 0) / 1000.0,
        attrs=tuple(attrs.items()),
    )


def _decode_span(span_buf: bytes, service: str) -> SpanRecord:
    sp = wire.scan_fields(span_buf)
    trace_id = wire.first(sp, 1, b"\0") or b"\0"
    start = int(wire.first(sp, 7, 0) or 0)
    end = int(wire.first(sp, 8, 0) or 0)
    duration_us = max(end - start, 0) / 1000.0
    attrs = _attrs_to_dict(sp.get(9, []))
    is_error = False
    status_buf = wire.first(sp, 15)
    if status_buf:
        st = wire.scan_fields(_as_msg(status_buf))
        is_error = int(wire.first(st, 3, 0) or 0) == _STATUS_ERROR
    name_raw = wire.first(sp, 5)
    return SpanRecord(
        service=service,
        duration_us=duration_us,
        trace_id=trace_id,
        is_error=is_error,
        attr=_pick_attr(attrs),
        name=name_raw.decode("utf-8", "replace") if isinstance(name_raw, bytes) else None,
        events=tuple(
            _decode_event(ev_buf, start) for ev_buf in sp.get(11, [])
        ),
    )


def decode_export_request_json(payload: bytes) -> list[SpanRecord]:
    """JSON-encoded OTLP (the collector's otlphttp json mode)."""
    doc = json.loads(payload)
    records: list[SpanRecord] = []
    for rs in doc.get("resourceSpans", []):
        service = "unknown"
        for attr in rs.get("resource", {}).get("attributes", []):
            if attr.get("key") == "service.name":
                service = attr.get("value", {}).get("stringValue", service)
        for ss in rs.get("scopeSpans", []):
            for sp in ss.get("spans", []):
                attrs = {
                    a.get("key"): a.get("value", {}).get("stringValue")
                    for a in sp.get("attributes", [])
                }
                start = int(sp.get("startTimeUnixNano", 0))
                end = int(sp.get("endTimeUnixNano", 0))
                events = tuple(
                    SpanEvent(
                        # str() guard: an explicit null/non-string name
                        # must not poison the store (obsui joins names).
                        name=str(ev.get("name") or ""),
                        ts_offset_us=max(
                            int(ev.get("timeUnixNano", 0) or 0) - start, 0
                        ) / 1000.0,
                        attrs=tuple(
                            (a.get("key"), a.get("value", {}).get("stringValue"))
                            for a in ev.get("attributes", [])
                            if a.get("key")
                            and a.get("value", {}).get("stringValue") is not None
                        ),
                    )
                    for ev in sp.get("events", [])
                )
                records.append(
                    SpanRecord(
                        service=service,
                        duration_us=max(end - start, 0) / 1000.0,
                        trace_id=bytes.fromhex(sp.get("traceId", "00")),
                        is_error=sp.get("status", {}).get("code") in (2, "STATUS_CODE_ERROR"),
                        attr=_pick_attr({k: v for k, v in attrs.items() if v}),
                        name=sp.get("name"),
                        events=events,
                    )
                )
    return records


def _severity_from_number(num: int) -> str | None:
    """OTLP SeverityNumber enum → the store's scale (None if unset).

    Spec bands: 1-4 TRACE, 5-8 DEBUG, 9-12 INFO, 13-16 WARN,
    17-20 ERROR, 21-24 FATAL."""
    if num <= 0:
        return None
    if num <= 8:
        return "DEBUG"
    if num <= 12:
        return "INFO"
    if num <= 16:
        return "WARN"
    if num <= 20:
        return "ERROR"
    return "FATAL"


def decode_logs_request(payload: bytes) -> list:
    """ExportLogsServiceRequest protobuf → LogDocs.

    The collector's third signal (otelcol-config.yml:128-131, logs →
    OpenSearch): ResourceLogs{resource=1, scope_logs=2},
    ScopeLogs{log_records=2}, LogRecord{time_unix_nano=1,
    severity_number=2, severity_text=3, body=5, attributes=6,
    trace_id=9, observed_time_unix_nano=11} per the public
    opentelemetry-proto logs/v1 field numbers. Spec fallbacks: severity
    text is optional (severity_number alone is valid), and
    time_unix_nano=0 means "use ObservedTimestamp".
    """
    from ..telemetry.logstore import LogDoc, normalize_severity

    docs: list = []
    req = wire.scan_fields(payload)
    for rl_buf in req.get(1, []):
        rl = wire.scan_fields(rl_buf)
        service = "unknown"
        res_buf = wire.first(rl, 1)
        if res_buf:
            res = wire.scan_fields(res_buf)
            service = _attrs_to_dict(res.get(1, [])).get("service.name", service)
        for sl_buf in rl.get(2, []):
            sl = wire.scan_fields(sl_buf)
            for lr_buf in sl.get(2, []):
                lr = wire.scan_fields(lr_buf)
                sev_raw = wire.first(lr, 3)
                sev_text = (
                    sev_raw.decode("utf-8", "replace")
                    if isinstance(sev_raw, bytes) and sev_raw else None
                )
                if sev_text is None:  # text optional: number-only is valid
                    sev_text = _severity_from_number(
                        int(wire.first(lr, 2, 0) or 0)
                    )
                body_buf = wire.first(lr, 5)
                body = _anyvalue_str(body_buf) if isinstance(body_buf, bytes) else None
                trace_id = wire.first(lr, 9)
                t_ns = int(wire.first(lr, 1, 0) or 0)
                if t_ns == 0:  # spec: fall back to ObservedTimestamp
                    t_ns = int(wire.first(lr, 11, 0) or 0)
                docs.append(LogDoc(
                    ts=t_ns / 1e9,
                    service=service,
                    severity=normalize_severity(sev_text),
                    body=body or "",
                    attrs=_attrs_to_dict(lr.get(6, [])),
                    trace_id=trace_id if isinstance(trace_id, bytes) and trace_id else None,
                ))
    return docs


def decode_logs_request_json(payload: bytes) -> list:
    """JSON-encoded OTLP logs (the collector's otlphttp json mode)."""
    from ..telemetry.logstore import LogDoc, normalize_severity

    doc = json.loads(payload)
    docs: list = []
    for rl in doc.get("resourceLogs", []):
        service = "unknown"
        for attr in rl.get("resource", {}).get("attributes", []):
            if attr.get("key") == "service.name":
                service = attr.get("value", {}).get("stringValue", service)
        for sl in rl.get("scopeLogs", []):
            for lr in sl.get("logRecords", []):
                attrs = {
                    a.get("key"): a.get("value", {}).get("stringValue")
                    for a in lr.get("attributes", [])
                }
                trace_hex = lr.get("traceId") or ""
                sev_text = lr.get("severityText") or _severity_from_number(
                    int(lr.get("severityNumber", 0) or 0)
                )
                t_ns = int(lr.get("timeUnixNano", 0) or 0)
                if t_ns == 0:  # spec: fall back to ObservedTimestamp
                    t_ns = int(lr.get("observedTimeUnixNano", 0) or 0)
                docs.append(LogDoc(
                    ts=t_ns / 1e9,
                    service=service,
                    severity=normalize_severity(sev_text),
                    body=lr.get("body", {}).get("stringValue", ""),
                    attrs={k: v for k, v in attrs.items() if v is not None},
                    trace_id=bytes.fromhex(trace_hex) if trace_hex else None,
                ))
    return docs


def decode_export_request_columnar(payload: bytes):
    """Protobuf request → native columnar batch, or None to fall back.

    Returns a ``runtime.native.ColumnarSpans`` when the C++ decoder is
    available (feed it to ``DetectorPipeline.submit_columnar``); None
    when the native library can't load — callers then take the
    record-level Python path with identical results.
    """
    if not native.available():
        return None
    return native.decode_otlp(payload, MONITORED_ATTR_KEYS)


class OtlpHttpReceiver:
    """Threaded OTLP/HTTP receiver feeding callbacks, one per signal.

    ``POST /v1/traces`` (and any unrecognised path, for compatibility)
    decodes spans: ``on_records`` is called from the server thread with
    each request's SpanRecords; the callback enqueues into the pipeline
    (which owns batching/tensorization on its own thread). When
    ``on_columnar`` is provided and the native decoder is available,
    protobuf bodies skip Python record objects entirely: C++ wire decode
    → columnar arrays → ``on_columnar`` (the pipeline's fast path).

    When ``on_payload`` is provided (the parallel ingest engine,
    ``runtime.ingest_pool``), protobuf trace bodies take the fastest
    path of all: the RAW body is handed to the decode pool and the
    handler blocks only on the request's :class:`DecodeTicket` —
    batched C++ decode, pooled buffers, coalesced tensorize all happen
    on the pool's workers. The verdicts are unchanged: malformed still
    answers 400 (the ticket carries the per-request decode error, even
    when the request was decoded in a batch), success still means the
    rows are enqueued, and a full pool queue answers the same
    retryable 429 as pipeline saturation — the bounded-admission
    contract has no unbounded buffer ahead of the pool.

    ``POST /v1/metrics`` decodes OTLP metrics/v1 (runtime.otlp_metrics)
    into ``on_metric_records`` — the collector's metrics-pipeline leg
    (otelcol-config.yml:124-126). ``POST /v1/logs`` decodes OTLP
    logs/v1 into ``on_log_records`` — the third signal
    (otelcol-config.yml:128-131). Absent the respective callback,
    exports are acknowledged and dropped (an ingest-side null sink,
    matching a collector with that pipeline unconfigured).

    Ingest hardening (the fault-tolerant-runtime contract, proven by
    tests/test_chaos.py): a malformed body answers 400, a truncated
    body (client died mid-upload) 400, an oversized body 413 — each
    tallied in ``rejects[reason]`` and reported through ``on_reject`` —
    and an abrupt client disconnect (half-open socket, reset mid-
    response) releases the handler thread via the per-connection
    ``timeout`` instead of pinning it. None of these ever kill the
    server: the next well-formed export proceeds normally.

    Backpressure (``retry_after``, tests/test_overload.py): while the
    pipeline sits above its high watermark, trace exports answer the
    OTLP retryable-error contract — ``429`` with an integer
    ``Retry-After`` (delta-seconds, rounded up — real SDKs parse it as
    an int), tallied as ``rejects["saturated"]``. The body is drained
    (bounded by the oversized check) but never decoded: a 429 sent
    over unread bytes would RST the client mid-send and the exporter
    would see a connection error instead of the retryable status.
    Metrics/logs exports stay admitted: they arrive at scrape cadence,
    orders of magnitude below the span path the budget protects.
    """

    # Half-open-socket bound: StreamRequestHandler applies this to the
    # connection in setup(), so a client that stops sending mid-request
    # frees the thread instead of pinning it forever.
    CONNECTION_TIMEOUT_S = 10.0

    def __init__(
        self,
        on_records: Callable[[list[SpanRecord]], None],
        host: str = "0.0.0.0",
        port: int = 4318,
        on_columnar: Callable | None = None,
        on_metric_records: Callable | None = None,
        on_log_records: Callable | None = None,
        on_reject: Callable[[str], None] | None = None,
        max_body_bytes: int = 16 << 20,
        retry_after: Callable[[], float | None] | None = None,
        on_payload: Callable | None = None,
    ):
        receiver = self

        class Handler(BaseHTTPRequestHandler):
            timeout = receiver.CONNECTION_TIMEOUT_S

            def do_POST(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    receiver._reject("bad_length")
                    self.send_response(400)
                    self.end_headers()
                    return
                if length > receiver.max_body_bytes:
                    # Oversized: refuse WITHOUT reading — draining a
                    # multi-GB body to politely answer 413 is itself a
                    # resource fault. Close so the pipelined remainder
                    # can't be parsed as a next request.
                    receiver._reject("oversized")
                    self.send_response(413)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.close_connection = True
                    return
                if retry_after is not None and not (
                    path.endswith("/v1/metrics") or path.endswith("/v1/logs")
                ):
                    hint = retry_after()
                    if hint is not None:
                        # Saturated: retryable refusal. The body IS
                        # drained first (it's already bounded by the
                        # oversized check above): answering 429 with
                        # unread bytes queued would RST a client still
                        # blocked in send(), and an exporter that sees
                        # a reset instead of the 429 never learns to
                        # back off — the exact failure this gate
                        # exists to prevent. Decode is skipped; the
                        # drain is the whole price of admission
                        # control. Retry-After is integer
                        # delta-seconds (RFC 7231 — real OTLP SDKs
                        # parse it as an int), rounded UP so the hint
                        # never undershoots the configured pace.
                        try:
                            self.rfile.read(length)
                        except OSError:
                            receiver._reject("disconnect")
                            self.close_connection = True
                            return
                        receiver._reject("saturated")
                        self.send_response(429)
                        self.send_header(
                            "Retry-After", str(max(int(-(-hint // 1)), 1))
                        )
                        self.end_headers()
                        return
                try:
                    body = self.rfile.read(length)
                except OSError:
                    # Timeout or reset mid-body: the client is gone —
                    # nothing to answer, just release the thread.
                    receiver._reject("disconnect")
                    self.close_connection = True
                    return
                if len(body) < length:
                    # Truncated frame: the client promised more bytes
                    # than it sent (died mid-upload). 4xx, not a crash.
                    receiver._reject("truncated")
                    self.send_response(400)
                    self.end_headers()
                    return
                is_json = "json" in (self.headers.get("Content-Type") or "")
                is_traces = not (
                    path.endswith("/v1/metrics") or path.endswith("/v1/logs")
                )
                if (
                    is_traces
                    and not is_json
                    and receiver.on_payload is not None
                ):
                    # Parallel ingest engine: hand the raw body to the
                    # decode pool; block only on THIS request's ticket.
                    from .ingest_pool import (
                        IngestPoolSaturated,
                        IngestWorkerError,
                    )

                    try:
                        ticket = receiver.on_payload(body)
                    except IngestPoolSaturated:
                        # Same retryable refusal as pipeline
                        # saturation: the pool queue is bounded by
                        # design, and a full one means "come back".
                        receiver._reject("saturated")
                        self.send_response(429)
                        self.send_header("Retry-After", "1")
                        self.end_headers()
                        return
                    try:
                        ticket.result()
                    except TimeoutError:
                        # Wedged flush (supervisor territory): the
                        # request MAY still land, but the client must
                        # not treat it as accepted — 503 is the OTLP
                        # retryable status, never a 4xx that would
                        # make an exporter discard the batch.
                        self.send_response(503)
                        self.send_header("Retry-After", "1")
                        self.end_headers()
                        return
                    except IngestWorkerError:
                        # Server-side flush failure: our bug, not the
                        # client's bytes — must surface as 5xx, never
                        # masquerade as "malformed".
                        self.send_response(500)
                        self.end_headers()
                        return
                    except Exception:
                        # The pool's per-request DECODE verdict (any
                        # exception the payload raised while being
                        # picked apart): malformed wire data is the
                        # client's fault — 400, the serial path's
                        # answer.
                        receiver._reject("malformed")
                        self.send_response(400)
                        self.end_headers()
                        return
                    try:
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "application/x-protobuf"
                        )
                        self.end_headers()
                        self.wfile.write(b"")
                    except OSError:
                        receiver._reject("disconnect")
                        self.close_connection = True
                    return
                columnar = None
                metric_records = None
                log_records = None
                try:
                    if path.endswith("/v1/logs"):
                        if is_json:
                            log_records = decode_logs_request_json(body)
                        else:
                            log_records = decode_logs_request(body)
                    elif path.endswith("/v1/metrics"):
                        from . import otlp_metrics

                        if is_json:
                            metric_records = (
                                otlp_metrics.decode_metrics_request_json(body)
                            )
                        else:
                            metric_records = (
                                otlp_metrics.decode_metrics_request(body)
                            )
                    elif is_json:
                        records = decode_export_request_json(body)
                    elif receiver.on_columnar is not None:
                        columnar = decode_export_request_columnar(body)
                        if columnar is None:
                            records = decode_export_request(body)
                    else:
                        records = decode_export_request(body)
                except Exception:
                    # Anything a malformed body can raise while being
                    # picked apart (WireError, JSONDecodeError, but also
                    # TypeError/AttributeError from structurally-wrong
                    # shapes) is the client's fault: answer 400 rather
                    # than letting http.server abort the connection.
                    # Only decoding is in scope — a failure in the ingest
                    # callback below is a server bug and must surface,
                    # not masquerade as a client error.
                    receiver._reject("malformed")
                    self.send_response(400)
                    self.end_headers()
                    return
                if log_records is not None:
                    if receiver.on_log_records is not None:
                        receiver.on_log_records(log_records)
                elif metric_records is not None:
                    if receiver.on_metric_records is not None:
                        receiver.on_metric_records(metric_records)
                elif columnar is not None:
                    receiver.on_columnar(columnar)
                else:
                    receiver.on_records(records)
                try:
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-protobuf"
                    )
                    self.end_headers()
                    self.wfile.write(b"")  # empty Export*ServiceResponse
                except OSError:
                    # Client reset between upload and ack: the data is
                    # already ingested (at-least-once), only the ack was
                    # lost — count it, release the thread.
                    receiver._reject("disconnect")
                    self.close_connection = True

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self.on_records = on_records
        self.on_columnar = on_columnar
        self.on_payload = on_payload
        self.on_metric_records = on_metric_records
        self.on_log_records = on_log_records
        self.on_reject = on_reject
        self.max_body_bytes = max_body_bytes
        self.retry_after = retry_after
        # reason → count; the daemon mirrors these into
        # anomaly_ingest_rejected_total{transport="http",reason=...}.
        self.rejects: dict[str, int] = {}
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="otlp-receiver", daemon=True
        )

    def _reject(self, reason: str) -> None:
        self.rejects[reason] = self.rejects.get(reason, 0) + 1
        if self.on_reject is not None:
            try:
                self.on_reject(reason)
            except Exception:  # noqa: BLE001 — metrics must not kill ingest
                pass

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def alive(self) -> bool:
        """Liveness for the supervisor: the serve thread is running."""
        return self._thread.is_alive()

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        # BaseServer.shutdown() blocks on an event only serve_forever sets;
        # calling it on a never-started server would wait forever.
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()
