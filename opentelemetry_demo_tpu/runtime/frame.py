"""The ONE verified columnar wire format: checksummed, versioned frames.

Before this module, the stack moved state bytes in three ad-hoc
layouts: the ingest pool copied decode-scratch columns into the
pipeline with bare ``ndarray.copy`` semantics, ``replication.py``
shipped SNAPSHOT/DELTA payloads as ad-hoc npz blobs, and
``checkpoint.py`` persisted npz archives with a sha256 sidecar digest.
Three encoders meant three corruption surfaces — and two of them
(replication deltas, recycled scratch buffers) had NO detection at
all: a flipped bit merged straight into live sketch state. PR 4 proved
bit-identical monoid convergence only when the bytes arrive intact;
this module makes "intact" enforced rather than hoped, and makes the
three hops ONE layout so Kafka→device, primary→standby and disk are
all verify + memcpy + monoid merge with zero re-encode.

Layout (all integers little-endian)::

    offset  size  field
    0       4     magic            b"OTDF"
    4       2     format version   (FRAME_VERSION; readers accept
                                    MIN_READ_VERSION..FRAME_VERSION)
    6       2     flags            (reserved, 0)
    8       8     schema hash      (u64 over the column name/dtype/rank
                                    table; 0 in v1 frames)
    16      4     header length    (u32, JSON bytes incl. alignment pad)
    20      ...   header JSON      {"cols": [{"n", "t", "s"[, "c"]}...],
                                    "meta": {...}} — "t" is the numpy
                                    dtype.str, "s" the shape, "c" the
                                    per-column CRC32C (v2+)
    ...     ...   column payloads  contiguous C-order bytes, each
                                    column start padded to 8-byte
                                    alignment (zero-copy views decode
                                    aligned)
    end-4   4     trailer          CRC32C over bytes[0 : end-4]

Verification discipline — why BOTH a trailer and per-column CRCs:

- The **trailer** catches transport/storage corruption: any flipped
  bit anywhere in the frame (header included) fails the single
  whole-frame check. ``tests/test_frame.py`` proves it exhaustively —
  every single-bit flip of a small frame is caught.
- The **per-column CRCs** are computed from the SOURCE memory before
  the bytes are copied into the frame, and re-checked against the
  copy at decode time. A reusable decode-scratch buffer recycled while
  its rows were still being encoded (the ingest pool's aliasing
  hazard) produces a copy that diverges from its source CRC — a race
  the self-consistent trailer can never see.

Version skew: a v(N) reader accepts v(N−1) frames through the explicit
shim in :func:`decode` (v1 frames carry no per-column CRCs and a zero
schema hash — the trailer still verifies), and :func:`decode_arrays`
additionally accepts the pre-frame npz blob layout ("v0") by sniffing,
so a rolling primary/standby upgrade never bricks replication
mid-failover. ``ANOMALY_FRAME_WRITE_VERSION`` (utils.config
FRAME_KNOBS) lets a half-upgraded fleet keep WRITING v1 until every
reader is current.

CRC32C (Castagnoli) is the checksum: hardware-friendly, and the
polynomial with the best burst-detection record for storage framing
(the same choice as Kafka record batches, ext4 metadata and iSCSI).
The native kernel (``native/ingest.cc otd_crc32c``, the SSE4.2
``crc32`` instruction when the CPU offers it — same polynomial, so
bit-identical by definition — slicing-by-8 otherwise, GIL-released
like every other native call) computes it at memory bandwidth;
environments without a compiler fall back to the table implementation
below — same bits, less throughput.

Corruption handling contract for every consumer: verify BEFORE
merging; a failed check **quarantines** the frame (``quarantine()``
writes the evidence aside when ``ANOMALY_FRAME_QUARANTINE_DIR`` is
set), increments ``anomaly_frame_corrupt_total{hop}``, and the live
sketch state is never touched. ``scripts/sanitycheck.py`` pins this
module as the single source of truth: npz/frombuffer byte layouts
anywhere else in the package fail ``make check``.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import struct
import threading
from typing import NamedTuple

import numpy as np

FRAME_MAGIC = b"OTDF"
FRAME_VERSION = 2
# Oldest frame version this reader still decodes (the rolling-upgrade
# window). The pre-frame npz layout ("v0") is additionally accepted by
# decode_arrays/read_npz — a sniffed shim, not a frame version.
MIN_READ_VERSION = 1

_FIXED = struct.Struct("<4sHHQI")  # magic, version, flags, schema, hlen
_TRAILER = struct.Struct("<I")
_ALIGN = 8


class FrameError(ValueError):
    """Malformed frame (structure, schema, or checksum)."""


class FrameCorrupt(FrameError):
    """A frame whose bytes cannot be trusted: truncated, checksum
    mismatch, or an unparseable header. Consumers quarantine instead of
    merging (the counter/quarantine contract in the module doc)."""


class FrameVersionError(FrameError):
    """A frame whose format version is outside this reader's window —
    an upgrade-order problem (operator), not corruption (environment);
    consumers must NOT quarantine these as bad bytes."""


class Frame(NamedTuple):
    """A decoded frame: ``arrays`` are zero-copy views into the frame
    buffer (the buffer stays alive through the views' ``.base``)."""

    version: int
    arrays: dict[str, np.ndarray]
    meta: dict
    schema: int


# -- CRC32C ------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78  # reflected Castagnoli
_py_table: list[int] | None = None
_crc_native: bool | None = None  # resolved on first call


def _py_crc32c_table() -> list[int]:
    global _py_table
    if _py_table is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            table.append(c)
        _py_table = table
    return _py_table


def _py_crc32c(data, crc: int = 0) -> int:
    """Portable table-driven CRC32C — the no-compiler fallback (same
    bits as the native slicing-by-8 kernel, ~100× slower)."""
    table = _py_crc32c_table()
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    c = ~crc & 0xFFFFFFFF
    for b in bytes(data):
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return ~c & 0xFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """CRC32C over ``data`` (bytes/bytearray/contiguous ndarray).

    For an ndarray the SOURCE memory is checksummed directly (no
    tobytes copy) — that is what lets encode() certify scratch views
    before the copy-out, the race the per-column CRCs exist to catch.
    """
    global _crc_native
    if _crc_native is None:
        try:
            from . import native

            _crc_native = native.available()
        except Exception:  # noqa: BLE001 — any binding/build fault
            _crc_native = False  # means the portable path owns it
    if _crc_native:
        from . import native

        return native.crc32c(data, crc)
    return _py_crc32c(data, crc)


# -- schema hash -------------------------------------------------------


def _crc_range(buf, start: int, end: int) -> int:
    """CRC32C over ``buf[start:end]`` without slicing (a slice of a
    multi-MB frame is a full memcpy; a frombuffer view is free)."""
    return crc32c(np.frombuffer(buf, np.uint8, count=end - start, offset=start))


def schema_hash(cols: list[tuple[str, str, int]]) -> int:
    """u64 over the (name, dtype.str, rank) table — the frame's
    self-description fingerprint. Shapes are excluded on purpose: row
    counts vary per frame, the LAYOUT contract does not."""
    blob = ";".join(f"{n}:{t}:{r}" for n, t, r in cols).encode()
    return int.from_bytes(hashlib.sha256(blob).digest()[:8], "little")


# -- module write/verify configuration ---------------------------------

# Process-wide knobs (daemon boot threads utils.config.FRAME_KNOBS in
# here via configure(); bare-component tests override per call). One
# module global per knob keeps the single-source-of-truth property the
# registry promises — every hop in the process writes/verifies alike.
_write_version = FRAME_VERSION
_verify_default = True
_quarantine_dir: str | None = None
_quarantine_seq = itertools.count()
_quarantine_lock = threading.Lock()


def configure(
    write_version: int | None = None,
    verify: bool | None = None,
    quarantine_dir: str | None = None,
) -> None:
    """Set the process-wide frame policy (daemon boot)."""
    global _write_version, _verify_default, _quarantine_dir
    if write_version is not None:
        if not MIN_READ_VERSION <= int(write_version) <= FRAME_VERSION:
            raise ValueError(
                f"frame write version {write_version} outside "
                f"{MIN_READ_VERSION}..{FRAME_VERSION}"
            )
        _write_version = int(write_version)
    if verify is not None:
        _verify_default = bool(verify)
    if quarantine_dir is not None:
        _quarantine_dir = quarantine_dir or None


def write_version() -> int:
    return _write_version


def verify_enabled() -> bool:
    return _verify_default


def quarantine(buf: bytes, hop: str, directory: str | None = None) -> str | None:
    """Move a corrupt frame's bytes aside for inspection.

    Returns the evidence path, or None when no quarantine directory is
    configured (in-memory hops then drop the bytes after counting — the
    counter is the contract, the file is the forensics bonus)."""
    directory = directory or _quarantine_dir
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        with _quarantine_lock:
            seq = next(_quarantine_seq)
        path = os.path.join(
            directory, f"{hop}-{os.getpid()}-{seq}.frame.corrupt"
        )
        with open(path, "wb") as f:
            f.write(buf)
        return path
    except OSError:
        return None  # forensics must never compound the fault


# -- encode ------------------------------------------------------------


def _pad_to(n: int, align: int = _ALIGN) -> int:
    return (-n) % align


def encode(
    arrays: dict[str, np.ndarray],
    meta: dict | None = None,
    version: int | None = None,
) -> bytes:
    """Arrays + meta → one self-describing frame (bytes).

    Column order is dict order. Per-column CRCs (v2+) are computed from
    the SOURCE arrays before their bytes are copied into the frame —
    see the module doc's scratch-recycling rationale. ``meta`` must be
    JSON-serializable.
    """
    if version is None:
        version = _write_version
    if not MIN_READ_VERSION <= version <= FRAME_VERSION:
        raise ValueError(f"cannot write frame version {version}")
    cols = []
    blobs: list[bytes] = []
    schema_rows: list[tuple[str, str, int]] = []
    for name, arr in arrays.items():
        # NOT ascontiguousarray: that call promotes 0-d arrays to 1-d
        # and would silently rewrite scalar state (step_idx) shapes.
        a = np.asarray(arr)
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        entry: dict = {"n": name, "t": a.dtype.str, "s": list(a.shape)}
        if version >= 2:
            # CRC the source memory FIRST, copy second: a source that
            # mutates between the two (scratch recycled under us)
            # yields a copy that fails this CRC at decode.
            entry["c"] = crc32c(a)
        cols.append(entry)
        schema_rows.append((name, a.dtype.str, a.ndim))
        blobs.append(a.tobytes())
    schema = schema_hash(schema_rows) if version >= 2 else 0
    header = json.dumps(
        {"cols": cols, "meta": meta or {}}, separators=(",", ":")
    ).encode()
    # Pad the header with spaces (JSON-transparent) so the payload
    # region starts 8-byte aligned — decode's zero-copy views then
    # never touch unaligned memory.
    header += b" " * _pad_to(_FIXED.size + len(header))
    out = bytearray()
    out += _FIXED.pack(FRAME_MAGIC, version, 0, schema, len(header))
    out += header
    for blob in blobs:
        out += b"\0" * _pad_to(len(out))
        out += blob
    out += _TRAILER.pack(crc32c(out))  # bytearray: checksummed in place
    return bytes(out)


# -- decode ------------------------------------------------------------


def _parse_header(buf: bytes) -> tuple[int, int, int, dict, int]:
    """(version, schema, header_len, header_doc, payload_start) —
    structure only, no checksum verification."""
    if len(buf) < _FIXED.size + _TRAILER.size:
        raise FrameCorrupt(f"frame truncated at {len(buf)} bytes")
    magic, version, _flags, schema, hlen = _FIXED.unpack_from(buf, 0)
    if magic != FRAME_MAGIC:
        raise FrameCorrupt(f"bad frame magic {magic!r}")
    if version > FRAME_VERSION or version < MIN_READ_VERSION:
        # Disambiguate a REAL version-window miss from a bit flip in
        # the version field itself: the trailer (last 4 bytes, a
        # format invariant across versions) decides. A failing trailer
        # means corruption — and it must be reported as such, or a
        # single flipped version bit in a checkpoint would crash the
        # boot path (FrameVersionError → ValueError) instead of
        # quarantining + cold-starting. (Header-only peeks pass a
        # fabricated trailer and so report corrupt here — peek callers
        # treat any failure as "no evidence", which is right.)
        stored = _TRAILER.unpack_from(buf, len(buf) - _TRAILER.size)[0]
        if _crc_range(buf, 0, len(buf) - _TRAILER.size) != stored:
            raise FrameCorrupt(
                f"frame version field reads {version} and the trailer "
                "CRC fails: corrupt header, not version skew"
            )
        raise FrameVersionError(
            f"frame version {version} outside this reader's window "
            f"{MIN_READ_VERSION}..{FRAME_VERSION}"
        )
    start = _FIXED.size + hlen
    if start + _TRAILER.size > len(buf):
        raise FrameCorrupt("frame header overruns the buffer")

    def _require(ok: bool, why: str) -> None:
        # Explicit raises, not asserts: the negative-dimension guard
        # below stops np.frombuffer's count=-1 read-to-end semantics
        # and must survive python -O.
        if not ok:
            raise FrameCorrupt(f"frame header unparseable: {why}")

    try:
        doc = json.loads(buf[_FIXED.size : start].decode())
        cols = doc["cols"]
    except Exception as e:  # noqa: BLE001 — any header shape fault is
        # corruption by definition (the writer only emits valid JSON)
        raise FrameCorrupt(f"frame header unparseable: {e}") from e
    _require(isinstance(cols, list), "cols is not a list")
    for c in cols:
        _require(
            isinstance(c, dict) and isinstance(c.get("n"), str),
            "column name missing",
        )
        try:
            np.dtype(c.get("t"))
        except Exception as e:  # noqa: BLE001 — unknown dtype string
            raise FrameCorrupt(f"frame header unparseable: {e}") from e
        shape = c.get("s")
        _require(
            isinstance(shape, list)
            and all(isinstance(d, int) and d >= 0 for d in shape),
            f"column {c.get('n')!r} has a non-natural shape",
        )
    return version, schema, hlen, doc, start


def decode(
    buf: bytes,
    verify: bool | None = None,
    expect_schema: int | None = None,
) -> Frame:
    """One frame → :class:`Frame` (zero-copy array views).

    With ``verify`` (default: the module policy, normally True) the
    trailer CRC is checked first, then every per-column CRC (v2+) and
    the schema hash. Raises :class:`FrameCorrupt` on any mismatch or
    truncation, :class:`FrameVersionError` outside the version window.
    ``expect_schema`` additionally pins the frame to a known profile
    (e.g. the ingest span columns) — a hash mismatch there is a
    protocol error, not corruption, and raises :class:`FrameError`.
    """
    if verify is None:
        verify = _verify_default
    version, schema, _hlen, doc, start = _parse_header(buf)
    cols = doc["cols"]
    if verify:
        stored = _TRAILER.unpack_from(buf, len(buf) - _TRAILER.size)[0]
        actual = _crc_range(buf, 0, len(buf) - _TRAILER.size)
        if actual != stored:
            # Name the damaged column when the per-column CRCs can —
            # better forensics than "trailer mismatch" alone.
            bad = _bad_columns(buf, cols, start) if version >= 2 else []
            raise FrameCorrupt(
                f"frame trailer CRC mismatch (stored {stored:#010x}, "
                f"computed {actual:#010x})"
                + (f"; corrupt column(s): {', '.join(bad)}" if bad else "")
            )
    arrays: dict[str, np.ndarray] = {}
    pos = start
    schema_rows: list[tuple[str, str, int]] = []
    for c in cols:
        dtype = np.dtype(c["t"])
        shape = tuple(c["s"])
        nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
        pos += _pad_to(pos)
        if pos + nbytes + _TRAILER.size > len(buf):
            raise FrameCorrupt(
                f"column {c['n']!r} overruns the frame "
                f"({pos + nbytes} past {len(buf) - _TRAILER.size})"
            )
        count = nbytes // dtype.itemsize if dtype.itemsize else 0
        try:
            view = np.frombuffer(buf, dtype=dtype, count=count, offset=pos)
            arrays[c["n"]] = view.reshape(shape)
        except (ValueError, TypeError) as e:
            # Unreachable when the trailer verified (a lying column
            # table fails the CRC first) — but with verification
            # disabled a malformed header must still surface as
            # corruption, not a bare numpy error.
            raise FrameCorrupt(
                f"column {c['n']!r} unmappable ({dtype}, {shape}): {e}"
            ) from e
        schema_rows.append((c["n"], dtype.str, len(shape)))
        if verify and version >= 2:
            actual = _crc_range(buf, pos, pos + nbytes)
            if actual != int(c["c"]):
                raise FrameCorrupt(
                    f"column {c['n']!r} CRC mismatch (stored "
                    f"{int(c['c']):#010x}, computed {actual:#010x}) — "
                    "source mutated during encode, or storage rot"
                )
        pos += nbytes
    if version >= 2:
        computed_schema = schema_hash(schema_rows)
        if verify and computed_schema != schema:
            raise FrameCorrupt(
                "frame schema hash does not match its column table"
            )
        schema = computed_schema
    if expect_schema is not None and version >= 2 and schema != expect_schema:
        raise FrameError(
            f"frame schema {schema:#018x} is not the expected profile "
            f"{expect_schema:#018x}"
        )
    return Frame(version, arrays, doc.get("meta", {}), schema)


def _bad_columns(buf: bytes, cols: list, start: int) -> list[str]:
    """Best-effort list of columns whose stored CRC mismatches."""
    bad = []
    pos = start
    try:
        for c in cols:
            dtype = np.dtype(c["t"])
            nbytes = int(
                dtype.itemsize * int(np.prod(tuple(c["s"]), dtype=np.int64))
            )
            pos += _pad_to(pos)
            if pos + nbytes + _TRAILER.size > len(buf):
                bad.append(c["n"])
                break
            if _crc_range(buf, pos, pos + nbytes) != int(c.get("c", -1)):
                bad.append(c["n"])
            pos += nbytes
    except Exception:  # noqa: BLE001 — diagnostics only
        pass
    return bad


class FramePeek(NamedTuple):
    """Header-only view of a frame: format version, schema hash, and
    the meta block — everything a consumer can learn without touching
    (or verifying) the column payload. Carries whatever the writer
    stamped into meta: fencing epoch for checkpoints, seq/epoch/time
    bounds for history records."""

    version: int
    schema: int
    meta: dict


def peek_meta(buf: bytes) -> FramePeek:
    """:class:`FramePeek` from the header ONLY — no payload
    verification, no column decode.

    THE header-only peek for every caller that needs frame evidence
    cheaply and treats unreadable as absent: save-time fencing peeks
    (checkpoint epoch on a shared volume, via :func:`peek_file_meta`)
    and the history store's time index (seq/epoch/time bounds per
    record without decoding megabytes of sketch columns)."""
    version, schema, _hlen, doc, _start = _parse_header(buf)
    return FramePeek(version, schema, doc.get("meta", {}))


def peek_stream_meta(f) -> FramePeek:
    """Header-only peek at an open binary stream's CURRENT position
    (the history store's record-meta reads: a frame at an arbitrary
    offset inside a segment, peeked without touching its columns).
    Leaves the stream positioned just past the header JSON."""
    fixed = f.read(_FIXED.size)
    if len(fixed) < _FIXED.size:
        raise FrameCorrupt("frame shorter than its fixed header")
    _magic, _version, _flags, _schema, hlen = _FIXED.unpack(fixed)
    header = f.read(hlen)
    return peek_meta(fixed + header + b"\0" * _TRAILER.size)


def peek_file_meta(path: str) -> FramePeek:
    """Header-only read of a frame FILE: fixed header + JSON, never the
    payload — cheap enough for every save-time fencing peek."""
    with open(path, "rb") as f:
        return peek_stream_meta(f)


# -- migration shims ---------------------------------------------------


def sniff(buf: bytes) -> str:
    """'frame' | 'npz' (the pre-frame v0 zip layout) | 'unknown'."""
    if buf[:4] == FRAME_MAGIC:
        return "frame"
    if buf[:2] == b"PK":
        return "npz"
    return "unknown"


def read_npz(source) -> dict[str, np.ndarray]:
    """Legacy ("v0") npz decode — the ONLY np.load in the package.

    ``source`` is a path or bytes. Every way the CONTAINER can lie —
    truncation, a torn zip, a corrupt deflate stream, a bad npy header
    — raises :class:`FrameCorrupt`; environment faults (permissions,
    EIO, memory) propagate untouched so callers can retry them.
    """
    import io
    import zipfile
    import zlib

    f = io.BytesIO(source) if isinstance(source, (bytes, bytearray)) else source
    try:
        with np.load(f) as data:
            return {k: data[k] for k in data.files}
    except (
        zipfile.BadZipFile,  # truncated/garbage container
        zlib.error,          # corrupt deflate stream inside an entry
        EOFError,            # entry shorter than its header claims
        struct.error,        # torn zip/npy structural fields
        ValueError,          # bad npy magic/header
        KeyError,            # central directory references a lost entry
        IndexError,
    ) as e:
        raise FrameCorrupt(f"legacy npz unreadable: {e}") from e


def write_npz(arrays: dict[str, np.ndarray], compressed: bool = True) -> bytes:
    """Legacy ("v0") npz encode — test fixtures and the version-skew
    suites build old-layout blobs through here so the writer stays in
    the one module sanitycheck pins."""
    import io

    buf = io.BytesIO()
    (np.savez_compressed if compressed else np.savez)(buf, **arrays)
    return buf.getvalue()


def decode_arrays(
    blob: bytes, verify: bool | None = None
) -> dict[str, np.ndarray]:
    """Arrays from a frame OR a legacy npz blob (sniffed) — the shim
    replication uses so an un-upgraded primary's npz payloads still
    hydrate an upgraded standby mid-rolling-upgrade."""
    kind = sniff(blob)
    if kind == "frame":
        return decode(blob, verify=verify).arrays
    if kind == "npz":
        return read_npz(blob)
    raise FrameCorrupt(f"payload is neither frame nor npz ({blob[:4]!r})")


# -- the ingest span profile -------------------------------------------

# The decode-scratch column set (native.ColumnarSpans minus the
# services list, which rides in meta): the ONE layout the ingest pool
# moves from scratch to pipeline. Declared here so the schema hash is
# a compile-time constant both ends pin.
SPAN_COLUMNS: tuple[tuple[str, str], ...] = (
    ("duration_us", "<f4"),
    ("trace_key", "<u8"),
    ("is_error", "|u1"),
    ("attr_crc", "<u4"),
    ("attr_present", "|u1"),
    ("svc_idx", "<i4"),
    ("event_count", "<i4"),
    ("has_exception", "|u1"),
)
SPAN_SCHEMA = schema_hash(
    [(n, np.dtype(t).str, 1) for n, t in SPAN_COLUMNS]
)


def span_column_crcs(cols) -> dict[str, int]:
    """Per-column CRC32Cs over a ColumnarSpans' (scratch-view) memory.

    The zero-copy ingest spine's integrity manifest: computed from the
    decode-scratch views the moment decode finishes, then re-checked by
    :func:`verify_span_columns` when the scratch's ticket is scavenged
    (ingest_pool.ScratchPool) — a buffer that was scribbled while its
    rows were still referenced by the pipeline fails the re-check, the
    same divergence the frame round trip's copy-out CRCs caught, now
    without the per-flush copy."""
    return {
        name: crc32c(np.ascontiguousarray(getattr(cols, name)))
        for name, _t in SPAN_COLUMNS
    }


def verify_span_columns(cols, crcs: dict[str, int]) -> list[str]:
    """Names of columns whose memory no longer matches ``crcs``
    (empty = intact). The scavenge-time half of the zero-copy
    integrity contract."""
    return [
        name
        for name, _t in SPAN_COLUMNS
        if crc32c(np.ascontiguousarray(getattr(cols, name)))
        != int(crcs[name])
    ]


def encode_spans(cols, version: int | None = None) -> bytes:
    """native.ColumnarSpans → one frame; the encode IS the copy-out of
    the pooled decode scratch (CRC source views, then memcpy)."""
    arrays = {
        name: np.asarray(getattr(cols, name)).astype(
            np.dtype(t), copy=False
        )
        for name, t in SPAN_COLUMNS
    }
    return encode(arrays, meta={"services": list(cols.services)}, version=version)


def decode_spans(buf: bytes, verify: bool | None = None):
    """Frame → native.ColumnarSpans (verified, zero-copy views)."""
    from .native import ColumnarSpans

    f = decode(buf, verify=verify, expect_schema=SPAN_SCHEMA)
    missing = [n for n, _t in SPAN_COLUMNS if n not in f.arrays]
    if missing:
        raise FrameError(f"span frame missing columns {missing}")
    return ColumnarSpans(
        *(f.arrays[n] for n, _t in SPAN_COLUMNS),
        services=[
            s if s is None else str(s)
            for s in f.meta.get("services", [])
        ],
    )
