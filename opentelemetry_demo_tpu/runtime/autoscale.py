"""Saturation-driven fleet autoscaler: the elastic half of ROADMAP 2.

The detector already *emits* every signal an autoscaler needs — the
PR 2 admission watermarks and shed counters, the brownout ladder
level, the PR 9 spine overlap ratio — and PR 14's membership tier
already knows how to change the ring under guardrails. This module
closes the loop: a supervised, STRICTLY OPT-IN controller
(:class:`AutoscaleController`) that watches a per-window saturation
score and proposes **shard split** on sustained brownout and **shard
join** on sustained idle.

Guardrails are the remediation construction, reused verbatim:

- **Two-edge hysteresis**: a window at/above ``high_water`` extends
  the split streak, at/below ``low_water`` the join streak; the dead
  band between the edges resets BOTH. Proposals need ``act_batches``
  (split) / ``clear_batches`` (join) consecutive windows — one noisy
  window never resizes a production ring.
- **Token-bucket budget** (:class:`~.remediation.TokenBucket`,
  observed timebase): a flapping load shape exhausts the bucket and
  the ring FREEZES in its last shape — proposals refused and counted,
  never oscillation.
- **Role + epoch gating**: only a PRIMARY proposes, and every
  decision passes ``fence.check(path="autoscale")`` — the SIXTH
  fenced path (checkpoint, offsets, frame, history, remediation,
  autoscale): a resurrected stale primary's resize proposal is
  refused and counted, never applied.
- **Opt-in**: ``enabled=False`` (the default) is observe-only — the
  controller tracks streaks, exports metrics and flight-records what
  it WOULD have proposed, but never calls the propose hook.

Every applied decision is flight-recorded and evidence-dumped (the
last observation window rides along), so a 3am "why did the fleet
grow" has its answer on disk.

The controller itself never touches detector state, sockets or disk —
``propose`` is a caller-owned hook (the daemon exports the decision
for the deployment layer, where a resize is one ``FLEET_KNOBS`` change
end-to-end; the bench applies it to a live in-proc ring).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from .remediation import TokenBucket
from .replication import StaleEpochError

DECISION_SPLIT = "split"
DECISION_JOIN = "join"

# Bounded evidence ring: enough context to explain a decision, small
# enough to dump whole.
EVIDENCE_KEEP = 64


class AutoscaleController:
    """Guardrailed split/join proposer over a saturation-score stream.

    Drive it with :meth:`observe` once per observation window (the
    daemon's 1 s self-report cadence) and :meth:`tick` for budget
    housekeeping; read :meth:`stats` for the metric surface.

    ``signals``: name → value in [0, 1] (watermark fraction, shed
    activity, brownout level, ...). The window's saturation score is
    their max — any one saturated axis is saturation.

    ``shards_fn``: current live shard count (the proposal's base).
    ``propose``: applied-decision hook; only called when ``enabled``
    and every gate passed. Return False to report the proposal could
    not be applied (refunds the budget token).
    """

    def __init__(
        self,
        *,
        enabled: bool = False,
        act_batches: int = 5,
        clear_batches: int = 30,
        budget: int = 2,
        refill_s: float = 300.0,
        high_water: float = 0.75,
        low_water: float = 0.15,
        min_shards: int = 2,
        max_shards: int = 8,
        shards_fn: Callable[[], int] | None = None,
        role_fn: Callable[[], str] | None = None,
        fence=None,
        flight=None,
        propose: Callable[[dict], bool] | None = None,
    ):
        self.enabled = bool(enabled)
        self.act_batches = max(int(act_batches), 1)
        self.clear_batches = max(int(clear_batches), 1)
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.min_shards = max(int(min_shards), 1)
        self.max_shards = max(int(max_shards), self.min_shards)
        self._shards_fn = shards_fn
        self._role_fn = role_fn
        self._fence = fence
        self._flight = flight
        self._propose = propose
        self.bucket = TokenBucket(budget, refill_s)
        self._lock = threading.Lock()
        self._hot = 0          # consecutive windows >= high_water
        self._idle = 0         # consecutive windows <= low_water
        self._score = 0.0
        self._target: int | None = None  # last proposed fleet size
        self._evidence: deque = deque(maxlen=EVIDENCE_KEEP)
        # One "observe_only"/"budget_exhausted" note per episode, not
        # per window (the remediation ep["noted"] discipline).
        self._noted: set[str] = set()
        self.counters = {
            "proposals_split": 0,
            "proposals_join": 0,
            "refused_disabled": 0,
            "refused_role": 0,
            "refused_fenced": 0,
            "refused_bounds": 0,
            "refused_budget": 0,
            "refused_apply": 0,
        }

    # -- hot path -------------------------------------------------------

    def observe(self, t_now: float, signals: dict[str, float]) -> float:
        """One observation window; returns the saturation score.

        Dict work under one lock, never I/O — the propose hook (and
        flight dump) run after the streak bookkeeping, still on the
        caller's thread: a decision is rare by construction (budget),
        so the pump pays for it only when the fleet actually resizes.
        """
        score = 0.0
        for v in signals.values():
            v = float(v)
            if v > score:
                score = min(v, 1.0)
        decision: dict | None = None
        with self._lock:
            self.bucket.advance(t_now)
            self._score = score
            self._evidence.append(
                {"t": t_now, "score": round(score, 4), **{
                    k: round(float(v), 4) for k, v in signals.items()
                }}
            )
            if score >= self.high_water:
                self._hot += 1
                self._idle = 0
            elif score <= self.low_water:
                self._idle += 1
                self._hot = 0
            else:
                # The dead band: a shape bouncing between the edges
                # resets BOTH streaks — freeze beats oscillation.
                self._hot = 0
                self._idle = 0
            if self._hot >= self.act_batches:
                decision = self._decide_locked(DECISION_SPLIT, t_now)
                self._hot = 0
            elif self._idle >= self.clear_batches:
                decision = self._decide_locked(DECISION_JOIN, t_now)
                self._idle = 0
        if decision is not None:
            self._apply(decision)
        return score

    def _decide_locked(self, action: str, t_now: float) -> dict | None:
        """Gate one would-be decision; returns the decision dict only
        when every guardrail passed (the remediation gate order:
        enabled → role → fence → bounds → budget)."""
        shards = self._current_shards()
        target = shards + 1 if action == DECISION_SPLIT else shards - 1
        base = {
            "action": action,
            "shards": shards,
            "target": target,
            "t": t_now,
            "score": self._score,
        }
        if not self.enabled:
            self.counters["refused_disabled"] += 1
            self._note("observe_only", base)
            return None
        if self._role_fn is not None and self._role_fn() != "primary":
            self.counters["refused_role"] += 1
            return None
        if self._fence is not None:
            try:
                self._fence.check(path="autoscale")
            except StaleEpochError:
                self.counters["refused_fenced"] += 1
                return None
        if not self.min_shards <= target <= self.max_shards:
            self.counters["refused_bounds"] += 1
            self._note(f"bounds_{action}", base)
            return None
        if not self.bucket.take():
            self.counters["refused_budget"] += 1
            self._note("budget_exhausted", base)
            return None
        self._noted.clear()  # a landed decision starts a new episode
        self._target = target
        base["evidence"] = list(self._evidence)
        return base

    def _note(self, key: str, decision: dict) -> None:
        """Flight-record a refusal ONCE per episode (not per window)."""
        if key in self._noted or self._flight is None:
            return
        self._noted.add(key)
        try:
            self._flight.record(
                "autoscale-refused", reason=key,
                action=decision["action"], shards=decision["shards"],
                target=decision["target"], score=decision["score"],
            )
        except Exception:  # noqa: BLE001 — evidence must not gate
            pass

    def _current_shards(self) -> int:
        if self._shards_fn is None:
            return self.min_shards
        try:
            return max(int(self._shards_fn()), 1)
        except Exception:  # noqa: BLE001 — a broken view proposes
            return self.min_shards  # nothing expansive

    def _apply(self, decision: dict) -> None:
        """Record + hand one gated decision to the propose hook."""
        self.counters[f"proposals_{decision['action']}"] += 1
        if self._flight is not None:
            try:
                self._flight.record(
                    "autoscale", action=decision["action"],
                    shards=decision["shards"],
                    target=decision["target"],
                    score=decision["score"],
                )
                self._flight.dump(
                    f"autoscale-{decision['action']}",
                    decision=decision,
                )
            except Exception:  # noqa: BLE001 — evidence must not gate
                pass
        if self._propose is None:
            return
        try:
            ok = self._propose(dict(decision))
        except Exception:  # noqa: BLE001 — a broken hook refunds
            ok = False
        if not ok:
            with self._lock:
                self.counters["refused_apply"] += 1
                self.bucket.tokens = min(
                    self.bucket.tokens + 1.0, float(self.bucket.capacity)
                )

    # -- housekeeping / surfaces ----------------------------------------

    def tick(self, t_now: float | None = None) -> None:
        with self._lock:
            self.bucket.advance(
                time.monotonic() if t_now is None else t_now
            )

    @property
    def frozen(self) -> bool:
        """True while the proposal budget is exhausted — the ring
        holds its last shape and decisions are refused (counted)."""
        return self.bucket.tokens < 1.0

    def stats(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "enabled": self.enabled,
                "score": self._score,
                "hot_streak": self._hot,
                "idle_streak": self._idle,
                "frozen": self.bucket.tokens < 1.0,
                "tokens": self.bucket.tokens,
                "target_shards": self._target,
            }

    # Trivial lifecycle so the supervision tree can own the component
    # like every other leg (no thread of its own: observations ride
    # the daemon pump, decisions are synchronous records).
    def start(self) -> None:
        pass

    def alive(self) -> bool:
        return True

    def close(self) -> None:
        pass
