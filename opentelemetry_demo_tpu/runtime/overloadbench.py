"""Overload saturation driver: one methodology, bench + tests.

The lagbench/ingestbench sibling for the overload fault class: drive
the REAL DetectorPipeline at a multiple of its drain capacity, then cut
the pressure, and measure the graceful-degradation contract end to end:

- the pending queue never exceeds its row budget (bounded memory);
- error-lane rows are NEVER shed (per-lane counters prove it — and the
  final arithmetic does too: after a full drain, dispatched spans ==
  fed − shed − brownout exactly, so every admitted error row reached
  the device);
- sustained saturation engages the brownout ladder, and after the
  pressure clears the ladder relaxes to level 0 with the queue back
  under the low watermark within a bounded recovery window.

``tests/test_overload.py`` asserts on this dict (the acceptance bar);
``make overloadbench`` prints it as ONE json line, the bench.py habit.
"""

from __future__ import annotations

import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .lagbench import make_columns
from .pipeline import DetectorPipeline
from .tensorize import SpanColumns


def _mark_errors(cols: SpanColumns, error_fraction: float, rng) -> SpanColumns:
    """Re-stamp the error lane at a controlled fraction (make_columns
    draws ~2%; the overload suite wants the knob explicit)."""
    err = (rng.random(cols.rows) < error_fraction).astype(np.float32)
    return cols._replace(is_error=err)


def measure_overload(
    over_factor: float = 5.0,
    seconds: float = 3.0,
    batch: int = 256,
    queue_max_rows: int = 2048,
    high_watermark: float = 0.85,
    low_watermark: float = 0.5,
    brownout_hold_s: float = 0.25,
    brownout_max_level: int = 4,
    error_fraction: float = 0.02,
    pump_interval_s: float = 0.02,
    recovery_timeout_s: float = 30.0,
    seed: int = 0,
    config: DetectorConfig | None = None,
) -> dict:
    """Drive ingest at ``over_factor``× the pipeline's drain capacity
    for ``seconds``, then let it recover; return the overload ledger.

    Capacity here is structural, not measured: the pump dispatches at
    most ``batch`` rows per ``pump_interval_s``, so submitting
    ``over_factor × batch`` rows per pump interval is a sustained
    ``over_factor``× overload by construction — no calibration run
    that could make the bench flaky.
    """
    detector = AnomalyDetector(config or DetectorConfig())
    pipe = DetectorPipeline(
        detector,
        batch_size=batch,
        queue_max_rows=queue_max_rows,
        high_watermark=high_watermark,
        low_watermark=low_watermark,
        brownout_hold_s=brownout_hold_s,
        brownout_max_level=brownout_max_level,
    )
    rng = np.random.default_rng(seed)
    chunk_rows = max(int(over_factor * batch), 1)
    chunks = [
        _mark_errors(make_columns(rng, chunk_rows), error_fraction, rng)
        for _ in range(8)
    ]

    # Warmup compile off the timed path.
    pipe.submit_columns(make_columns(rng, batch))
    pipe.pump(time.monotonic())
    pipe.drain()

    fed = fed_errors = 0
    max_pending = 0
    brownout_max = 0
    t_end = time.monotonic() + seconds
    i = 0
    while time.monotonic() < t_end:
        cols = chunks[i % len(chunks)]
        i += 1
        fed += cols.rows
        fed_errors += int((cols.is_error > 0).sum())
        pipe.submit_columns(cols)
        pipe.pump(time.monotonic())
        max_pending = max(max_pending, pipe.pending_rows())
        brownout_max = max(brownout_max, pipe.brownout_level)
        time.sleep(pump_interval_s)
    saturated_under_load = pipe.saturated

    # Pressure clears: recovery = ladder back to 0 AND queue under the
    # low watermark (the acceptance window).
    t0 = time.monotonic()
    recovery_s = None
    while time.monotonic() - t0 < recovery_timeout_s:
        pipe.pump(time.monotonic())
        max_pending = max(max_pending, pipe.pending_rows())
        if (
            pipe.brownout_level == 0
            and not pipe.saturated
            and pipe.pending_rows() <= pipe._low_rows
        ):
            recovery_s = round(time.monotonic() - t0, 3)
            break
        time.sleep(pump_interval_s)
    pipe.drain()
    dispatched = pipe.stats.spans
    pipe.close()

    shed_ok = pipe.stats.shed_rows["ok"]
    shed_error = pipe.stats.shed_rows["error"]
    brownout_rows = pipe.stats.brownout_rows
    return {
        "over_factor": over_factor,
        "queue_max_rows": queue_max_rows,
        "max_pending_rows": max_pending,
        # Arithmetic conservation over the run (the zero-error-lane-loss
        # proof): every fed row is dispatched, shed or brownout-sampled.
        "fed_rows": fed,
        "fed_error_rows": fed_errors,
        "dispatched_rows": dispatched,
        "shed_ok_rows": shed_ok,
        "shed_error_rows": shed_error,
        "brownout_rows": brownout_rows,
        "conserved": bool(
            dispatched + shed_ok + shed_error + brownout_rows
            == fed + batch  # + batch: the warmup chunk also dispatched
        ),
        "saturated_under_load": bool(saturated_under_load),
        "saturation_events": pipe.stats.saturation_events,
        "brownout_max_level": brownout_max,
        "recovery_s": recovery_s,
        "lag_p99_ms": round(pipe.stats.lag_p99_ms(), 3),
    }


def main() -> None:
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--over-factor", type=float, default=5.0)
    parser.add_argument("--seconds", type=float, default=3.0)
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--queue-max-rows", type=int, default=2048)
    parser.add_argument("--error-fraction", type=float, default=0.02)
    args = parser.parse_args()
    out = measure_overload(
        over_factor=args.over_factor,
        seconds=args.seconds,
        batch=args.batch,
        queue_max_rows=args.queue_max_rows,
        error_fraction=args.error_fraction,
        # Small geometry: the bench measures flow control, not kernels.
        config=DetectorConfig(num_services=8, hll_p=8, cms_width=512),
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
