"""Key lifecycle plane: bounded-memory survival of cardinality bombs.

The detector's headline job is flagging OTHER services' cardinality
anomalies, yet its own intern table was append-only: a UUID-per-
"service" bug either grew host memory without bound (~935 MB per
million keys, measured by the PR 19 soak) or — once the static table
filled — collapsed every future legitimate service into the overflow
bucket forever. This module closes that hole with a budgeted keyspace:

- **Watchdog** (:meth:`KeyspaceManager.tick`): samples process RSS
  (``/proc/self/status`` VmRSS — the same read the soak bench uses)
  and the intern-table fill fraction, and clocks the pipeline's
  keyspace degradation ladder (``DetectorPipeline.keyspace_update``,
  two-edge hysteresis like the brownout ladder).
- **Evictor** (:meth:`KeyspaceManager.evict_idle`): under pressure,
  folds IDLE keys' sketch/head rows into one history record via the
  existing monoids (HLL rows max-merge later reads; CMS/span-total are
  written as the add-identity so nothing double-counts), zeroes the
  rows, and retires the intern ids into the tensorizer's generation-
  stamped free list so ids recycle without mis-attribution. Detector
  state is written ONLY under the pipeline dispatch lock (the
  donation-race contract; the eviction-lock staticcheck pass pins the
  ``retire_services`` half).
- **Generation epoch**: every retirement sweep bumps
  ``SpanTensorizer.generation``; frames (replication, checkpoint,
  fleet reshard, history) carry it and refuse to merge across a bump —
  the ShardMergeError drift-refusal contract extended to recycled ids.

An evicted key is NOT forgotten: its final head state and in-progress
window rode into history, so ``/query/*`` answers stitch from disk
with ``source:"evicted"``, and if the key returns it re-interns (a
fresh slot, a fresh baseline) with its past still answerable.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Iterable

import numpy as np

from .fleet import MERGE_HEAD_ROWS
from .pipeline import KEYSPACE_LEVEL_EVICT

log = logging.getLogger(__name__)


def process_rss_bytes() -> int:
    """Resident set size of THIS process in bytes (0 where
    /proc/self/status is unavailable — macOS CI, sandboxes): the
    budget watchdog's denominator and the anomaly_process_rss_bytes
    gauge. One open+scan, no dependencies."""
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


class KeyspaceManager:
    """The supervised keyspace watchdog + idle-key evictor.

    ``tick()`` is the whole behavior (the background thread just calls
    it on a cadence; tests and the churn soak call it directly with a
    virtual clock): sample pressure → clock the ladder → evict idle
    keys while the ladder is engaged. All detector-state writes happen
    under ``pipeline._dispatch_lock``; the interner retirement happens
    inside the same critical section, so no flush can intern a new key
    into a slot whose rows still hold the old key's state.

    ``protected`` names (the fleet's pre-interned shared table) are
    never evicted — cross-shard frame exchange requires the shared
    prefix to stay put.
    """

    def __init__(
        self,
        pipeline,
        *,
        idle_s: float = 300.0,
        evict_batch: int = 64,
        rss_budget_mb: float = 0.0,
        interval_s: float = 1.0,
        protected: Iterable[str] = (),
        history_writer=None,
        flight=None,
        now_fn: Callable[[], float] = time.monotonic,
        wall_fn: Callable[[], float] = time.time,
        rss_fn: Callable[[], int] = process_rss_bytes,
    ):
        self.pipeline = pipeline
        self.idle_s = float(idle_s)
        self.evict_batch = max(int(evict_batch), 1)
        self.rss_budget_mb = float(rss_budget_mb)
        self.interval_s = float(interval_s)
        self.protected = set(protected)
        self.history_writer = history_writer
        self.flight = flight
        self.now_fn = now_fn
        self.wall_fn = wall_fn
        self.rss_fn = rss_fn
        # Keys interned before this manager existed (restore, fleet
        # pre-intern) have no last-seen sample; they idle from HERE,
        # not from the epoch, so a just-restored quiet key is not
        # evicted on the first pressured tick.
        self._t0 = now_fn()
        self.last_rss = 0
        self.last_fill = 0.0
        self.evictions = 0  # keys evicted by THIS manager
        self.sweeps = 0  # sweeps that evicted at least one key
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Spawn the watchdog thread (idempotent while it lives)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="keyspace-watchdog", daemon=True
        )
        self._thread.start()

    def alive(self) -> bool:
        return self._thread is None or self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — one bad tick is a
                # skipped sweep, never a dead watchdog; crash loops
                # surface through the supervisor probe.
                log.exception("keyspace watchdog tick failed")

    # -- the watchdog --------------------------------------------------

    def fill_fraction(self) -> float:
        tz = self.pipeline.tensorizer
        return tz.live_keys / max(tz.capacity, 1)

    def rss_over_budget(self, rss_bytes: int) -> bool:
        if self.rss_budget_mb <= 0:
            return False
        return rss_bytes > self.rss_budget_mb * 1024 * 1024

    def tick(self, now: float | None = None) -> dict:
        """One watchdog step: pressure sample → ladder clock → evict
        while engaged. Returns the sample (the daemon's gauge source
        and the soak's probe)."""
        now = self.now_fn() if now is None else now
        self.last_rss = rss = self.rss_fn()
        self.last_fill = fill = self.fill_fraction()
        level = self.pipeline.keyspace_update(
            fill, self.rss_over_budget(rss), now=now
        )
        evicted: list[str] = []
        if self.pipeline.keyspace_enable and level >= KEYSPACE_LEVEL_EVICT:
            evicted = self.evict_idle(now)
        return {
            "level": level,
            "fill": fill,
            "rss_bytes": rss,
            "evicted": evicted,
        }

    # -- the evictor ---------------------------------------------------

    def idle_candidates(self, now: float) -> list[tuple[float, str, int]]:
        """(last_seen, name, id) of eviction-eligible keys, oldest
        first: idle past the budget, not protected, not the overflow
        bucket. Reads the immutable snapshot — no intern lock."""
        tz = self.pipeline.tensorizer
        last_seen = self.pipeline._last_seen
        out: list[tuple[float, str, int]] = []
        for name, sid in tz._svc_snapshot.items():
            if name in self.protected or sid >= tz.num_services - 1:
                continue
            seen = last_seen[sid] if last_seen[sid] > 0.0 else self._t0
            if now - seen >= self.idle_s:
                out.append((seen, name, sid))
        out.sort()
        return out[: self.evict_batch]

    def evict_idle(self, now: float | None = None) -> list[str]:
        """One eviction sweep: fold the idle keys' rows into a history
        record, zero them, retire the ids (generation bump) — all
        state writes under the dispatch lock. Returns evicted names."""
        import jax

        from ..models.detector import DetectorState

        now = self.now_fn() if now is None else now
        candidates = self.idle_candidates(now)
        if not candidates:
            return []
        names = [name for _, name, _ in candidates]
        sids = np.asarray([sid for _, _, sid in candidates], np.int64)
        pipeline = self.pipeline
        tz = pipeline.tensorizer
        with pipeline._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in pipeline.detector.state._asdict().items()
            }
            # Fold record FIRST (the rows still hold the keys' state):
            # the in-progress shortest-window HLL bank rides whole
            # (max-merge is idempotent — no double count), CMS/span
            # totals ride as the add-identity (their cells are shared
            # across services and already recorded by the regular rung
            # ladder), head arrays ride whole (last-value merge — the
            # evicted keys' final baselines, every other row identical
            # to what the next regular record would carry anyway).
            record = {
                "hll_bank": np.array(arrays["hll_bank"][0, 0], copy=True),
                "cms_bank": np.zeros_like(arrays["cms_bank"][0, 0]),
                "span_total": np.zeros_like(arrays["span_total"][0, 0]),
            }
            for head in MERGE_HEAD_ROWS:
                if head in arrays:
                    record[head] = np.array(arrays[head], copy=True)
            rec_meta = {
                "seq": int(np.asarray(arrays.get("step_idx", 0))),
                "service_names": tz.service_names,  # PRE-retirement
                "config": list(
                    pipeline.detector.config._replace(sketch_impl=None)
                ),
                "generation": tz.generation,  # PRE-bump: old ids
                "evicted": list(names),
                "query": {},
            }
            # Zero the retired rows: a recycled id must start from the
            # monoid identities, or its first occupant inherits ghosts.
            out = dict(arrays)
            hll = np.array(arrays["hll_bank"], copy=True)
            hll[:, :, sids, :] = 0
            out["hll_bank"] = hll
            for head in MERGE_HEAD_ROWS:
                if head in arrays:
                    h = np.array(arrays[head], copy=True)
                    h[sids] = 0
                    out[head] = h
            pipeline.detector.state = DetectorState(
                **{k: jax.device_put(v) for k, v in out.items()}
            )
            # Retire INSIDE the lock: after the snapshot republish a
            # freed id is assignable on the very next flush, and that
            # flush must find zeroed rows.
            freed = tz.retire_services(names)
        evicted = [n for n in names if tz._svc_snapshot.get(n) is None]
        self.evictions += len(freed)
        self.sweeps += 1
        if self.history_writer is not None and freed:
            self.history_writer.record_eviction(
                record, rec_meta, now=self.wall_fn()
            )
        if self.flight is not None and freed:
            self.flight.record(
                "keyspace", op="evict", keys=len(freed),
                generation=tz.generation, fill=self.fill_fraction(),
                rss_mb=round(self.last_rss / (1024 * 1024), 1),
                names=names[:8],
            )
        return evicted

    def stats(self) -> dict:
        tz = self.pipeline.tensorizer
        return {
            "level": self.pipeline.keyspace_level,
            "rows": tz.live_keys,
            "capacity": tz.capacity,
            "fill": round(self.fill_fraction(), 4),
            "free_ids": tz.free_ids,
            "generation": tz.generation,
            "evicted_total": tz.evicted_total,
            "overflow_assigns_total": tz.overflow_assigns_total,
            "rss_bytes": self.last_rss,
            "rss_budget_mb": self.rss_budget_mb,
            "sweeps": self.sweeps,
        }
