"""The anomaly-detector sidecar daemon: the deployable service.

This is what runs inside the ``anomaly-detector`` container that
deploy/docker-compose.anomaly.yml adds to the shop (wired like the
reference's fraud-detection consumer,
/root/reference/docker-compose.yml:226-256): an OTLP/HTTP receiver for
the collector's ``otlphttp/anomaly`` exporter, an optional Kafka
``orders`` consumer, the device pipeline, a Prometheus ``/metrics``
surface, flagd gating, and offset-keyed checkpoints.

Configuration is environment-driven with hard failure on malformed
values — the reference's ``mustMapEnv`` discipline
(/root/reference/src/checkout/main.go:230-236): a service that boots
with half a config is worse than one that refuses to boot.

Env contract (all optional, sensible defaults). Daemon-core knobs are
ONE registry — ``utils.config.DAEMON_KNOBS`` — consumed here, by the
compose overlay, the k8s generator and the checkers, so the set can
never drift between surfaces (scripts/staticcheck knob-discipline
pass):

- ``ANOMALY_OTLP_PORT``      OTLP/HTTP listen port (default 4318)
- ``ANOMALY_NUM_SERVICES`` / ``ANOMALY_CMS_WIDTH`` / ``ANOMALY_HLL_P`` /
  ``ANOMALY_WARMUP_BATCHES`` / ``ANOMALY_Z_WARMUP_BATCHES``
                             detector geometry/warmup overrides (defaults
                             from models.DetectorConfig; geometry shrinks
                             compile time on small deployments)
- ``ANOMALY_OTLP_GRPC_PORT`` OTLP/gRPC listen port (default 4317, the
                             collector's primary ingress; -1 disables)
- ``ANOMALY_METRICS_PORT``   Prometheus listen port (default 9464)
- ``ANOMALY_BATCH``          device batch size (default 2048)
- ``ANOMALY_HARVEST_INTERVAL``  report readback cadence seconds (default 0
  = every batch); ``ANOMALY_HARVEST_ASYNC=1`` fetches on a side thread
- ``ANOMALY_ADAPTIVE_BATCH``  adaptive dispatch-width controller
  (default 1 = on): widens batches in pow2 steps when report readback
  can't keep pace, bounding the skip rate under load spikes; set 0 for
  a fixed width. The width ladder precompiles in the background at boot
- ``ANOMALY_PUMP_INTERVAL_S``  batch cadence (default 0.05 — the <100ms
                               detection-lag budget spends half on batching)
- ``FLAGD_FILE``             flagd-schema JSON path (hot-reloaded)
- ``OFREP_URL``              OFREP endpoint (used when FLAGD_FILE unset)
- ``KAFKA_ADDR``             bootstrap servers for the orders topic
                             (requires a Kafka client in the image)
- ``ANOMALY_CHECKPOINT``       snapshot path prefix (enables resume)
- ``ANOMALY_CHECKPOINT_INTERVAL_S``  snapshot cadence (default 30)
- ``ANOMALY_OTLP_MAX_BODY``    ingest body-size cap in bytes (default
                               16 MiB; oversized exports answer
                               413/RESOURCE_EXHAUSTED)
- Overload knobs (one registry: ``utils.config.OVERLOAD_KNOBS``):
  ``ANOMALY_QUEUE_MAX_ROWS`` (pending-queue row budget, default 65536,
  0 = unbounded), ``ANOMALY_QUEUE_HIGH_WATERMARK`` /
  ``ANOMALY_QUEUE_LOW_WATERMARK`` (saturation hysteresis, defaults
  0.85/0.5), ``ANOMALY_BROWNOUT_HOLD_S`` / ``ANOMALY_BROWNOUT_MAX_LEVEL``
  (head-sampling ladder, defaults 2.0 s / 4), ``ANOMALY_RETRY_AFTER_S``
  (the 429/RESOURCE_EXHAUSTED retry hint, default 1.0)
- Ingest-pool knobs (one registry: ``utils.config.INGEST_KNOBS``;
  engine: ``runtime.ingest_pool``): ``ANOMALY_INGEST_WORKERS`` (decode
  workers, default 2; 0 disables the pool — serial in-thread decode),
  ``ANOMALY_INGEST_COALESCE`` (max requests per batched decode+flush,
  default 64), ``ANOMALY_INGEST_MAX_PENDING`` (bounded request queue
  ahead of the pool, default 512; full = retryable 429),
  ``ANOMALY_INGEST_NATIVE_THREADS`` / ``ANOMALY_INGEST_SHARD_MIN_BYTES``
  (two-pass scanner pass-2 sharding: extraction threads per batched
  decode call and the payload-byte floor that arms them)
- Device-put spine knobs (one registry: ``utils.config.SPINE_KNOBS``;
  engine: ``runtime.spine`` — the staging ring between batch assembly
  and the donated device step): ``ANOMALY_SPINE_RING`` (pre-allocated
  host staging buffers, default 2 = double buffering; 0 = spine off,
  pack+put inline on the pump thread), ``ANOMALY_SPINE_OVERLAP``
  (1 = overlap batch k+1's host→device put with batch k's in-flight
  step; anomaly_spine_put_overlap_ratio reports the hit rate),
  ``ANOMALY_SPINE_CHUNK_ROWS`` (pack copy block rows, 0 = whole batch)
- Hot-standby replication knobs (one registry:
  ``utils.config.REPLICATION_KNOBS``; engine: ``runtime.replication``):
  ``ANOMALY_ROLE`` (``primary`` serves + ships state deltas,
  ``standby`` applies them and promotes itself on primary silence),
  ``ANOMALY_REPLICATION_PORT`` (primary-side listener, -1 off),
  ``ANOMALY_REPLICATION_TARGET`` (standby-side primary host:port),
  ``ANOMALY_REPLICATION_INTERVAL_S`` (delta cadence, default 1.0),
  ``ANOMALY_FAILOVER_TIMEOUT_S`` (standby watchdog before promotion,
  default 5.0), ``ANOMALY_PRIMARY_HEALTH_ADDR`` (optional grpc-health
  double-check before promoting), ``ANOMALY_OFFSET_DEFER_MAX`` (cap on
  the deferred-confirmation offset list, default 64)
- Live-query-plane knobs (one registry: ``utils.config.QUERY_KNOBS``;
  engine: ``runtime.query`` — the HTTP/gRPC read API over live sketch
  state + the Grafana simple-JSON datasource):
  ``ANOMALY_QUERY_PORT`` (HTTP/JSON + Grafana surface, 0 = ephemeral,
  -1 disables), ``ANOMALY_QUERY_GRPC_PORT`` (same documents over
  gRPC, default -1), ``ANOMALY_QUERY_TOPK`` (default k for top-k
  answers), ``ANOMALY_QUERY_EXEMPLARS`` (per-service exemplar-ring
  size — trace ids captured at flag time), ``ANOMALY_QUERY_TIMELINE``
  (snapshot-timeline ring depth), ``ANOMALY_QUERY_READ_REPLICA``
  (1 = a standby serves queries from its replicated mirror while
  remaining promotable), ``ANOMALY_QUERY_MAX_STALENESS_S`` (snapshot
  cache budget; every answer reports its staleness)
- Self-telemetry knobs (one registry: ``utils.config.SELFTRACE_KNOBS``;
  engines: ``runtime.selftrace`` + ``runtime.flightrec``):
  ``ANOMALY_SELFTRACE_ENABLE`` (batch-lifecycle tracer, default 1),
  ``ANOMALY_SELFTRACE_SAMPLE`` (deterministic splitmix64 head-sampling
  rate, default 0.01), ``ANOMALY_SELFTRACE_ENDPOINT`` (OTLP endpoint
  for the detector's own traces; empty = encode-only),
  ``ANOMALY_SELFTRACE_FLIGHT_RING`` (flight-recorder ring size,
  default 512), ``ANOMALY_SELFTRACE_FLIGHT_DIR`` (evidence-dump
  directory written on DEGRADED/SATURATED/FENCED/PROMOTING
  transitions; empty = ring-only)
- Verified-frame knobs (one registry: ``utils.config.FRAME_KNOBS``;
  engine: ``runtime.frame`` — the ONE checksummed columnar format that
  ingest scratch→pipeline, replication payloads and checkpoint files
  all move): ``ANOMALY_FRAME_VERIFY`` (checksum verification at every
  hop, default 1), ``ANOMALY_FRAME_WRITE_VERSION`` (format version
  written, default 2; readers accept 1..2 — pin to 1 mid-rolling-
  upgrade), ``ANOMALY_FRAME_QUARANTINE_DIR`` (where corrupt frames are
  written aside for forensics; empty = count + drop)
- Time-travel history knobs (one registry:
  ``utils.config.HISTORY_KNOBS``; engine: ``runtime.history`` — the
  compaction thread folding expiring window banks into an on-disk
  retention ladder of verified frames, plus the range-query backend
  and the replay corpus): ``ANOMALY_HISTORY_DIR`` (segment-log
  directory; empty = tier off), ``ANOMALY_HISTORY_RUNGS`` (ladder
  spans seconds, default ``1,60,3600``),
  ``ANOMALY_HISTORY_RETENTION_S`` (per-rung caps),
  ``ANOMALY_HISTORY_COMPACT_INTERVAL_S`` (compaction tick),
  ``ANOMALY_HISTORY_SEGMENT_MB`` (segment roll size),
  ``ANOMALY_HISTORY_SPANS`` ('1' = capture dispatched span batches
  for replaybench; or a per-service sample-rate map
  ``svc:rate[,*:rate]`` — record a mitigation drill's flagged service
  at 100% without the quiet firehose), ``ANOMALY_HISTORY_REPLAY_RATE``
  (replaybench's wall-clock speedup target)
- Closed-loop auto-mitigation knobs (one registry:
  ``utils.config.REMEDIATION_KNOBS``; engine: ``runtime.remediation``
  — the supervised controller that subscribes to the pipeline's
  per-service verdicts and, ONLY when opted in, flips flagd
  mitigation flags + promotes the sampling policy, then verifies its
  own action recovered the system): ``ANOMALY_REMEDIATION_ENABLE``
  (default 0 — observe-only), ``ANOMALY_REMEDIATION_ACT_BATCHES`` /
  ``ANOMALY_REMEDIATION_CLEAR_BATCHES`` (two-edge hysteresis),
  ``ANOMALY_REMEDIATION_BUDGET`` /
  ``ANOMALY_REMEDIATION_BUDGET_REFILL_S`` (token-bucket actuation
  budget — a flapping detector freezes the flags instead of
  oscillating them), ``ANOMALY_REMEDIATION_DEADLINE_S`` /
  ``ANOMALY_REMEDIATION_ROLLBACK`` (verified recovery; a missed
  deadline rolls the actuation back and parks MITIGATION_FAILED),
  ``ANOMALY_REMEDIATION_FLAG_URL`` (remote flag-editor write surface;
  empty = the daemon's own flag store),
  ``ANOMALY_REMEDIATION_TIMEOUT_S`` (bounded per-write transport),
  ``ANOMALY_REMEDIATION_SAMPLING`` (exemplar-seeded keep-100%
  promotion of flagged services)

Replication / failover (runtime.replication; tests/test_replication.py):
the daemon runs a role state machine — PRIMARY / STANDBY / PROMOTING
(plus FENCED, the visible end state of a stale resurrected primary).
A primary ships epoch-stamped state deltas to attached standbys;
offsets ship only after flush confirmation (the PR-3 deferred-
confirmation rule), so a promoted standby resumes the ``orders`` pump
at-least-once from its replicated offset map. A standby that stops
hearing frames for ``ANOMALY_FAILOVER_TIMEOUT_S`` (optionally
double-checking the primary's gRPC health first) promotes: epoch bump,
Kafka seek to the replicated offsets, OTLP receivers up, immediate
epoch-stamped checkpoint, and its own replication listener for the
next standby. A stale primary's writes are fenced on all three paths
(checkpoint save, epoch-tagged Kafka offset commit, replication
frames); it parks in role=fenced instead of split-braining.

Overload protection (tests/test_overload.py): above the high watermark
the pending queue sheds oldest OK-lane rows (never error-lane), trace
exports answer retryable 429 + ``Retry-After`` (HTTP) /
``RESOURCE_EXHAUSTED`` + retry hint (gRPC), the Kafka pump pauses
fetching (offsets hold, the broker buffers), and sustained pressure
engages a deterministic brownout head-sampling ladder. ``/healthz`` on
the metrics port reports ``saturated`` distinct from ``degraded``.

Fault tolerance (runtime.supervision; proven by tests/test_chaos.py):
every ingest leg is supervised — a crashed receiver restarts with
bounded backoff+jitter, poison ``orders`` records are quarantined (not
fatal), a truncated OTLP body answers 4xx, and a corrupt checkpoint at
boot degrades to a cold start. Component state is visible as
``anomaly_component_up{component=...}`` /
``anomaly_component_restarts_total`` / ``anomaly_degraded`` on
``/metrics`` and per-component on the gRPC health service
(``runtime.health_probe --component <name>``).
"""

from __future__ import annotations

import logging
import threading
import time

from ..models.detector import AnomalyDetector, DetectorConfig
from ..telemetry import metrics as tele_metrics
from ..utils.config import (
    ConfigError,
    autoscale_config,
    daemon_config,
    fleet_config,
    fleet_tenant_map,
    frame_config,
    frontdoor_config,
    history_config,
    history_spans_policy,
    ingest_config,
    keyspace_config,
    overload_config,
    provenance_config,
    query_config,
    remediation_config,
    replication_config,
    selftrace_config,
    shadow_config,
    spine_config,
)
from ..utils.flags import FlagEvaluator, FlagFileStore, OfrepClient
from . import autoscale, checkpoint, fleet, history, provenance, remediation, replication, selftrace, shadow
from . import frame as frame_fmt
from .flightrec import FlightRecorder
from .metrics_feed import MetricsFeed
from .otlp import OtlpHttpReceiver
from .pipeline import DetectorPipeline
from .tensorize import EVICTED_SLOT
from .replication import (
    ROLE_FENCED,
    ROLE_PRIMARY,
    ROLE_PROMOTING,
    ROLE_STANDBY,
    EpochFence,
)
from .supervision import Supervisor


def _package_version() -> str:
    """Package version for the build_info gauge ("unknown" rather than
    a crash if the package is run from a mangled checkout)."""
    try:
        from .. import __version__

        return str(__version__)
    except Exception:  # noqa: BLE001 — a label must never fail boot
        return "unknown"


def _jax_version() -> str:
    try:
        import jax

        return str(jax.__version__)
    except Exception:  # noqa: BLE001 — a label must never fail boot
        return "unknown"


class DetectorDaemon:
    """Wires receiver → pipeline → detector → metrics; owns the loop."""

    def __init__(self, config: DetectorConfig | None = None):
        # Daemon-core knobs (ONE registry: utils.config.DAEMON_KNOBS —
        # the same literal dict the compose overlay, the k8s generator
        # and the checkers consume; the env reads this replaces were
        # the stray-knob violations the staticcheck knob-discipline
        # pass exists to catch).
        try:
            dk = daemon_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.otlp_port = int(dk["ANOMALY_OTLP_PORT"])
        self.metrics_port = int(dk["ANOMALY_METRICS_PORT"])
        self.batch_size = int(dk["ANOMALY_BATCH"])
        self.pump_interval_s = float(dk["ANOMALY_PUMP_INTERVAL_S"])
        self.ckpt_path = str(dk["ANOMALY_CHECKPOINT"]) or None
        self.ckpt_interval_s = float(dk["ANOMALY_CHECKPOINT_INTERVAL_S"])

        # Verified-frame policy FIRST (knob registry:
        # utils.config.FRAME_KNOBS; engine: runtime.frame): the
        # checkpoint load below and every hop after it read/write the
        # one columnar format, so the write-version/verify/quarantine
        # knobs must be installed before any byte moves.
        try:
            fk = frame_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        frame_fmt.configure(
            write_version=int(fk["ANOMALY_FRAME_WRITE_VERSION"]),
            verify=bool(int(fk["ANOMALY_FRAME_VERIFY"])),
            quarantine_dir=str(fk["ANOMALY_FRAME_QUARANTINE_DIR"]),
        )

        # Replication role state machine (knob registry:
        # utils.config.REPLICATION_KNOBS; engine: runtime.replication).
        try:
            rp = replication_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.role = (
            ROLE_STANDBY if rp["ANOMALY_ROLE"] == "standby" else ROLE_PRIMARY
        )
        self._repl_port = int(rp["ANOMALY_REPLICATION_PORT"])
        self._repl_target = str(rp["ANOMALY_REPLICATION_TARGET"])
        self._repl_interval_s = float(rp["ANOMALY_REPLICATION_INTERVAL_S"])
        self._failover_timeout_s = float(rp["ANOMALY_FAILOVER_TIMEOUT_S"])
        self._primary_health_addr = str(rp["ANOMALY_PRIMARY_HEALTH_ADDR"])
        self._offset_defer_max = int(rp["ANOMALY_OFFSET_DEFER_MAX"])
        if self.role == ROLE_STANDBY and not self._repl_target:
            raise SystemExit(
                "ANOMALY_ROLE=standby requires ANOMALY_REPLICATION_TARGET "
                "(the primary's replication listener host:port)"
            )
        self.repl_primary: replication.ReplicationPrimary | None = None
        self.repl_standby: replication.ReplicationStandby | None = None

        # Live query plane (knob registry: utils.config.QUERY_KNOBS;
        # engine: runtime.query). Parsed before the pipeline below —
        # the exemplar-ring size is a pipeline constructor knob.
        try:
            qk = query_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self._query_port_req = int(qk["ANOMALY_QUERY_PORT"])
        self._query_grpc_port_req = int(qk["ANOMALY_QUERY_GRPC_PORT"])
        self._query_topk = int(qk["ANOMALY_QUERY_TOPK"])
        self._query_exemplars = int(qk["ANOMALY_QUERY_EXEMPLARS"])
        self._query_candidates = int(qk["ANOMALY_QUERY_CANDIDATES"])
        self._query_timeline = int(qk["ANOMALY_QUERY_TIMELINE"])
        self._query_read_replica = bool(
            int(qk["ANOMALY_QUERY_READ_REPLICA"])
        )
        self._query_max_staleness_s = float(
            qk["ANOMALY_QUERY_MAX_STALENESS_S"]
        )
        self._query_evicted_lookback_s = float(
            qk["ANOMALY_QUERY_EVICTED_LOOKBACK_S"]
        )

        # Detector self-telemetry (knob registry:
        # utils.config.SELFTRACE_KNOBS; engines: runtime.selftrace +
        # runtime.flightrec). Parsed before the pipeline below — the
        # tracer and the phase-observe hook are pipeline/pool
        # constructor arguments, and the flight recorder must exist
        # before any boot-time transition (a boot-fenced primary is
        # the first event worth recording).
        try:
            st = selftrace_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.flight = FlightRecorder(
            size=int(st["ANOMALY_SELFTRACE_FLIGHT_RING"]),
            dump_dir=str(st["ANOMALY_SELFTRACE_FLIGHT_DIR"]),
        )
        self.selftrace = None
        self._selftrace_poster = None
        if int(st["ANOMALY_SELFTRACE_ENABLE"]):
            endpoint = str(st["ANOMALY_SELFTRACE_ENDPOINT"])
            submit = None
            if endpoint:
                # The ONE network leg of self-tracing: the shared
                # background poster (encode on the harvester, POST on
                # the sender thread — never the pump).
                self._selftrace_poster = selftrace.make_exporter(endpoint)
                submit = self._selftrace_poster.submit
            self.selftrace = selftrace.SelfTracer(
                submit=submit,
                sample=float(st["ANOMALY_SELFTRACE_SAMPLE"]),
            )
        # Provenance log-record export reuses the selftrace collector
        # endpoint: evidence bundles ride the same OTLP pipeline as
        # every other self-observation signal (no second endpoint knob).
        self._otlp_export_endpoint = str(st["ANOMALY_SELFTRACE_ENDPOINT"])
        self.flight.record(
            "boot", role=self.role,
            selftrace=bool(int(st["ANOMALY_SELFTRACE_ENABLE"])),
            sample=float(st["ANOMALY_SELFTRACE_SAMPLE"]),
        )
        # Transition-edge state for the flight recorder's health wiring.
        self._flight_last_state: str | None = None
        self._flight_last_brownout = 0
        self._flight_fence_seen = 0
        self._spine_overlap_seen = (0, 0)  # (hits, taken) window base

        # Time-travel history tier knobs (registry:
        # utils.config.HISTORY_KNOBS; engine: runtime.history). Parsed
        # here; the store/writer themselves are constructed after the
        # pipeline below (the writer snapshots through the same
        # dispatch-lock helper replication uses, and its span capture
        # is a pipeline hook) and BEFORE the boot fencing check (the
        # log's on-disk epochs are fencing evidence like the
        # checkpoint volume's).
        try:
            hk = history_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        from ..utils.config import history_ladder

        self._history_dir = str(hk["ANOMALY_HISTORY_DIR"]) or None
        # The SAME parse history_config() just validated with — not a
        # re-implementation that could drift from it.
        self._history_rungs, self._history_retention = history_ladder(
            hk["ANOMALY_HISTORY_RUNGS"],
            hk["ANOMALY_HISTORY_RETENTION_S"],
        )
        self._history_interval_s = float(
            hk["ANOMALY_HISTORY_COMPACT_INTERVAL_S"]
        )
        self._history_segment_bytes = (
            int(hk["ANOMALY_HISTORY_SEGMENT_MB"]) << 20
        )
        # Span-capture policy: '0'/'1' or the per-service sample-rate
        # map — the SAME parse history_config() just validated with.
        self._history_spans, self._history_span_rates = (
            history_spans_policy(hk["ANOMALY_HISTORY_SPANS"])
        )
        # Replay-rate target: consumed by replaybench against a
        # recorded log; surfaced in the flight record below so a
        # postmortem knows what the deployment promised.
        self._history_replay_rate = float(
            hk["ANOMALY_HISTORY_REPLAY_RATE"]
        )
        self.history_store: history.HistoryStore | None = None
        self.history_writer: history.HistoryWriter | None = None
        self.history_reader: history.HistoryReader | None = None
        self._history_seen = {"compactions": 0, "frames_corrupt": 0}

        flagd_file = str(dk["FLAGD_FILE"]) or None
        ofrep = str(dk["OFREP_URL"]) or None
        if flagd_file:
            flags = FlagFileStore(flagd_file)
        elif ofrep:
            flags = OfrepClient(ofrep)  # type: ignore[assignment]
        else:
            flags = FlagEvaluator()

        if config is None:
            # Geometry knobs use -1 as "keep the model's default" (the
            # registry must stay literal/jax-free, so it cannot name
            # DetectorConfig's values).
            base = DetectorConfig()

            def _geom(knob: str, current, cast):
                value = dk[knob]
                return current if float(value) < 0 else cast(value)

            config = base._replace(
                num_services=_geom(
                    "ANOMALY_NUM_SERVICES", base.num_services, int
                ),
                cms_width=_geom("ANOMALY_CMS_WIDTH", base.cms_width, int),
                hll_p=_geom("ANOMALY_HLL_P", base.hll_p, int),
                warmup_batches=_geom(
                    "ANOMALY_WARMUP_BATCHES", base.warmup_batches, float
                ),
                z_warmup_batches=_geom(
                    "ANOMALY_Z_WARMUP_BATCHES", base.z_warmup_batches,
                    float,
                ),
            )
        restored_offsets: dict = {}
        meta: dict | None = None
        ckpt_corrupt = False
        if self.ckpt_path:
            # Resilient boot: a truncated/bit-rotted snapshot means
            # cold start + a counter, never a boot crash — the snapshot
            # is an optimization, not a dependency (checkpoint module
            # docstring). Config mismatch still refuses to boot.
            self.detector, meta, ckpt_corrupt = checkpoint.load_resilient(
                self.ckpt_path, config
            )
        if meta is not None:
            restored_names = meta.get("service_names", [])
            # JSON round-trips partition keys as strings; offsets are
            # keyed by int partition everywhere else.
            restored_offsets = {
                int(p): int(o) for p, o in meta.get("offsets", {}).items()
            }
        else:
            self.detector = AnomalyDetector(config)
            restored_names = []
        # The fencing epoch resumes from the snapshot (a promoted
        # standby's checkpoint carries its bumped epoch, so ITS restart
        # keeps outranking the old primary); further fencing evidence
        # arrives from the broker's commit tags below and from
        # replication frames at runtime.
        self._fence = EpochFence(
            int(meta.get("epoch", 0)) if meta is not None else 0
        )

        self.registry = tele_metrics.MetricRegistry()
        # Build identity: version labels are static for the process
        # lifetime, so the gauge is set exactly once here; the matching
        # start_ts rides /healthz (restart forensics pair with bundle
        # timestamps through these two surfaces).
        self._start_ts = time.time()
        self.registry.gauge_set(
            tele_metrics.ANOMALY_BUILD_INFO, 1.0,
            version=_package_version(),
            frame_version=str(frame_fmt.FRAME_VERSION),
            jax=_jax_version(),
        )
        self.registry.describe(
            tele_metrics.ANOMALY_BUILD_INFO,
            "Constant 1 labelled with package/frame/jax versions",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLAG_TOTAL,
            "Anomaly flags raised, by service",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_Z_SCORE,
            "Current |z| per service and signal",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_COMPONENT_RESTARTS,
            "Supervised component restarts, by component",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_COMPONENT_UP,
            "1 while the supervised component is up, 0 in backoff/degraded",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_DEGRADED,
            "1 while any supervised component is crash-looping",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUARANTINE_TOTAL,
            "Poison records quarantined instead of crashing the consumer",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_REJECTED,
            "Malformed/truncated/oversized ingest bodies answered 4xx",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_CHECKPOINT_CORRUPT,
            "Corrupt snapshots found at boot (each = one cold start)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SHED_ROWS,
            "Pending-queue rows dropped under overload, by lane and cause",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUEUE_ROWS,
            "Pending-queue depth in rows (bounded by the row budget)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUEUE_WATERMARK,
            "Configured saturation watermarks in rows, by mark",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_BROWNOUT_LEVEL,
            "Brownout head-sampling level (keep 1/2^level of OK-lane spans)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SATURATED,
            "1 while admission is saturated (429/RESOURCE_EXHAUSTED to producers)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KAFKA_PAUSED,
            "1 while the orders pump holds fetching under saturation",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_POOL_DEPTH,
            "Requests queued ahead of the decode pool (bounded)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_POOL_FLUSHES,
            "Coalesced decode+tensorize flushes merged into the pipeline",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_POOL_SPANS,
            "Spans decoded through the parallel ingest pool",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_POOL_REQUESTS,
            "Export requests folded into pool flushes (requests/flush = "
            "the live coalescing factor)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_INGEST_POOL_UTILIZATION,
            "Decode-worker busy fraction over the last scrape window "
            "(1.0 = the pool itself is the bottleneck: add workers)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SPINE_PUT_OVERLAP,
            "Fraction of dispatched batches whose host->device put "
            "completed entirely behind the in-flight step (1.0 = "
            "transfer fully hidden by compute)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SPINE_RING_DEPTH,
            "Configured device-put staging ring depth (0 = spine off)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_ROLE,
            "1 on the series matching this process's replication role",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_EPOCH,
            "Current fencing epoch (bumped by every promotion)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_REPLICATION_DELTAS,
            "Replication deltas, by direction (shipped on the primary, "
            "applied on the standby)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_REPLICATION_SNAPSHOTS,
            "Full-state replication snapshots, by direction",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_REPLICATION_LAG,
            "Primary: seconds since the last acked delta; standby: "
            "seconds since the last frame (the watchdog's clock)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_REPLICATION_FENCED,
            "Stale-epoch writes rejected, by path "
            "(checkpoint/offsets/frame)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FAILOVERS,
            "Standby promotions performed by this process",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_OFFSET_DEFER_DROPPED,
            "Deferred-confirmation offset entries shed at the cap "
            "(each = a bounded replay on restart, never silent loss)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_RESTORE_PARTIAL,
            "Boots whose snapshot had a metrics leg that could not be "
            "hydrated (geometry change): span leg restored, metrics "
            "head cold-started",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FRAME_CORRUPT,
            "Frames that failed checksum verification, by hop — each "
            "one is corruption caught at a boundary and quarantined, "
            "never merged into sketch state",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FRAME_VERSION,
            "Columnar frame format version this process writes "
            "(mixed values across a fleet = rolling upgrade in flight)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUERY_REQUESTS,
            "Query-plane requests, by endpoint and HTTP status code",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUERY_LATENCY,
            "Query-plane request latency (host-side numpy over the "
            "cached state snapshot)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUERY_STALENESS,
            "Bound on how old query answers are: snapshot age plus "
            "replication lag on a read replica",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_EXEMPLARS_CAPTURED,
            "Exemplar trace ids captured at anomaly-flag time (each "
            "links a flag to a concrete Jaeger trace)",
        )
        self.registry.counter_add(
            tele_metrics.ANOMALY_EXEMPLARS_CAPTURED, 0.0
        )
        self.registry.describe(
            tele_metrics.ANOMALY_PHASE_SECONDS,
            "Batch-lifecycle phase latency (decode/verify/tensorize/"
            "stage/dispatch/harvest/flag) — the promoted per-phase "
            "timers, one observation per flush/batch",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SPINE_PUT_WAIT,
            "Seconds the pump waited on a staged batch's device put "
            "(0 = the transfer hid entirely behind the in-flight step)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HARVEST_LAG,
            "Submit-to-harvest detection lag per fetched report (the "
            "p99 the lag SLO gates, now Prometheus-owned)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SPINE_OVERLAP_WINDOW,
            "Windowed put-overlap ratio (one observation per scrape "
            "window) — the histogram companion to the lifetime gauge",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_QUERY_STALENESS_HIST,
            "Per-answer query staleness bound (histogram companion to "
            "the anomaly_query_staleness_seconds gauge)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HISTORY_SEGMENTS,
            "Segment files in the on-disk history log (sealed + active)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HISTORY_BYTES,
            "Total bytes across history segments (bounded by the "
            "per-rung retention caps)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HISTORY_COMPACTIONS,
            "Retention-ladder folds performed (N fine-rung records "
            "monoid-merged into one coarse record)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HISTORY_OLDEST,
            "Age of the oldest history record — how far back time "
            "travel reaches",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_HISTORY_READ_LATENCY,
            "History range-read latency (seek + memcpy + verified "
            "decode + monoid merge per query)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SELFTRACE_TRACES,
            "Sampled batch-lifecycle traces exported by the self-tracer",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_SELFTRACE_SPANS,
            "Spans exported inside self-trace batch traces",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLIGHT_EVENTS,
            "Flight-recorder events recorded, by kind (role moves, "
            "shed/brownout steps, fence hits, quarantines, snapshots)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLIGHT_DUMPS,
            "Flight-recorder evidence dumps written, by transition "
            "reason (each one is a postmortem file on disk)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_MITIGATION_ACTIONS,
            "Mitigations actuated by the remediation controller, by "
            "actuator (flagd flag flips / sampling-policy promotions)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_MITIGATION_ROLLBACKS,
            "Actuations automatically rolled back after the verified-"
            "recovery deadline expired",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_MITIGATION_VERIFIED,
            "Mitigations whose recovery the controller VERIFIED with "
            "its own detection heads (clean-streak within deadline)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_MITIGATION_FAILED,
            "Mitigations that did not recover the system within the "
            "deadline (service parked in MITIGATION_FAILED)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_MITIGATION_ACTIVE,
            "Services currently under an active or failed mitigation",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_TIME_TO_MITIGATE,
            "Fault-flagged to verified-recovery interval per mitigated "
            "incident — time-to-mitigate beside time-to-detect",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_PREFLIGHT_VERDICTS,
            "Counterfactual pre-flight verdicts by direction "
            "(released = the shadow replay proved the mitigation "
            "clears the heads; refused = it would not have helped)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_PREFLIGHT_REFUSED,
            "Pre-flight refusals by reason (still_flagged / deadline "
            "/ insufficient_records / error) — every one is a "
            "mitigation that did NOT fire, with flight evidence",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_PREFLIGHT_SECONDS,
            "Act-decision to shadow-verdict wall interval — what the "
            "counterfactual gate adds in front of every actuation",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_COLLECTOR_KEEP_RATIO,
            "Storage fraction the pushed collector tail-sampling "
            "policy implies (promoted services keep 1.0, quiet ones "
            "the base head-sampling rate)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLEET_SHARDS_LIVE,
            "Shards this member currently believes alive (itself "
            "included) — N means full fleet, less means a keyspace "
            "slice is browned out or resharded",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLEET_RING_VERSION,
            "Stable digest of the current ring member set (all live "
            "members agree on this value; disagreement = a ring split)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLEET_FROZEN,
            "1 while the reshard budget is exhausted: the ring holds "
            "its last state and membership changes are refused",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_RESHARDS,
            "Ring membership changes APPLIED (leave + join), each one "
            "a keyspace reassignment",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_RESHARDS_REFUSED,
            "Membership changes REFUSED by the exhausted reshard "
            "budget — the flapping-shard audit trail",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_FLEET_SHARD_SPANS,
            "Spans ingested by this shard, labeled with its shard id "
            "(the per-shard ingest-rate panel)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_PROCESS_RSS,
            "Resident set size of this process (VmRSS) — the keyspace "
            "budget watchdog's denominator",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_ROWS,
            "Live interned service keys (detector state rows in use)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_CAPACITY,
            "Intern-table key budget (num_services minus the overflow "
            "slot)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_FILL,
            "Intern-table fill fraction (rows/capacity) — the "
            "keyspace ladder's pressure signal",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_LEVEL,
            "Keyspace degradation-ladder level: 0 normal, 1 evict "
            "idle, 2 throttle new keys, 3 collapse new keys to "
            "overflow, 4 shed ingest (429)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_GENERATION,
            "Keyspace generation epoch — bumped by every eviction "
            "sweep; frames refuse to merge across a bump",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_EVICTED,
            "Idle keys evicted into history (their ids recycled)",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_FREE_IDS,
            "Retired intern ids awaiting reuse",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_THROTTLED,
            "New keys refused by the per-tenant admission throttle "
            "(ladder level 2+), by tenant",
        )
        self.registry.describe(
            tele_metrics.ANOMALY_KEYSPACE_OVERFLOW,
            "New keys collapsed into the overflow bucket under "
            "keyspace pressure (ladder level 3+), by tenant",
        )
        self._exemplars_seen = 0
        # Mint the per-hop corrupt series at zero (like the shed-lane
        # counters): "this number never moved" must be a visible 0.
        for hop in ("ingest", "replication", "checkpoint", "history"):
            self.registry.counter_add(
                tele_metrics.ANOMALY_FRAME_CORRUPT, 0.0, hop=hop
            )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_FRAME_VERSION,
            float(frame_fmt.write_version()),
        )
        if ckpt_corrupt:
            self.registry.counter_add(
                tele_metrics.ANOMALY_CHECKPOINT_CORRUPT, 1.0
            )
            self.registry.counter_add(
                tele_metrics.ANOMALY_FRAME_CORRUPT, 1.0, hop="checkpoint"
            )
            self.flight.record("quarantine", hop="checkpoint", frames=1)
        # The supervision tree: restart hooks + probes are registered
        # for each ingest leg; passive (run_step-guarded) components
        # register here, thread/server-backed ones in start().
        self._supervisor = Supervisor(registry=self.registry)
        self._supervisor.register(
            "pump", base_backoff_s=0.1, max_backoff_s=5.0,
            restart_budget=10, budget_window_s=60.0,
        )
        try:
            ov = overload_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        # Device-put spine (knob registry: utils.config.SPINE_KNOBS;
        # engine: runtime.spine): staging ring + stager thread so the
        # host→device put of batch k+1 overlaps batch k's in-flight
        # donated step. Ring 0 restores the inline pack+put path.
        try:
            sp = spine_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        # Sharded-fleet knobs (registry: utils.config.FLEET_KNOBS;
        # engines: runtime.fleet membership/ring + runtime.aggregator
        # scatter-gather). Parsed before the pipeline below — the
        # per-tenant quota and tenant map are pipeline constructor
        # knobs; the membership leg itself is built after the health
        # surface exists (its heartbeats poll peer /healthz).
        try:
            fl = fleet_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self._fleet_shards = int(fl["ANOMALY_FLEET_SHARDS"])
        self._fleet_index = int(fl["ANOMALY_FLEET_SHARD_INDEX"])
        self._fleet_peers_raw = str(fl["ANOMALY_FLEET_PEERS"])
        self._fleet_query_peers_raw = str(fl["ANOMALY_FLEET_QUERY_PEERS"])
        self._fleet_repl_peers_raw = str(fl["ANOMALY_FLEET_REPL_PEERS"])
        self._fleet_vnodes = int(fl["ANOMALY_FLEET_VNODES"])
        self._fleet_services = [
            s.strip()
            for s in str(fl["ANOMALY_FLEET_SERVICES"]).split(",")
            if s.strip()
        ]
        self._fleet_heartbeat_s = float(fl["ANOMALY_FLEET_HEARTBEAT_S"])
        self._fleet_dead_after_s = float(fl["ANOMALY_FLEET_DEAD_AFTER_S"])
        self._fleet_rejoin_after_s = float(
            fl["ANOMALY_FLEET_REJOIN_AFTER_S"]
        )
        self._fleet_reshard_budget = int(
            fl["ANOMALY_FLEET_RESHARD_BUDGET"]
        )
        self._fleet_reshard_refill_s = float(
            fl["ANOMALY_FLEET_RESHARD_REFILL_S"]
        )
        self._tenant_map = fleet_tenant_map(fl["ANOMALY_FLEET_TENANTS"])
        self._tenant_quota_rows_s = float(
            fl["ANOMALY_FLEET_TENANT_QUOTA_ROWS_S"]
        )
        self._aggregator_port_req = int(fl["ANOMALY_AGGREGATOR_PORT"])
        self._aggregator_timeout_s = float(
            fl["ANOMALY_AGGREGATOR_TIMEOUT_S"]
        )
        self.fleet = None
        self.aggregator_service = None
        # Adoption surface (filled in by the fleet block below when
        # ANOMALY_FLEET_REPL_PEERS wires the successor mirrors).
        self._fleet_repl_addrs: dict[str, str] = {}
        self._adoption_mirror = None
        self._adoption_target: str | None = None
        self._adoption_fence = None
        self._adoptions_total = 0
        self._adoptions_refused: dict[str, int] = {}
        self._adoption_seen = {"total": 0}
        self._last_adoption_tta: float | None = None

        # Verdict provenance plane (knob registry:
        # utils.config.PROVENANCE_KNOBS; engine: runtime.provenance).
        # The engine rings head trajectories off the already-harvested
        # reports and assembles one bounded evidence bundle per flagged
        # service at capture time — the pipeline owns the flag-time
        # hook, so the engine must exist before the pipeline does.
        try:
            pv = provenance_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.provenance = None
        self._explain_poster = None
        self._provenance_ring = int(pv["ANOMALY_PROVENANCE_RING"])
        self._explanations_seen = 0
        if int(pv["ANOMALY_PROVENANCE_ENABLE"]):
            self.provenance = provenance.ProvenanceEngine(
                self.detector.config,
                topk=int(pv["ANOMALY_PROVENANCE_TOPK"]),
                trajectory_windows=int(
                    pv["ANOMALY_PROVENANCE_TRAJECTORY_WINDOWS"]
                ),
                epoch_fn=lambda: self._fence.epoch,
            )
            if self._otlp_export_endpoint:
                # Bundles double as OTLP log records on the same
                # collector pipeline the selftrace spans ride.
                from .otlp_export import OtlpHttpLogsExporter

                self._explain_poster = OtlpHttpLogsExporter(
                    self._otlp_export_endpoint
                )
            self.flight.record(
                "provenance", op="enabled",
                ring=self._provenance_ring,
                topk=int(pv["ANOMALY_PROVENANCE_TOPK"]),
                export=bool(self._explain_poster is not None),
            )
        # Key lifecycle plane (knob registry: utils.config.
        # KEYSPACE_KNOBS; engine: runtime.keyspace): the pipeline owns
        # the ladder + per-tenant new-key admission; the manager below
        # owns the watchdog thread and the idle-key evictor.
        try:
            ks = keyspace_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self._keyspace_cfg = ks
        self.pipeline = DetectorPipeline(
            self.detector,
            flags=flags,
            on_report=self._on_report,
            batch_size=self.batch_size,
            # Remote/tunneled devices: readback RTT dominates — set an
            # interval (and/or async) so dispatch never waits on fetch.
            harvest_interval_s=float(dk["ANOMALY_HARVEST_INTERVAL"]),
            harvest_async=bool(int(dk["ANOMALY_HARVEST_ASYNC"])),
            # Adaptive width (on by default): bounds the report skip
            # rate when readback RTT outpaces the batch interval — the
            # 10× stress regime. The ladder precompiles in the
            # background below so an escalation never compiles
            # mid-incident.
            adaptive_batching=bool(int(dk["ANOMALY_ADAPTIVE_BATCH"])),
            # Bounded admission + brownout (the overload half of the
            # fault matrix; knob registry: utils.config.OVERLOAD_KNOBS).
            queue_max_rows=ov["ANOMALY_QUEUE_MAX_ROWS"],
            high_watermark=ov["ANOMALY_QUEUE_HIGH_WATERMARK"],
            low_watermark=ov["ANOMALY_QUEUE_LOW_WATERMARK"],
            brownout_hold_s=ov["ANOMALY_BROWNOUT_HOLD_S"],
            brownout_max_level=ov["ANOMALY_BROWNOUT_MAX_LEVEL"],
            retry_after_s=ov["ANOMALY_RETRY_AFTER_S"],
            # Query plane: exemplar trace ids captured at flag time —
            # every anomaly answer links to a concrete Jaeger trace —
            # and the recently-seen candidate keys top-k scores.
            exemplar_ring=self._query_exemplars,
            hh_candidates=self._query_candidates,
            # Device-put spine (SPINE_KNOBS; runtime.spine).
            spine_ring=sp["ANOMALY_SPINE_RING"],
            spine_overlap=bool(int(sp["ANOMALY_SPINE_OVERLAP"])),
            spine_chunk_rows=sp["ANOMALY_SPINE_CHUNK_ROWS"],
            # Self-telemetry (SELFTRACE_KNOBS; runtime.selftrace): the
            # promoted phase histograms + sampled batch-lifecycle traces.
            phase_observe=self._observe_phase,
            selftrace=self.selftrace,
            # Per-tenant namespaces (FLEET_KNOBS; runtime.fleet): one
            # noisy tenant sheds alone, ahead of the shared ladder.
            tenant_of=(
                (lambda name: fleet.tenant_of(name, self._tenant_map))
                if self._tenant_quota_rows_s > 0 else None
            ),
            tenant_quota_rows_s=self._tenant_quota_rows_s,
            # Verdict provenance (PROVENANCE_KNOBS; runtime.provenance):
            # evidence bundles assembled at flag time on the harvester.
            provenance=self.provenance,
            explain_ring=self._provenance_ring,
            # Key lifecycle ladder (KEYSPACE_KNOBS; runtime.keyspace):
            # evict idle → throttle new keys per tenant → collapse new
            # keys to overflow → 429 through every ingest door.
            keyspace_enable=bool(int(ks["ANOMALY_KEYSPACE_ENABLE"])),
            keyspace_high_watermark=ks["ANOMALY_KEYSPACE_HIGH_WATERMARK"],
            keyspace_low_watermark=ks["ANOMALY_KEYSPACE_LOW_WATERMARK"],
            keyspace_hold_s=ks["ANOMALY_KEYSPACE_HOLD_S"],
            keyspace_newkey_rate=ks["ANOMALY_KEYSPACE_NEWKEY_RATE"],
            keyspace_retry_after_s=ks["ANOMALY_KEYSPACE_RETRY_AFTER_S"],
        )
        # Watermark gauges are static config — export once so every
        # scrape can judge anomaly_queue_rows against them; and mint the
        # per-lane shed series at zero so the error-lane invariant
        # ("this number never moves") is a visible 0, not a missing row.
        if self.pipeline.queue_max_rows:
            self.registry.gauge_set(
                tele_metrics.ANOMALY_QUEUE_WATERMARK,
                float(self.pipeline._high_rows), mark="high",
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_QUEUE_WATERMARK,
                float(self.pipeline._low_rows), mark="low",
            )
        for lane in ("ok", "error"):
            for cause in ("overflow", "brownout"):
                self.registry.counter_add(
                    tele_metrics.ANOMALY_SHED_ROWS, 0.0,
                    lane=lane, cause=cause,
                )
        self._shed_seen = {"ok": 0, "error": 0}
        self._brownout_seen = 0
        self._kafka_paused = False
        # SATURATED surfaces beside (and ordered below) DEGRADED: the
        # supervisor reports it on overall_state()/anomaly_saturated,
        # /healthz (below) serves it to probes.
        self._supervisor.set_saturation_probe(lambda: self.pipeline.saturated)
        # Role/epoch surface beside saturation: anomaly_role/anomaly_epoch
        # from the supervisor's tick, role+epoch on /healthz below —
        # how a probe tells a healthy standby from a degraded primary.
        self._supervisor.set_role_probe(
            lambda: (self.role, self._fence.epoch)
        )
        if self.pipeline.adaptive_batching:
            threading.Thread(
                target=self._warm_widths_quietly,
                name="width-ladder-warmup", daemon=True,
            ).start()
        # Positional re-adoption, NOT name-by-name re-interning: a
        # checkpoint written after an eviction sweep carries
        # EVICTED_SLOT tombstones, and interning each live name in
        # sequence would compact past them — shifting every later id
        # off the sketch rows the restored state holds for it. The
        # keyspace generation rides along so a restored primary keeps
        # refusing frames from before its last eviction sweep.
        self.pipeline.tensorizer.adopt_names(restored_names)
        if meta is not None:
            self.pipeline.tensorizer.generation = int(
                meta.get("generation") or 0
            )
        for name in self._fleet_services:
            # Fleet mode pre-interns ONE shared service table in knob
            # order on every shard: CMS cells fold the service id into
            # the key hash, so cross-shard frame adoption (reshard) is
            # bit-exact only when the tables agree —
            # fleet.merge_shard_arrays refuses drifted tables. A
            # checkpoint restored above already carries the same order
            # (interning an existing name is a no-op).
            self.pipeline.tensorizer.service_id(name)

        # Parallel host-ingest engine (runtime.ingest_pool): N decode
        # workers between the receivers and the pipeline — batched
        # native decode, pooled buffers, one tensorize+merge per flush.
        # Workers=0 keeps the serial in-thread decode (the receivers'
        # on_columnar path). Knob registry: utils.config.INGEST_KNOBS.
        try:
            ing = ingest_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.ingest_pool = None
        if ing["ANOMALY_INGEST_WORKERS"] > 0:
            from .ingest_pool import IngestPool

            self.ingest_pool = IngestPool(
                submit_columns=self.pipeline.submit_columns,
                tensorizer=self.pipeline.tensorizer,
                workers=ing["ANOMALY_INGEST_WORKERS"],
                coalesce_max=ing["ANOMALY_INGEST_COALESCE"],
                max_pending=ing["ANOMALY_INGEST_MAX_PENDING"],
                phase_observe=self._observe_phase,
                selftrace=self.selftrace,
                native_threads=ing["ANOMALY_INGEST_NATIVE_THREADS"],
                shard_min_bytes=ing["ANOMALY_INGEST_SHARD_MIN_BYTES"],
            )
            self._supervisor.register(
                "ingest-pool", base_backoff_s=0.1, max_backoff_s=5.0,
                restart=self.ingest_pool.restart_workers,
                probe=lambda: (
                    self.ingest_pool is None or self.ingest_pool.alive()
                ),
            )
        self._pool_seen = {
            "flushes": 0, "flushed_spans": 0, "coalesced_requests": 0,
            "frames_corrupt": 0, "busy_s": 0.0, "wall_t": time.monotonic(),
        }
        # Native front door (runtime/frontdoor.py): opt-in second
        # producer into the SAME bounded decode queue — socket→scratch
        # →scan with zero Python per payload. Resolved (and validated)
        # at boot even when disabled, so a typo'd knob fails fast;
        # started only from run()/promotion on a serving primary.
        # Knob registry: utils.config.FRONTDOOR_KNOBS.
        try:
            self._frontdoor_cfg = frontdoor_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.frontdoor = None
        # Orders flushes whose pool ticket hadn't resolved within the
        # pump's wait: offsets are withheld until the flush confirms,
        # so a checkpoint can never persist offsets for records that
        # never reached the pipeline (at-least-once: a crash before
        # confirmation replays them from the broker on resume). BOUNDED
        # (ANOMALY_OFFSET_DEFER_MAX): a permanently-failing flush path
        # sheds the oldest entry (counted; its records replay on
        # restart) and forces a checkpoint barrier.
        from .kafka_orders import DeferredOffsets

        self._deferred_offsets = DeferredOffsets(cap=self._offset_defer_max)
        self._defer_dropped_seen = 0

        # The OTLP metrics leg: /v1/metrics → feed → metrics head. The
        # feed keeps its OWN service table: results join on service NAME
        # at the export surface, and sharing the span tensorizer's table
        # would let metric-only scrape jobs (kafka, node exporters, …)
        # exhaust the span detector's service slots.
        from ..models.metrics_head import MetricsHeadConfig

        self.metrics_feed = MetricsFeed(
            MetricsHeadConfig(num_services=config.num_services),
            on_report=self._on_metrics_report,
        )
        if meta is not None:
            restored_feed = checkpoint.restore_metrics_feed(
                meta, self.metrics_feed
            )
            if not restored_feed and (
                meta.get("_metrics_arrays")
                or meta.get("metrics_config") is not None
            ):
                # The snapshot HAD a metrics leg we could not hydrate
                # (geometry change — restore_metrics_feed logged the
                # mismatching key): a partial restore an operator must
                # be able to see, not infer from a cold metrics head.
                self.registry.counter_add(
                    tele_metrics.ANOMALY_RESTORE_PARTIAL, 1.0
                )
        self._metric_series_seen: set[tuple[str, str]] = set()
        # Logs leg (the collector's third signal,
        # otelcol-config.yml:128-131): /v1/logs → bounded store (the
        # OpenSearch-analogue index, queryable for debugging) + a
        # severity-rate lane into the metrics head so an error-log burst
        # is detectable even when the producing service emits no spans.
        from ..telemetry.logstore import LogStore

        self.log_store = LogStore()
        self.max_body_bytes = int(dk["ANOMALY_OTLP_MAX_BODY"])
        self._grpc_port_req = int(dk["ANOMALY_OTLP_GRPC_PORT"])
        # A standby answers no ingest until promotion, and a
        # boot-fenced stale primary answers none EVER (a fenced process
        # that kept serving would hold the orchestrator's readiness and
        # the collector's traffic on a replica whose writes are all
        # rejected): receivers are constructed below only once the
        # fence evidence is in, and at promote time for standbys.
        self.receiver = None
        self.grpc_receiver = None
        self.exporter = tele_metrics.PrometheusExporter(
            self.registry, port=self.metrics_port, health=self._healthz
        )
        self._orders = None
        self._quarantine_seen = 0
        kafka_addr = str(dk["KAFKA_ADDR"]) or None
        if kafka_addr:
            from .kafka_orders import OrdersSource  # gated import

            self._orders = OrdersSource(kafka_addr)
            # Fencing: commits are epoch-tagged + fence-guarded, and a
            # resurrected primary reads the tag its successor left on
            # the group's committed offsets BEFORE its first write —
            # the broker doubles as a fencing witness.
            self._orders.fence = self._fence
            self._fence.observe(self._orders.last_committed_epoch())
            if restored_offsets:
                # The snapshot's offsets win over broker-committed ones:
                # sketch state corresponds to THEM (checkpoint.py module
                # docstring — replay past the snapshot double-counts).
                self._orders.seek(restored_offsets)
            self._supervisor.register(
                "kafka-orders", base_backoff_s=0.5, max_backoff_s=15.0,
            )
        # Time-travel tier (runtime.history): the store opens for every
        # role that has the directory (range reads are disk-only); the
        # COMPACTION WRITER is built here but started only by a serving
        # role (start() on a primary, promote() on a standby). Opening
        # the store OBSERVES the largest epoch already on disk — the
        # fourth fencing path: a resurrected stale primary sharing the
        # history volume learns it was superseded before the boot-fence
        # check below, exactly like the checkpoint volume.
        if self._history_dir:
            self.history_store = history.HistoryStore(
                self._history_dir,
                segment_bytes=self._history_segment_bytes,
                fence=self._fence,
                retention_s=self._history_retention,
            )
            self.history_reader = history.HistoryReader(
                self.history_store, rungs=self._history_rungs
            )
            self.history_writer = history.HistoryWriter(
                self.history_store,
                snapshot_fn=self._replication_snapshot,
                rungs=self._history_rungs,
                interval_s=self._history_interval_s,
                capture_spans=self._history_spans,
                # Per-service capture rates (the map form of the spans
                # knob); the remediation sampling actuator re-publishes
                # over this live (flagged service → keep-100%).
                span_sample=self._history_span_rates or None,
                service_names_fn=(
                    lambda: self.pipeline.tensorizer.service_names
                ),
            )
            if self._history_spans:
                self.pipeline.history_capture = self.history_writer.capture
            self.flight.record(
                "history", dir=self._history_dir,
                rungs=list(self._history_rungs),
                retention_s=list(self._history_retention),
                spans=self._history_spans,
                replay_rate=self._history_replay_rate,
            )
        # Key lifecycle watchdog + evictor (runtime.keyspace): built
        # after the history tier so eviction fold records have a
        # writer to land in; started only by a SERVING role (start()
        # below / promote()) — a standby mirrors the primary's state
        # verbatim and must not run local eviction sweeps that would
        # diverge its generation.
        self.keyspace = None
        self._keyspace_level_seen = 0
        self._keyspace_evicted_seen = 0
        self._keyspace_tenant_seen: dict[str, dict[str, float]] = {
            "throttled": {}, "overflow": {},
        }
        if int(ks["ANOMALY_KEYSPACE_ENABLE"]):
            from .keyspace import KeyspaceManager

            self.keyspace = KeyspaceManager(
                self.pipeline,
                idle_s=ks["ANOMALY_KEYSPACE_IDLE_S"],
                evict_batch=ks["ANOMALY_KEYSPACE_EVICT_BATCH"],
                rss_budget_mb=ks["ANOMALY_KEYSPACE_RSS_MB"],
                protected=self._fleet_services,
                history_writer=self.history_writer,
                flight=self.flight,
            )
            self.flight.record(
                "keyspace", op="enabled",
                capacity=self.pipeline.tensorizer.capacity,
                idle_s=float(ks["ANOMALY_KEYSPACE_IDLE_S"]),
                evict_batch=int(ks["ANOMALY_KEYSPACE_EVICT_BATCH"]),
                rss_budget_mb=float(ks["ANOMALY_KEYSPACE_RSS_MB"]),
            )
        # Closed-loop auto-mitigation (knob registry:
        # utils.config.REMEDIATION_KNOBS; engine: runtime.remediation).
        # Constructed for EVERY role — a standby observes episodes so a
        # promotion inherits warm streaks — but only an enabled PRIMARY
        # ever actuates, and every actuator write is fence-guarded
        # (path="remediation", the fifth fenced write path).
        try:
            rk = remediation_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        rem_actuators: list = []
        rem_url = str(rk["ANOMALY_REMEDIATION_FLAG_URL"])
        rem_timeout_s = float(rk["ANOMALY_REMEDIATION_TIMEOUT_S"])
        if rem_url or not isinstance(flags, OfrepClient):
            # OFREP is evaluate-only: without a writable store a flagd
            # actuator needs the remote flag-editor URL; with neither,
            # only the sampling actuator runs.
            rem_actuators.append(remediation.FlagdActuator(
                store=None if rem_url else flags,
                url=rem_url,
                timeout_s=rem_timeout_s,
            ))
        if int(rk["ANOMALY_REMEDIATION_SAMPLING"]):
            rem_actuators.append(remediation.SamplingActuator(
                publish=self._publish_sampling_policy,
                base_policy=dict(self._history_span_rates),
                exemplar_fn=self._exemplars_for,
            ))
        # Collector-steering leg (ROADMAP item 4): when a policy file
        # path or endpoint is configured, the flagged service's traces
        # tail-sample at 100% (exemplar-seeded) while quiet services
        # head-sample at the base keep — the storage-reduction ratio
        # rides the scrape as anomaly_collector_keep_ratio.
        self._collector_actuator = None
        col_path = str(rk["ANOMALY_REMEDIATION_COLLECTOR_PATH"])
        col_url = str(rk["ANOMALY_REMEDIATION_COLLECTOR_URL"])
        if col_path or col_url:
            self._collector_actuator = remediation.CollectorActuator(
                policy_path=col_path,
                url=col_url,
                base_keep=float(
                    rk["ANOMALY_REMEDIATION_COLLECTOR_BASE_KEEP"]
                ),
                exemplar_fn=self._exemplars_for,
                # Tombstoned (evicted) slots are not services — a
                # sampling rule for one would be noise in the policy.
                services_fn=(
                    lambda: [
                        n
                        for n in self.pipeline.tensorizer.service_names
                        if n != EVICTED_SLOT
                    ]
                ),
                timeout_s=rem_timeout_s,
            )
            rem_actuators.append(self._collector_actuator)
        # Counterfactual pre-flight gate (knob registry:
        # utils.config.SHADOW_KNOBS; engine: runtime.shadow): opt-in,
        # and a gate that cannot replay is a misconfiguration that
        # refuses to boot — never a silent rubber stamp.
        try:
            sk = shadow_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.shadow_verifier: shadow.ShadowVerifier | None = None
        if int(sk["ANOMALY_SHADOW_ENABLE"]):
            if self.history_reader is None or not self._history_spans:
                raise SystemExit(
                    "ANOMALY_SHADOW_ENABLE=1 needs the recorded replay "
                    "corpus: set ANOMALY_HISTORY_DIR and turn on "
                    "ANOMALY_HISTORY_SPANS span capture"
                )
            self.shadow_verifier = shadow.ShadowVerifier(
                self.history_reader,
                self.detector.config,
                batch_size=self.batch_size,
                window_s=float(sk["ANOMALY_SHADOW_WINDOW_S"]),
                deadline_s=float(sk["ANOMALY_SHADOW_DEADLINE_S"]),
                rate_target=float(sk["ANOMALY_SHADOW_RATE"]),
                min_records=int(sk["ANOMALY_SHADOW_MIN_RECORDS"]),
                flight=self.flight,
                bundle_fn=self._bundle_for,
            )
            self.flight.record(
                "preflight", op="enabled",
                window_s=float(sk["ANOMALY_SHADOW_WINDOW_S"]),
                deadline_s=float(sk["ANOMALY_SHADOW_DEADLINE_S"]),
            )
        self.remediation = remediation.RemediationController(
            rem_actuators,
            enabled=bool(int(rk["ANOMALY_REMEDIATION_ENABLE"])),
            act_batches=int(rk["ANOMALY_REMEDIATION_ACT_BATCHES"]),
            clear_batches=int(rk["ANOMALY_REMEDIATION_CLEAR_BATCHES"]),
            budget=int(rk["ANOMALY_REMEDIATION_BUDGET"]),
            budget_refill_s=float(
                rk["ANOMALY_REMEDIATION_BUDGET_REFILL_S"]
            ),
            deadline_s=float(rk["ANOMALY_REMEDIATION_DEADLINE_S"]),
            rollback=bool(int(rk["ANOMALY_REMEDIATION_ROLLBACK"])),
            role_fn=lambda: self.role,
            fence=self._fence,
            flight=self.flight,
            preflight=(
                self._preflight_mitigation
                if self.shadow_verifier is not None else None
            ),
            bundle_fn=self._bundle_for,
        )
        self._remediation_seen: dict[str, int] = {}
        if self.remediation.enabled:
            self.flight.record(
                "mitigation", op="enabled",
                actuators=[a.name for a in rem_actuators],
            )
        # Saturation-driven autoscaler (knob registry:
        # utils.config.AUTOSCALE_KNOBS; engine: runtime.autoscale):
        # strictly opt-in like remediation — default is observe-only —
        # proposing shard split on sustained brownout and join on
        # sustained idle behind the reused token-bucket + two-edge
        # hysteresis, with every decision fence-checked
        # (path="autoscale", the sixth fenced write path). The daemon
        # cannot spawn a shard itself: a landed proposal is
        # evidence-dumped and exported (anomaly_autoscale_target_shards
        # + /healthz), and the deployment layer makes the resize one
        # FLEET_KNOBS change end-to-end.
        try:
            ak = autoscale_config()
        except ConfigError as e:
            raise SystemExit(str(e)) from e
        self.autoscaler = autoscale.AutoscaleController(
            enabled=bool(int(ak["ANOMALY_AUTOSCALE_ENABLE"])),
            act_batches=int(ak["ANOMALY_AUTOSCALE_ACT_BATCHES"]),
            clear_batches=int(ak["ANOMALY_AUTOSCALE_CLEAR_BATCHES"]),
            budget=int(ak["ANOMALY_AUTOSCALE_BUDGET"]),
            refill_s=float(ak["ANOMALY_AUTOSCALE_REFILL_S"]),
            high_water=float(ak["ANOMALY_AUTOSCALE_HIGH_WATER"]),
            low_water=float(ak["ANOMALY_AUTOSCALE_LOW_WATER"]),
            min_shards=int(ak["ANOMALY_AUTOSCALE_MIN_SHARDS"]),
            max_shards=int(ak["ANOMALY_AUTOSCALE_MAX_SHARDS"]),
            shards_fn=self._fleet_shard_count,
            role_fn=lambda: self.role,
            fence=self._fence,
            flight=self.flight,
        )
        self._autoscale_seen: dict[str, int] = {}
        self._autoscale_shed_seen = 0
        if self.autoscaler.enabled:
            self.flight.record("autoscale", op="enabled")
        # Sharded fleet membership (knob registry:
        # utils.config.FLEET_KNOBS; engine: runtime.fleet): a
        # supervised heartbeat loop over the peer shards' /healthz
        # surfaces feeding the consistent-hash ring, with the
        # double-check + hysteresis + reshard-budget guardrails. Every
        # ROLE runs it (a standby's view of the fleet must be warm at
        # promotion). The optional embedded aggregator serves the
        # fleet-global /query/* scatter-gather tier from this process
        # (ANOMALY_AGGREGATOR_PORT >= 0; the compose/k8s
        # anomaly-aggregator service runs it standalone instead).
        if self._fleet_shards > 1:
            peer_addrs = fleet.parse_peer_list(
                self._fleet_peers_raw, self._fleet_shards,
                self._fleet_index,
            )
            # Adoption mirrors (ANOMALY_FLEET_REPL_PEERS, index-aligned
            # like the heartbeat list): each shard subscribes a standby
            # to its RING-SUCCESSOR's replication stream, so when
            # membership declares that pair dead through the
            # double-check + budget guardrails, this daemon already
            # holds the victim's frame and adopts its keyspace with
            # zero operator action. Empty list = PR 14 behavior (the
            # operator merge drill).
            self._fleet_repl_addrs = (
                fleet.parse_peer_list(
                    self._fleet_repl_peers_raw, self._fleet_shards,
                    self_index=-1, prefix="shard-",
                )
                if self._fleet_repl_peers_raw else {}
            )
            self.fleet = fleet.FleetMember(
                f"shard-{self._fleet_index}",
                peer_addrs,
                heartbeat_s=self._fleet_heartbeat_s,
                vnodes=self._fleet_vnodes,
                dead_after_s=self._fleet_dead_after_s,
                rejoin_after_s=self._fleet_rejoin_after_s,
                reshard_budget=self._fleet_reshard_budget,
                reshard_refill_s=self._fleet_reshard_refill_s,
                on_reshard=self._on_reshard,
                adoptive=bool(self._fleet_repl_addrs),
            )
            self._supervisor.register(
                "fleet", base_backoff_s=0.5, max_backoff_s=15.0,
                restart=self._restart_fleet,
                probe=lambda: (
                    self.fleet is None or self.fleet.alive()
                ),
            )
            if self._fleet_repl_addrs:
                # The mirror observes the SUCCESSOR's epoch domain —
                # never this shard's own fence (each shard is its own
                # primary; a peer's higher epoch must not fence us).
                self._adoption_fence = EpochFence()
                self._retarget_adoption_mirror(
                    list(self.fleet.membership.ring.members())
                )
            if self._aggregator_port_req >= 0:
                from .aggregator import (
                    AggregatorService,
                    FleetAggregator,
                )

                query_addrs = fleet.parse_peer_list(
                    self._fleet_query_peers_raw, self._fleet_shards,
                    self_index=-1,
                )
                self.aggregator_service = AggregatorService(
                    FleetAggregator(
                        query_addrs,
                        timeout_s=self._aggregator_timeout_s,
                        ring=self.fleet.membership.ring,
                        tenant_map=self._tenant_map,
                        live_fn=self._fleet_live_shards,
                    ),
                    registry=self.registry,
                    port=self._aggregator_port_req,
                )
        self._fleet_seen = {"reshards": 0, "refused": 0, "spans": 0}
        self._tenant_shed_seen: dict[str, int] = {}
        if self.fleet is not None:
            # Mint the fleet counters at zero (the shed-lane habit):
            # "no reshard ever happened" must be a visible 0.
            self.registry.counter_add(tele_metrics.ANOMALY_RESHARDS, 0.0)
            self.registry.counter_add(
                tele_metrics.ANOMALY_RESHARDS_REFUSED, 0.0
            )
            self.registry.counter_add(
                tele_metrics.ANOMALY_FLEET_SHARD_SPANS, 0.0,
                shard=f"shard-{self._fleet_index}",
            )
        if self.role == ROLE_PRIMARY and self._fence.stale():
            # Booted into a world that promoted past us (newer epoch on
            # the broker's commit tags or our own snapshot volume):
            # park FENCED instead of split-braining. Visible on
            # anomaly_role and /healthz; an operator redeploys us as a
            # standby (or retires us).
            self._become_fenced(at_boot=True)
        if self.role == ROLE_PRIMARY:
            self.receiver = self._make_http_receiver(self.otlp_port)
            # OTLP/gRPC :4317 — the reference collector's primary
            # ingress (otelcol-config.yml:5-8); every SDK defaults to
            # gRPC export.
            if self._grpc_port_req >= 0:
                try:
                    self.grpc_receiver = self._make_grpc_receiver(
                        self._grpc_port_req
                    )
                except ImportError:  # grpcio absent: HTTP leg serves
                    self.grpc_receiver = None
        if self.ckpt_path:
            self._supervisor.register(
                "checkpoint", base_backoff_s=1.0, max_backoff_s=60.0,
            )
        self._offsets: dict = dict(restored_offsets)
        # Guards _offsets against the replication session thread's
        # snapshot read: the pump thread mutates the map per poll,
        # and an unguarded concurrent iteration can raise
        # "dictionary changed size during iteration".
        self._offsets_lock = threading.Lock()
        # Live query plane (runtime.query): the engine consumes ONLY
        # the role-dispatched snapshot helper below — live state under
        # the dispatch lock on a primary, the replication mirror on a
        # standby — so queries fail over with the role and never race
        # donated device buffers. Constructed for every role; a plain
        # standby (read-replica off) starts it only at promotion.
        self.query_engine = None
        self.query_service = None
        self.query_grpc = None
        self._query_started = False
        if self._query_port_req >= 0:
            from .query import QueryEngine, QueryService

            self.query_engine = QueryEngine(
                snapshot_fn=self._query_snapshot,
                role_fn=lambda: self.role,
                epoch_fn=lambda: self._fence.epoch,
                lag_fn=self._query_lag,
                max_staleness_s=self._query_max_staleness_s,
                timeline_depth=self._query_timeline,
                topk_default=self._query_topk,
                # /query/flight serves THIS process's event ring — the
                # on-demand half of the flight-recorder surface.
                flight_fn=self.flight.snapshot,
                # Time-travel range queries (from/to params + Grafana
                # true ranges) answer from the on-disk log; every
                # range read lands one latency observation.
                history=self.history_reader,
                read_observe=self._observe_history_read,
                # Evicted-key continuity: how far back the fallback
                # searches history for a name the live table dropped.
                evicted_lookback_s=self._query_evicted_lookback_s,
            )
            self.query_service = QueryService(
                self.query_engine, registry=self.registry,
                port=self._query_port_req,
            )
            if self._query_grpc_port_req >= 0:
                try:
                    from .query import QueryGrpcService

                    self.query_grpc = QueryGrpcService(
                        self.query_engine, registry=self.registry,
                        port=self._query_grpc_port_req,
                    )
                except ImportError:  # grpcio absent: HTTP leg serves
                    self.query_grpc = None
        self._stop = threading.Event()
        self._last_ckpt = time.monotonic()

    # -- supervised construction ---------------------------------------

    def _on_ingest_reject(self, transport: str):
        def bump(reason: str) -> None:
            self.registry.counter_add(
                tele_metrics.ANOMALY_INGEST_REJECTED, 1.0,
                transport=transport, reason=reason,
            )

        return bump

    def _make_http_receiver(self, port: int) -> OtlpHttpReceiver:
        return OtlpHttpReceiver(
            self.pipeline.submit,
            port=port,
            on_columnar=self.pipeline.submit_columnar,
            # Parallel ingest: raw protobuf trace bodies go to the
            # decode pool (late-bound so a restarted pool is followed).
            on_payload=(
                (lambda body: self.ingest_pool.submit(body))
                if self.ingest_pool is not None else None
            ),
            on_metric_records=self.metrics_feed.submit,
            on_log_records=self._on_logs,
            on_reject=self._on_ingest_reject("http"),
            max_body_bytes=self.max_body_bytes,
            # Backpressure: the pipeline's single admission question —
            # late-bound through self so a restarted receiver follows.
            retry_after=lambda: self.pipeline.admission_retry_after(),
        )

    def _make_grpc_receiver(self, port: int):
        from .otlp_grpc import OtlpGrpcReceiver

        return OtlpGrpcReceiver(
            self.pipeline.submit,
            port=port,
            on_columnar=self.pipeline.submit_columnar,
            on_payload=(
                (lambda body: self.ingest_pool.submit(body))
                if self.ingest_pool is not None else None
            ),
            on_metric_records=self.metrics_feed.submit,
            on_log_records=self._on_logs,
            on_reject=self._on_ingest_reject("grpc"),
            max_body_bytes=self.max_body_bytes,
            component_status=self._supervisor.health_status,
            retry_after=lambda: self.pipeline.admission_retry_after(),
        )

    def _restart_http_receiver(self) -> None:
        if self.role == ROLE_FENCED or self.receiver is None:
            return  # fenced: the stop was deliberate, stay down
        # Rebind on the RESOLVED port: env may have requested :0, and
        # the collector's exporter keeps pointing at the first bind.
        port = self.receiver.port
        try:
            self.receiver.stop()
        except Exception:  # noqa: BLE001 — a dead server may half-stop
            pass
        self.receiver = self._make_http_receiver(port)
        self.receiver.start()

    def _start_frontdoor(self) -> None:
        """Opt-in native OTLP/HTTP front door (FRONTDOOR_KNOBS).

        Started only on a serving primary, only when
        ANOMALY_FRONTDOOR_ENABLE=1, only with a decode pool to ticket
        into (ANOMALY_INGEST_WORKERS>0), and only when the native
        library built — every other combination keeps the Python
        receiver as the sole door and logs why.
        """
        fd = self._frontdoor_cfg
        if int(fd["ANOMALY_FRONTDOOR_ENABLE"]) != 1:
            return
        if self.frontdoor is not None:
            return
        log = logging.getLogger(__name__)
        if self.ingest_pool is None:
            log.warning(
                "ANOMALY_FRONTDOOR_ENABLE=1 ignored: the front door "
                "tickets into the decode pool and "
                "ANOMALY_INGEST_WORKERS=0 built none"
            )
            return
        from . import native as _native

        if not _native.frontdoor_available():
            log.warning(
                "ANOMALY_FRONTDOOR_ENABLE=1 ignored: native front-door "
                "library unavailable (%s)", _native.frontdoor_load_error()
            )
            return
        from .frontdoor import FrontDoorServer

        self.frontdoor = FrontDoorServer(
            self.ingest_pool,
            port=int(fd["ANOMALY_FRONTDOOR_PORT"]),
            max_body_bytes=self.max_body_bytes,
            pumps=int(fd["ANOMALY_FRONTDOOR_PUMPS"]),
            batch_max=int(fd["ANOMALY_FRONTDOOR_BATCH"]),
            max_conns=int(fd["ANOMALY_FRONTDOOR_MAX_CONNS"]),
            retry_after=lambda: self.pipeline.admission_retry_after(),
            on_reject=self._on_ingest_reject("frontdoor"),
            on_metric_records=self.metrics_feed.submit,
            on_log_records=self._on_logs,
        )
        log.info("native front door serving on :%d", self.frontdoor.port)

    def _restart_grpc_receiver(self) -> None:
        if self.role == ROLE_FENCED or self.grpc_receiver is None:
            return
        port = self.grpc_receiver.port
        try:
            self.grpc_receiver.stop(grace=0.5)
        except Exception:  # noqa: BLE001 — best-effort stop of the old receiver before rebind
            pass
        self.grpc_receiver = self._make_grpc_receiver(port)
        self.grpc_receiver.start()

    def _probe_grpc(self) -> bool:
        if self.role == ROLE_FENCED or self.grpc_receiver is None:
            return True  # deliberately down, nothing to restart
        from .health_probe import probe

        return probe(f"127.0.0.1:{self.grpc_receiver.port}", timeout_s=2.0)

    # -- logs ingress ---------------------------------------------------

    def _on_logs(self, docs) -> None:
        """OTLP logs → store + per-service severity counts.

        The decoders normalize severity at the boundary
        (logstore.normalize_severity), so docs arrive on the canonical
        5-level scale. ERROR/FATAL counts also enter the metrics head
        as a delta-sum lane per service — the "error-log rate" signal
        the spanmetrics leg can't see.
        """
        from .otlp_metrics import TEMPORALITY_DELTA, MetricRecord

        error_counts: dict[str, float] = {}
        n = 0
        for doc in docs:
            self.log_store.add(doc)
            n += 1
            if doc.severity in ("ERROR", "FATAL"):
                error_counts[doc.service] = error_counts.get(doc.service, 0.0) + 1.0
        if error_counts:
            self.metrics_feed.submit([
                MetricRecord(
                    service=svc, name="log_error_records", value=v,
                    kind="sum", monotonic=True, temporality=TEMPORALITY_DELTA,
                )
                for svc, v in error_counts.items()
            ])
        if n:
            self.registry.counter_add(
                tele_metrics.ANOMALY_LOG_RECORDS_TOTAL, float(n)
            )

    # -- health surface -------------------------------------------------

    def _healthz(self):
        """/healthz payload: overall state + the overload/supervision
        numbers an operator triages with. ``saturated`` is distinct
        from ``degraded`` (and loses to it — supervision.SATURATED):
        a shedding daemon is healthy-but-browning-out, a crash-looping
        one is not."""
        from .supervision import UP

        state = self._supervisor.overall_state()
        detail = {
            "components": self._supervisor.states(),
            "queue_rows": self.pipeline.pending_rows(),
            "queue_max_rows": self.pipeline.queue_max_rows,
            "brownout_level": self.pipeline.brownout_level,
            "shed_rows": dict(self.pipeline.stats.shed_rows),
            # Replication surface: how Grafana/k8s tell a healthy
            # standby (role=standby, status ok) from a degraded primary
            # — and what health_probe --role prints.
            "role": self.role,
            "epoch": self._fence.epoch,
            # Process birth time: lets an operator (and the build_info
            # gauge's dashboards) correlate restarts with verdicts.
            "start_ts": self._start_ts,
            # Auto-mitigation surface: what is mitigated right now and
            # whether any mitigation FAILED (the DEGRADED-style state
            # an operator triages before trusting the loop again).
            "mitigation": {
                "enabled": self.remediation.enabled,
                "active": self.remediation.active_count(),
                "failed": self.remediation.failed_services(),
            },
        }
        # Keyspace block (the cardinality-bomb triage surface): how
        # full the intern table is, which ladder rung is engaged, and
        # the generation epoch peers must match to merge frames.
        # Present even with the evictor disabled — fill + RSS are the
        # early-warning numbers.
        if self.keyspace is not None:
            detail["keyspace"] = self.keyspace.stats()
        else:
            tz = self.pipeline.tensorizer
            detail["keyspace"] = {
                "level": self.pipeline.keyspace_level,
                "rows": tz.live_keys,
                "capacity": tz.capacity,
                "fill": round(tz.live_keys / max(tz.capacity, 1), 4),
                "free_ids": tz.free_ids,
                "generation": tz.generation,
                "evicted_total": tz.evicted_total,
            }
        if self.shadow_verifier is not None:
            # Counterfactual gate surface (separate block so the
            # mitigation block's shape stays pinned): verdict counts
            # by direction + refusal reasons.
            st = self.remediation.stats()
            detail["shadow"] = {
                "runs": self.shadow_verifier.runs,
                "verdicts": st["preflight_verdicts"],
                "refused": st["preflight_refused"],
            }
        if self.fleet is not None:
            # Fleet block (health_probe --shard reads this): ring
            # version, member set, peer liveness, reshard counters —
            # how an operator tells "one shard browned out" from "the
            # fleet is splitting". The adoption sub-block rides along:
            # what this heir merged, what it refused, at what TTA.
            detail["fleet"] = self.fleet.snapshot()
            detail["fleet"]["adoptions"] = {
                "total": self._adoptions_total,
                "refused": dict(self._adoptions_refused),
                "last_tta_s": self._last_adoption_tta,
                "mirror_target": self._adoption_target,
            }
        # Autoscale surface: the deployment layer reads the proposed
        # target from here (and the scrape) — a resize is one
        # FLEET_KNOBS change, this block says which one.
        detail["autoscale"] = self.autoscaler.stats()
        return ("ok" if state == UP else state), detail

    # -- self-telemetry -------------------------------------------------

    # Windowed overlap-ratio buckets: the interesting band is the top
    # end (is the put hidden or not), so the ladder is top-heavy.
    _OVERLAP_BUCKETS = (0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)

    def _observe_phase(self, phase: str, seconds: float) -> None:
        """The pipeline/pool phase hook → promoted histograms: each
        lifecycle phase lands in anomaly_phase_seconds{phase=}, except
        the two with their own dedicated series (put-wait, harvest
        lag). Phase labels come from the runtime.selftrace constant
        table — the trace-discipline pass fences the call sites."""
        if phase == selftrace.PHASE_HARVEST_LAG:
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_HARVEST_LAG, seconds,
                selftrace.PHASE_BUCKETS,
            )
        elif phase == selftrace.PHASE_PUT_WAIT:
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_SPINE_PUT_WAIT, seconds,
                selftrace.PHASE_BUCKETS,
            )
        else:
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_PHASE_SECONDS, seconds,
                selftrace.PHASE_BUCKETS, phase=phase,
            )

    def _flight_health_tick(self) -> None:
        """Edge-detect health/brownout/fence movement into the flight
        recorder; DEGRADED/SATURATED transitions dump evidence (role
        transitions dump from their own paths — promote/_become_fenced
        — so a standby that never saturates still leaves a trail)."""
        from .supervision import DEGRADED, SATURATED

        state = self._supervisor.overall_state()
        if state != self._flight_last_state:
            self.flight.record(
                "health", state=state, prev=self._flight_last_state,
                role=self.role, epoch=self._fence.epoch,
            )
            if state in (DEGRADED, SATURATED):
                self.flight.dump(state)
            self._flight_last_state = state
        brownout = self.pipeline.brownout_level
        if brownout != self._flight_last_brownout:
            self.flight.record(
                "brownout", level=brownout,
                prev=self._flight_last_brownout,
            )
            self._flight_last_brownout = brownout
        fence_total = sum(self._fence.fenced_by_path.values())
        if fence_total != self._flight_fence_seen:
            self.flight.record(
                "fence", total=fence_total,
                by_path=dict(self._fence.fenced_by_path),
            )
            self._flight_fence_seen = fence_total

    def _selftrace_delta(self, metric: str, key: str, value: int,
                         **labels) -> None:
        """Delta export with a seen-map the REPLICATION restart path
        never clears: the flight/tracer objects live for the process,
        so sharing _repl_counters (cleared on a supervised replication
        restart) would re-emit their full lifetime totals."""
        if not hasattr(self, "_selftrace_seen"):
            self._selftrace_seen = {}
        delta = value - self._selftrace_seen.get(key, 0)
        if delta > 0:
            self.registry.counter_add(metric, float(delta), **labels)
        self._selftrace_seen[key] = value

    def _export_selftrace_stats(self) -> None:
        """Flight/tracer counters → Prometheus (delta-based, like the
        replication exports), plus the tracer poster's sender-queue
        stats on the shared export family."""
        events, dumps = self.flight.counts()
        for kind, count in events.items():
            self._selftrace_delta(
                tele_metrics.ANOMALY_FLIGHT_EVENTS,
                f"flight_ev_{kind}", count, kind=kind,
            )
        for reason, count in dumps.items():
            self._selftrace_delta(
                tele_metrics.ANOMALY_FLIGHT_DUMPS,
                f"flight_dump_{reason}", count, reason=reason,
            )
        if self.selftrace is not None:
            stats = self.selftrace.stats()
            self._selftrace_delta(
                tele_metrics.ANOMALY_SELFTRACE_TRACES,
                "selftrace_traces", stats["traces_exported"],
            )
            self._selftrace_delta(
                tele_metrics.ANOMALY_SELFTRACE_SPANS,
                "selftrace_spans", stats["spans_exported"],
            )
        if self._selftrace_poster is not None:
            self._selftrace_delta(
                tele_metrics.ANOMALY_EXPORT_DROPPED,
                "selftrace_dropped", self._selftrace_poster.dropped,
                signal="selftrace",
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_EXPORT_QUEUE_DEPTH,
                float(self._selftrace_poster.take_high_water()),
                signal="selftrace",
            )

    # -- report → metrics ---------------------------------------------

    def _warm_widths_quietly(self) -> None:
        """Background ladder precompile; failure is non-fatal (the
        controller would then pay one compile at escalation time)."""
        try:
            self.pipeline.warm_widths()
        except Exception:  # noqa: BLE001 — warmup must never kill boot
            pass

    # -- remediation wiring --------------------------------------------

    def _exemplars_for(self, service: str) -> list[str]:
        """Flag-time exemplar trace ids for one service (the sampling
        actuator's policy seed; remediation worker thread — reads the
        pipeline's query meta under its own query lock, never the
        dispatch lock)."""
        names = self.pipeline.tensorizer.service_names
        if service not in names:
            return []
        idx = names.index(service)
        block = self.pipeline.query_meta()
        events = (block.get("exemplars") or {}).get(str(idx), [])
        return [e.get("trace_id") for e in events if e.get("trace_id")]

    def _bundle_for(self, service: str | int) -> str | None:
        """Newest evidence-bundle id for one service — the remediation
        (by name) and pre-flight (by index) citation hook: every
        episode/refusal names the verdict it answers (worker thread;
        query lock only, same discipline as ``_exemplars_for``)."""
        if self.provenance is None:
            return None
        if isinstance(service, int):
            names = self.pipeline.tensorizer.service_names
            if not 0 <= service < len(names):
                return None
            service = names[service]
        block = self.pipeline.query_meta()
        for b in reversed(block.get("explains") or []):
            if b.get("service") == service:
                return b.get("id")
        return None

    def _publish_sampling_policy(self, policy, seeds) -> None:
        """The sampling actuator's one push target: the history
        writer's span-capture sampler when the time-travel tier is on
        (flagged service records at 100% — the mitigation-drill
        corpus), a flight-recorder note either way."""
        if self.history_writer is not None:
            self.history_writer.set_span_sample(policy)
        self.flight.record(
            "mitigation", op="sampling_policy", policy=dict(policy),
            seeds={svc: len(ex) for svc, ex in (seeds or {}).items()},
        )

    def _preflight_mitigation(self, service: str):
        """The controller's pre-flight hook (worker thread): replay
        the recorded window with the service's fault columns
        suppressed — the counterfactual of the flagd mitigation — and
        return the shadow verdict. An unmappable service fails closed
        (the verifier could prove nothing about it)."""
        names = self.pipeline.tensorizer.service_names
        if service not in names:
            return shadow.refused(shadow.REASON_ERROR)
        idx = names.index(service)
        return self.shadow_verifier.verify(
            idx, shadow.suppress_transform(idx)
        )

    def _export_remediation_stats(self) -> None:
        """anomaly_mitigation_* (delta-based like every family) plus
        the TTM histogram observations drained from the controller."""
        st = self.remediation.stats()
        seen = self._remediation_seen
        for actuator, count in st["actions"].items():
            key = f"act_{actuator}"
            delta = count - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_MITIGATION_ACTIONS,
                    float(delta), actuator=actuator,
                )
            seen[key] = count
        for key, metric in (
            ("rollbacks", tele_metrics.ANOMALY_MITIGATION_ROLLBACKS),
            ("verified", tele_metrics.ANOMALY_MITIGATION_VERIFIED),
            ("failed", tele_metrics.ANOMALY_MITIGATION_FAILED),
        ):
            delta = st[key] - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(metric, float(delta))
            seen[key] = st[key]
        self.registry.gauge_set(
            tele_metrics.ANOMALY_MITIGATION_ACTIVE, float(st["active"])
        )
        for ttm, _act_to_recover in self.remediation.take_ttm_samples():
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_TIME_TO_MITIGATE, ttm,
                remediation.TTM_BUCKETS,
            )
        # Pre-flight family (delta-based like the rest; series appear
        # only once a verdict exists, so a gate-less daemon's scrape
        # is unchanged).
        for verdict, count in st["preflight_verdicts"].items():
            key = f"pf_{verdict}"
            delta = count - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_PREFLIGHT_VERDICTS,
                    float(delta), verdict=verdict,
                )
            seen[key] = count
        for reason, count in st["preflight_refused"].items():
            key = f"pfr_{reason}"
            delta = count - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_PREFLIGHT_REFUSED,
                    float(delta), reason=reason,
                )
            seen[key] = count
        for verdict_s in self.remediation.take_preflight_samples():
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_PREFLIGHT_SECONDS, verdict_s,
                shadow.PREFLIGHT_BUCKETS,
            )
        if self._collector_actuator is not None:
            self.registry.gauge_set(
                tele_metrics.ANOMALY_COLLECTOR_KEEP_RATIO,
                float(self._collector_actuator.keep_ratio()),
            )

    # -- report export --------------------------------------------------

    def _on_report(self, t_batch, report, flagged) -> None:
        names = self.pipeline.tensorizer.service_names
        # Close the loop: the controller sees the same per-service
        # verdicts the query plane serves (hot path: streak bookkeeping
        # under the controller's own lock, never I/O — actuator writes
        # happen on its worker thread). getattr: the width-ladder
        # warmup thread can deliver a report during __init__, before
        # the controller block runs.
        rem = getattr(self, "remediation", None)
        if rem is not None:
            try:
                rem.observe(t_batch, flagged, services=names)
            except Exception:  # noqa: BLE001 — the mitigation loop
                # must never take down report export; a controller bug
                # costs mitigations, not detection.
                logging.getLogger(__name__).exception(
                    "remediation observe failed"
                )
        tele_metrics.export_report(self.registry, names, report, flagged)
        self.registry.gauge_set(
            tele_metrics.ANOMALY_LAG_P99, self.pipeline.stats.lag_p99_ms()
        )
        self.registry.counter_add(
            tele_metrics.ANOMALY_SPANS_TOTAL,
            float(self.pipeline.stats.spans - getattr(self, "_spans_seen", 0)),
        )
        self._spans_seen = self.pipeline.stats.spans

    def _on_metrics_report(self, t_batch, report) -> None:
        names = self.metrics_feed.service_names
        flagged = self.metrics_feed.flagged_services(report, names)
        tele_metrics.export_metrics_report(
            self.registry,
            names,
            self.metrics_feed.metric_slot_names(),
            report,
            flagged,
            seen=self._metric_series_seen,
        )
        self.registry.counter_add(
            tele_metrics.ANOMALY_METRIC_POINTS_TOTAL,
            float(self.metrics_feed.points_total - getattr(self, "_points_seen", 0)),
        )
        self._points_seen = self.metrics_feed.points_total

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        # Fleet membership + the optional embedded aggregator come up
        # for EVERY role: heartbeats are reads, the aggregator mutates
        # nothing, and a standby that boots with a cold membership
        # table would misjudge the fleet at promotion time.
        if self.fleet is not None:
            self.fleet.start()
            if self.aggregator_service is not None:
                self.aggregator_service.start()
        if self.role == ROLE_STANDBY:
            # A standby serves only its metrics/health surface and the
            # replication client; ingest legs come up at promotion.
            # In read-replica mode it ALSO serves the query API from
            # the replicated mirror — the standby stops idling and
            # becomes the read path, while remaining promotable.
            self.exporter.start()
            self._start_replication_standby()
            if self._query_read_replica:
                self._start_query_plane()
            return
        if self.role == ROLE_FENCED:
            # Boot-fenced: health/metrics stay observable (that is how
            # the operator finds us), but no ingest, no replication —
            # readiness probes against the (absent) ingest ports fail
            # and the orchestrator keeps traffic on the live primary.
            # The query plane stays up: reads mutate nothing, and every
            # answer is labeled role=fenced for the operator to judge.
            self.exporter.start()
            self._start_query_plane()
            return
        self.receiver.start()
        if self.grpc_receiver is not None:
            self.grpc_receiver.start()
        self._start_frontdoor()
        self.exporter.start()
        self._start_query_plane()
        self._start_history_writer()
        self._start_keyspace()
        self._register_serving_components()
        if self._repl_port >= 0:
            self._start_replication_primary()

    def _register_serving_components(self) -> None:
        # Thread/server-backed components join the supervision tree
        # once they are actually up (registering before start() would
        # probe a receiver that hasn't bound yet).
        self._supervisor.register(
            "otlp-http",
            restart=self._restart_http_receiver,
            # Late-bound: a restart swaps self.receiver for a new
            # object, and the probe must follow it. Fenced = the
            # receiver was stopped ON PURPOSE — not a crash to undo.
            probe=lambda: (
                self.role == ROLE_FENCED
                or (self.receiver is not None and self.receiver.alive())
            ),
        )
        if self.grpc_receiver is not None:
            self._supervisor.register(
                "otlp-grpc",
                restart=self._restart_grpc_receiver,
                # A real health-check RPC on a slow cadence: the grpc
                # core owns its threads, so thread-liveness can't see a
                # wedged server — only the wire can.
                probe=self._probe_grpc,
                probe_interval_s=10.0,
            )
        if self.pipeline.harvest_async:
            self._supervisor.register(
                "harvester",
                restart=self.pipeline.restart_harvester,
                probe=self.pipeline.harvester_alive,
            )

    # -- replication wiring --------------------------------------------

    def _replication_snapshot(self) -> tuple[dict, dict]:
        """(arrays, meta) of the CURRENT state for the replication
        layer. Snapshotted under the pipeline's dispatch lock — live
        dispatch DONATES the state buffers, so an unlocked read could
        touch a just-deleted array (same rule as warm_widths)."""
        import numpy as np

        with self.pipeline._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in self.detector.state._asdict().items()
            }
            clock_t_prev = self.detector.clock._t_prev
        meta = {
            # Confirmed offsets ONLY (self._offsets merges after flush
            # confirmation — the PR-3 rule): a standby promoted from
            # this map replays any unconfirmed tail, never skips it.
            "offsets": self._offsets_snapshot(),
            "service_names": self.pipeline.tensorizer.service_names,
            # Keyspace generation: bumped by every eviction sweep.
            # Standbys refuse DELTAS from a different generation (the
            # arrays' slot→service mapping changed under them) and
            # adopt the new one wholesale from the next snapshot.
            "generation": self.pipeline.tensorizer.generation,
            "clock_t_prev": clock_t_prev,
            "config": list(
                self.detector.config._replace(sketch_impl=None)
            ),
            # Query-plane block (exemplar rings, anomaly events, top-k
            # candidates — all JSON-able): riding the replication meta
            # is what lets a read replica answer exemplar/anomaly/top-k
            # queries bit-identically to the primary.
            "query": self.pipeline.query_meta(),
        }
        return arrays, meta

    # -- query plane ---------------------------------------------------

    def _query_snapshot(self) -> tuple[dict, dict]:
        """THE query plane's single state access, role-dispatched:
        a standby answers from its replicated mirror (so queries work
        before promotion and fail over WITH the role), everything else
        from the replication snapshot helper — which copies live state
        under the pipeline's dispatch lock, the same discipline that
        keeps replication from racing donated device buffers.
        runtime/query.py itself never touches detector state
        (scripts/sanitycheck.py pins that statically)."""
        if (
            self.role in (ROLE_STANDBY, ROLE_PROMOTING)
            and self.repl_standby is not None
        ):
            return self.repl_standby.snapshot()
        return self._replication_snapshot()

    def _query_lag(self) -> float:
        """The replica half of reported staleness: seconds since the
        last replication frame on a standby, 0 on a serving role (its
        snapshot IS the live state at refresh time)."""
        if (
            self.role in (ROLE_STANDBY, ROLE_PROMOTING)
            and self.repl_standby is not None
        ):
            return max(self.repl_standby.seconds_since_frame(), 0.0)
        return 0.0

    def _start_query_plane(self) -> None:
        """Start + supervise the query listeners (idempotent): called
        at boot for serving roles and read-replica standbys, and at
        promotion for a standby that booted with read-replica off."""
        if self.query_service is None or self._query_started:
            return
        self.query_service.start()
        if self.query_grpc is not None:
            # The gRPC twin is optional: losing it must not take the
            # HTTP leg (already bound) down with it, and leaving
            # _query_started unset here would double-start HTTP on
            # the next call.
            try:
                self.query_grpc.start()
            except Exception:  # noqa: BLE001 — optional twin; HTTP alone still serves every query
                logging.getLogger(__name__).exception(
                    "query gRPC twin failed to start; HTTP-only"
                )
                self.query_grpc = None
        self._query_started = True
        if not self._supervisor.registered("query"):
            self._supervisor.register(
                "query", base_backoff_s=0.5, max_backoff_s=15.0,
                probe=lambda: (
                    self.query_service is None
                    or self.query_service.alive()
                ),
                restart=self._restart_query_service,
            )

    def _start_history_writer(self) -> None:
        """Start + supervise the compaction thread (idempotent):
        serving roles only — a standby's state is the primary's
        mirror, and recording it too would double the log."""
        if self.history_writer is None:
            return
        self.history_writer.start()
        if not self._supervisor.registered("history"):
            self._supervisor.register(
                "history", base_backoff_s=1.0, max_backoff_s=30.0,
                # A FENCED writer stopped on purpose; don't restart it.
                probe=lambda: (
                    self.role == ROLE_FENCED
                    or self.history_writer is None
                    or self.history_writer.alive()
                ),
                restart=self._restart_history_writer,
            )

    def _restart_history_writer(self) -> None:
        if self.history_writer is None or self.role == ROLE_FENCED:
            return
        self.history_writer.start()  # idempotent while alive

    def _start_keyspace(self) -> None:
        """Start + supervise the keyspace watchdog (idempotent):
        serving roles only — an eviction sweep WRITES detector state
        and bumps the generation, so a standby running its own sweeps
        would drift from the primary instead of mirroring it."""
        if self.keyspace is None:
            return
        self.keyspace.start()
        if not self._supervisor.registered("keyspace"):
            self._supervisor.register(
                "keyspace", base_backoff_s=0.5, max_backoff_s=15.0,
                probe=lambda: (
                    self.role == ROLE_FENCED
                    or self.keyspace is None
                    or self.keyspace.alive()
                ),
                restart=self._restart_keyspace,
            )

    def _restart_keyspace(self) -> None:
        if self.keyspace is None or self.role == ROLE_FENCED:
            return
        self.keyspace.start()  # idempotent while alive

    def _observe_history_read(self, seconds: float) -> None:
        from .query import LATENCY_BUCKETS

        self.registry.histogram_observe(
            tele_metrics.ANOMALY_HISTORY_READ_LATENCY, seconds,
            LATENCY_BUCKETS,
        )

    def _export_history_stats(self) -> None:
        """anomaly_history_* gauges/counters (delta-based like every
        other family), plus corrupt records on the shared
        anomaly_frame_corrupt_total{hop=history} series."""
        store = self.history_store
        if store is None:
            return
        st = store.stats()
        self.registry.gauge_set(
            tele_metrics.ANOMALY_HISTORY_SEGMENTS, float(st["segments"])
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_HISTORY_BYTES, float(st["bytes"])
        )
        oldest = st["oldest_t"]
        self.registry.gauge_set(
            tele_metrics.ANOMALY_HISTORY_OLDEST,
            max(time.time() - oldest, 0.0) if oldest else 0.0,
        )
        seen = self._history_seen
        if self.history_writer is not None:
            comp = self.history_writer.compactions
            if comp > seen["compactions"]:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_HISTORY_COMPACTIONS,
                    float(comp - seen["compactions"]),
                )
                seen["compactions"] = comp
        corrupt = st["frames_corrupt"]
        if corrupt > seen["frames_corrupt"]:
            self.registry.counter_add(
                tele_metrics.ANOMALY_FRAME_CORRUPT,
                float(corrupt - seen["frames_corrupt"]), hop="history",
            )
            self.flight.record(
                "quarantine", hop="history",
                frames=int(corrupt - seen["frames_corrupt"]),
            )
            seen["frames_corrupt"] = corrupt

    def _restart_query_service(self) -> None:
        if self.query_service is None:
            return
        from .query import QueryService

        port = self.query_service.port
        try:
            self.query_service.stop()
        except Exception:  # noqa: BLE001 — a dead server may half-stop
            pass
        self.query_service = QueryService(
            self.query_engine, registry=self.registry, port=port
        )
        self.query_service.start()

    def _export_query_stats(self) -> None:
        """Per-step query-plane housekeeping: keep the snapshot cache
        within its staleness budget even with no queries arriving (the
        timeline ring accretes from these refreshes), export the
        staleness gauge and the exemplar-capture counter delta."""
        self.query_engine.maybe_refresh()
        staleness = self.query_engine.staleness_s()
        if staleness != float("inf"):
            self.registry.gauge_set(
                tele_metrics.ANOMALY_QUERY_STALENESS, staleness
            )
        captured = self.pipeline.exemplars_captured
        delta = captured - self._exemplars_seen
        if delta > 0:
            self.registry.counter_add(
                tele_metrics.ANOMALY_EXEMPLARS_CAPTURED, float(delta)
            )
            self._exemplars_seen = captured

    def _export_provenance_stats(self) -> None:
        """Provenance housekeeping each step: the built-counter delta
        (same seen-baseline discipline as exemplars — a restore must
        not replay old increments), build-latency observations, and
        the export drain — each drained bundle lands in the history
        tier (ranged /query/explain after restart) and, when the
        collector endpoint is configured, ships as one OTLP log
        record on the shared background poster."""
        if self.provenance is None:
            return
        from .query import LATENCY_BUCKETS

        built = self.pipeline.explanations_built
        delta = built - self._explanations_seen
        if delta > 0:
            self.registry.counter_add(
                tele_metrics.ANOMALY_EXPLANATIONS_BUILT, float(delta)
            )
            self._explanations_seen = built
        for seconds in self.provenance.take_build_samples():
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_EXPLAIN_LATENCY, seconds,
                LATENCY_BUCKETS,
            )
        bundles = self.pipeline.take_explain_exports()
        if bundles:
            if self.history_writer is not None:
                for b in bundles:
                    self.history_writer.capture_explain(b)
            if self._explain_poster is not None:
                docs = [provenance.log_doc(b) for b in bundles]
                self._explain_poster(time.time(), docs)
            if (self.history_writer is not None
                    or self._explain_poster is not None):
                self.registry.counter_add(
                    tele_metrics.ANOMALY_EXPLANATIONS_EXPORTED,
                    float(len(bundles)),
                )
        if self._explain_poster is not None:
            self._explain_poster.publish_stats(
                self.registry, signal="explain"
            )

    def _register_replication_component(self) -> None:
        """One supervised 'replication' component for either role: the
        standby watchdog thread and the primary listener both restart
        under the same backoff/budget discipline as every ingest leg.
        Registered once — a supervised restart must not reset its own
        crash-budget accounting."""
        if "replication" in self._supervisor._components:
            return
        self._supervisor.register(
            "replication", base_backoff_s=0.5, max_backoff_s=15.0,
            probe=self._replication_alive,
            restart=self._restart_replication,
        )

    def _replication_alive(self) -> bool:
        if self.role == ROLE_STANDBY and self.repl_standby is not None:
            return self.repl_standby.alive()
        if self.role == ROLE_PRIMARY and self.repl_primary is not None:
            return self.repl_primary.alive()
        return True  # promoting/fenced: nothing to probe

    def _restart_replication(self) -> None:
        # The replacement object counts from zero: reset the delta
        # baselines so its first exports aren't swallowed by the old
        # object's high-water marks (see _export_counter_delta).
        self._repl_counters().clear()
        if self.role == ROLE_STANDBY and self.repl_standby is not None:
            try:
                self.repl_standby.stop()
            except Exception:  # noqa: BLE001 — may be half-dead already
                pass
            self._start_replication_standby()
        elif self.role == ROLE_PRIMARY and self.repl_primary is not None:
            port = self.repl_primary.port
            try:
                self.repl_primary.stop()
            except Exception:  # noqa: BLE001 — best-effort stop before relisten
                pass
            self._start_replication_primary(port=port)

    def _offsets_snapshot(self) -> dict[int, int]:
        with self._offsets_lock:
            return {int(p): int(o) for p, o in self._offsets.items()}

    def _start_replication_primary(self, port: int | None = None) -> None:
        self.repl_primary = replication.ReplicationPrimary(
            snapshot_fn=self._replication_snapshot,
            fence=self._fence,
            port=self._repl_port if port is None else port,
            interval_s=self._repl_interval_s,
        )
        self.repl_primary.start()
        self._register_replication_component()

    def _start_replication_standby(self) -> None:
        self.repl_standby = replication.ReplicationStandby(
            target=self._repl_target,
            fence=self._fence,
            config_fingerprint=list(
                self.detector.config._replace(sketch_impl=None)
            ),
            # Abandon a half-open session well before the promotion
            # watchdog would fire on the same silence.
            silence_reconnect_s=max(3 * self._repl_interval_s, 2.0),
        )
        self.repl_standby.start()
        self._register_replication_component()

    def step(self, t_now: float | None = None) -> None:
        """One pump + housekeeping tick (public for tests/sims)."""
        # Fleet gauges for EVERY role (a standby's membership view
        # must be scrapeable too — it inherits the ring at promotion).
        self._export_fleet_stats()
        if self.role in (ROLE_STANDBY, ROLE_PROMOTING):
            self._standby_step()
            return
        if self.role == ROLE_PRIMARY and self._fence.stale():
            # Someone promoted past us (learned via a replication
            # frame, the broker's commit tags, or the checkpoint
            # volume): stop writing IMMEDIATELY and visibly.
            self._become_fenced()
        if self.role == ROLE_FENCED:
            # A fenced ex-primary keeps draining what it already
            # admitted (and keeps its health/metrics surface honest)
            # but performs no durable writes: no orders pump, no offset
            # commits, no checkpoints.
            self.pipeline.pump(
                time.monotonic() if t_now is None else t_now
            )
            self.metrics_feed.pump(
                time.monotonic() if t_now is None else t_now
            )
            self._export_fence_stats()
            self._flight_health_tick()
            self._export_selftrace_stats()
            # Deadlines/budget still advance (rollbacks of pre-fence
            # actuations must fire), but every actuator WRITE is
            # refused by fence.check(path="remediation") — the fenced
            # daemon observes its loop, it never drives it.
            self.remediation.tick(
                time.monotonic() if t_now is None else t_now
            )
            self._export_remediation_stats()
            # Autoscale housekeeping too: the budget refills, and every
            # would-be proposal is refused by fence.check — the fenced
            # counter IS the sixth path's audit trail.
            self.autoscaler.tick(
                time.monotonic() if t_now is None else t_now
            )
            self._export_autoscale_stats()
            if self.query_engine is not None and self._query_started:
                self._export_query_stats()
            self._export_provenance_stats()
            self._supervisor.tick()
            return
        # Self-telemetry on a 1 s cadence (the collector's own otelcol_*
        # habit): ingest/batch/backlog visibility even before the first
        # detector report, and the first handle on a wedged pipeline.
        now_mono = time.monotonic()
        if now_mono - getattr(self, "_last_self_report", 0.0) >= 1.0:
            self._last_self_report = now_mono
            # docker_stats analogue: this container's resource stats on
            # the same exposition the shop's processes use.
            if not hasattr(self, "_proc_stats"):
                from ..telemetry.receivers import ProcessStatsReceiver

                self._proc_stats = ProcessStatsReceiver(
                    "anomaly-detector", registry=self.registry
                )
            self._proc_stats.scrape()
            self.registry.gauge_set(
                tele_metrics.ANOMALY_PENDING_ROWS,
                float(self.pipeline._pending_rows),
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_BATCHES_DISPATCHED,
                float(self.pipeline.stats.batches),
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_SPANS_INGESTED,
                float(self.pipeline.stats.spans),
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_LOG_DOCS_STORED,
                float(self.log_store.count()),
            )
            # History-tier gauges on the same 1 s cadence (they walk
            # the segment dir listing — not per-step work).
            self._export_history_stats()
            # Keyspace/RSS gauges on the 1 s cadence too (the RSS
            # sample is a /proc open+scan; the ladder moves on hold_s
            # timescales, never sub-second).
            self._export_keyspace_stats()
            # Trend context for any later transition dump: a compact
            # 1 Hz snapshot of where batch time goes right now.
            spine_st = self.pipeline.spine_stats()
            self.flight.record(
                "phase_snapshot",
                pool_phase_s=(
                    dict(self.ingest_pool.stats()["phase_s"])
                    if self.ingest_pool is not None else None
                ),
                spine_overlap=(
                    spine_st["overlap_ratio"] if spine_st else None
                ),
                pending_rows=self.pipeline.pending_rows(),
                lag_p99_ms=self.pipeline.stats.lag_p99_ms(),
            )
            # One autoscale observation window per self-report (the
            # same 1 s cadence ACT_BATCHES counts in).
            self._autoscale_observe(now_mono)
        # Overload gauges/counters every step (not the 1 s cadence):
        # saturation flips sub-second and the chaos tests scrape between
        # steps — a few dict writes, nothing device-side.
        self.registry.gauge_set(
            tele_metrics.ANOMALY_QUEUE_ROWS,
            float(self.pipeline.pending_rows()),
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_BROWNOUT_LEVEL,
            float(self.pipeline.brownout_level),
        )
        shed = self.pipeline.stats.shed_rows
        for lane in ("ok", "error"):
            delta = shed[lane] - self._shed_seen[lane]
            if delta:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_SHED_ROWS, float(delta),
                    lane=lane, cause="overflow",
                )
                self._shed_seen[lane] = shed[lane]
                self.flight.record("shed", lane=lane, rows=int(delta))
        brownout = self.pipeline.stats.brownout_rows
        if brownout != self._brownout_seen:
            self.registry.counter_add(
                tele_metrics.ANOMALY_SHED_ROWS,
                float(brownout - self._brownout_seen),
                lane="ok", cause="brownout",
            )
            self._brownout_seen = brownout
        # Per-tenant quota shed (the fleet's noisy-tenant isolation):
        # anomaly_shed_rows_total{tenant=} — one series per tenant
        # that ever shed, so "this tenant's loss" is a number an
        # operator can alert on in isolation.
        for tenant, total in list(
            self.pipeline.stats.shed_rows_tenant.items()
        ):
            delta = total - self._tenant_shed_seen.get(tenant, 0)
            if delta:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_SHED_ROWS, float(delta),
                    lane="ok", cause="tenant-quota", tenant=tenant,
                )
                self._tenant_shed_seen[tenant] = total
                self.flight.record(
                    "shed", lane="ok", tenant=tenant, rows=int(delta),
                )
        if self.ingest_pool is not None:
            self._export_pool_stats()
        self._export_spine_stats()
        self._export_fence_stats()
        self._flight_health_tick()
        self._export_selftrace_stats()
        # Remediation housekeeping on the pump cadence: the recovery
        # deadline and the token-bucket refill must advance even when
        # no report arrives (a wedged harvest must still roll back a
        # mitigation whose deadline passed).
        self.remediation.tick(time.monotonic() if t_now is None else t_now)
        self._export_remediation_stats()
        self.autoscaler.tick(time.monotonic() if t_now is None else t_now)
        self._export_autoscale_stats()
        if self.query_engine is not None and self._query_started:
            self._export_query_stats()
        self._export_provenance_stats()
        if self.repl_primary is not None:
            self._export_replication_stats()
        if self._orders is not None:
            # Guarded: an exception escaping the poll/submit loop (a
            # transport state no one anticipated) backs the pump off
            # and retries instead of killing the daemon loop.
            self._supervisor.run_step("kafka-orders", self._pump_orders)
        # The daemon is a WALL-CLOCK caller: pump(None) would reuse the
        # pipeline's last timebase (the virtual-time contract for
        # harness callers), freezing dt and window rotation for the
        # whole serve-loop lifetime — tumbling windows would never
        # expire, starving the cardinality head AND the history
        # ladder. Resolve the clock here, like the metrics feed always
        # has.
        self.pipeline.pump(time.monotonic() if t_now is None else t_now)
        self.metrics_feed.pump(time.monotonic() if t_now is None else t_now)
        self._supervisor.tick()
        if (
            self.ckpt_path
            and time.monotonic() - self._last_ckpt >= self.ckpt_interval_s
        ):
            # Guarded: a full disk is a degraded snapshot cadence, not
            # a dead detector.
            self._supervisor.run_step("checkpoint", self._checkpoint)

    def _export_keyspace_stats(self) -> None:
        """anomaly_process_rss_bytes (first-class — the soak bench's
        VmRSS read promoted to a scrape) + the anomaly_keyspace_*
        family, delta-based per-tenant counters like the shed exports,
        and one flight-recorder event per ladder EDGE — the evidence
        an operator replays after surviving a cardinality bomb."""
        tz = self.pipeline.tensorizer
        if self.keyspace is not None and self.keyspace.last_rss:
            rss = self.keyspace.last_rss
        else:
            from .keyspace import process_rss_bytes

            rss = process_rss_bytes()
        self.registry.gauge_set(
            tele_metrics.ANOMALY_PROCESS_RSS, float(rss)
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_ROWS, float(tz.live_keys)
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_CAPACITY, float(tz.capacity)
        )
        fill = tz.live_keys / max(tz.capacity, 1)
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_FILL, float(fill)
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_FREE_IDS, float(tz.free_ids)
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_GENERATION,
            float(tz.generation),
        )
        level = self.pipeline.keyspace_level
        self.registry.gauge_set(
            tele_metrics.ANOMALY_KEYSPACE_LEVEL, float(level)
        )
        delta = tz.evicted_total - self._keyspace_evicted_seen
        if delta:
            self.registry.counter_add(
                tele_metrics.ANOMALY_KEYSPACE_EVICTED, float(delta)
            )
            self._keyspace_evicted_seen = tz.evicted_total
        if level != self._keyspace_level_seen:
            # Every ladder edge (both directions) leaves evidence: the
            # eviction sweeps themselves record their own events.
            self.flight.record(
                "keyspace", op="level",
                prev=self._keyspace_level_seen, level=level,
                fill=round(float(fill), 4),
                rss_mb=round(rss / (1024 * 1024), 1),
                rows=tz.live_keys, free_ids=tz.free_ids,
                generation=tz.generation,
            )
            self._keyspace_level_seen = level
        for kind, metric, totals in (
            (
                "throttled", tele_metrics.ANOMALY_KEYSPACE_THROTTLED,
                self.pipeline.stats.newkey_throttled_tenant,
            ),
            (
                "overflow", tele_metrics.ANOMALY_KEYSPACE_OVERFLOW,
                self.pipeline.stats.overflow_keys_tenant,
            ),
        ):
            seen = self._keyspace_tenant_seen[kind]
            for tenant, total in list(totals.items()):
                d = total - seen.get(tenant, 0)
                if d:
                    self.registry.counter_add(
                        metric, float(d), tenant=tenant
                    )
                    seen[tenant] = total
                    self.flight.record(
                        "keyspace", op=kind, tenant=tenant, keys=int(d),
                    )

    def _export_pool_stats(self) -> None:
        """anomaly_ingest_pool_* gauges/counters from the pool's
        counters (delta-based, like the shed/quarantine exports)."""
        st = self.ingest_pool.stats()
        seen = self._pool_seen
        self.registry.gauge_set(
            tele_metrics.ANOMALY_INGEST_POOL_DEPTH, float(st["depth"])
        )
        for key, metric in (
            ("flushes", tele_metrics.ANOMALY_INGEST_POOL_FLUSHES),
            ("flushed_spans", tele_metrics.ANOMALY_INGEST_POOL_SPANS),
            ("coalesced_requests", tele_metrics.ANOMALY_INGEST_POOL_REQUESTS),
        ):
            delta = st[key] - seen[key]
            if delta:
                self.registry.counter_add(metric, float(delta))
                seen[key] = st[key]
        delta = st["frames_corrupt"] - seen["frames_corrupt"]
        if delta:
            self.registry.counter_add(
                tele_metrics.ANOMALY_FRAME_CORRUPT, float(delta),
                hop="ingest",
            )
            seen["frames_corrupt"] = st["frames_corrupt"]
            self.flight.record(
                "quarantine", hop="ingest", frames=int(delta)
            )
        # Windowed utilization: busy-seconds delta over wall delta,
        # normalized by worker count — the "is the pool the
        # bottleneck" gauge.
        now = time.monotonic()
        wall = max(now - seen["wall_t"], 1e-9)
        busy = st["busy_s"] - seen["busy_s"]
        self.registry.gauge_set(
            tele_metrics.ANOMALY_INGEST_POOL_UTILIZATION,
            min(busy / (wall * st["workers"]), 1.0),
        )
        seen["busy_s"] = st["busy_s"]
        seen["wall_t"] = now

    def _export_spine_stats(self) -> None:
        """anomaly_spine_* gauges: is the host→device transfer actually
        hidden behind compute (overlap ratio), at what ring depth."""
        st = self.pipeline.spine_stats()
        if st is None:
            self.registry.gauge_set(
                tele_metrics.ANOMALY_SPINE_RING_DEPTH, 0.0
            )
            return
        self.registry.gauge_set(
            tele_metrics.ANOMALY_SPINE_RING_DEPTH, float(st["ring_depth"])
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_SPINE_PUT_OVERLAP,
            float(st["overlap_ratio"]),
        )
        # Histogram companion on a per-window basis: the lifetime
        # gauge flattens transients; one observation per scrape window
        # lets Prometheus answer "what fraction of windows had the put
        # hidden" as a quantile.
        hits = int(st["overlap_hits"])
        taken = hits + int(st["overlap_misses"])
        seen_hits, seen_taken = self._spine_overlap_seen
        if taken > seen_taken:
            self.registry.histogram_observe(
                tele_metrics.ANOMALY_SPINE_OVERLAP_WINDOW,
                (hits - seen_hits) / (taken - seen_taken),
                self._OVERLAP_BUCKETS,
            )
            self._spine_overlap_seen = (hits, taken)

    # -- sharded fleet ---------------------------------------------------

    def _on_reshard(self, event: dict) -> None:
        """Membership applied a ring change (leave/join): evidence in
        the flight recorder — the postmortem question after any
        reshard is 'who moved, when, at what ring version' — and, in
        adoptive mode, the automatic-adoption trigger: when the leave
        named THIS shard the heir, the victim's keyspace merges from
        the successor mirror with zero operator action. Runs on the
        fleet heartbeat thread, AFTER the membership lock released
        (the two-phase tick contract), so the merge can take the
        dispatch lock without ordering against membership state."""
        self.flight.record(
            "reshard", op=event.get("op"), shard=event.get("shard"),
            ring_version=event.get("ring_version"),
            members=event.get("members"),
            heir=event.get("heir"),
        )
        if (
            event.get("op") == "leave"
            and event.get("heir") == f"shard-{self._fleet_index}"
        ):
            self._adopt_shard(event)
        # Membership moved, so this shard's ring-successor may have
        # too: re-point the mirror (a retargeted standby drops the old
        # peer's arrays and bootstraps from the new primary's
        # SNAPSHOT). After the adoption above — the merge needs the
        # mirror's pre-retarget state.
        self._retarget_adoption_mirror(event.get("members") or [])

    def _refuse_adoption(self, reason: str, victim: str) -> None:
        self._adoptions_refused[reason] = (
            self._adoptions_refused.get(reason, 0) + 1
        )
        self.flight.record(
            "adoption-refused", reason=reason, victim=victim,
        )
        self.flight.dump(
            "adoption-refused", refusal=reason, victim=victim,
        )

    def _adopt_shard(self, event: dict) -> None:
        """Automatic in-daemon frame adoption: merge the dead
        ring-successor's mirrored frame into live state under the
        dispatch lock (the PR 14 operator drill, with the operator
        replaced by the heir computation). Refusals are counted by
        reason and evidence-dumped — an adoption that CANNOT be done
        safely (drifted intern tables, no mirrored state) leaves the
        keyspace orphaned-but-audited, exactly like the manual path."""
        victim = str(event.get("shard"))
        mirror = self._adoption_mirror
        if mirror is None:
            self._refuse_adoption("no_mirror", victim)
            return
        if self.role != ROLE_PRIMARY or self._fence.stale():
            # A fenced/standby heir must not write state it does not
            # own; the keyspace stays with whoever outranked us.
            self._refuse_adoption("role", victim)
            return
        src_arrays, src_meta = mirror.snapshot()
        if not src_arrays:
            self._refuse_adoption("no_state", victim)
            return
        # The victim's keyspace slice under the PRE-leave ring: the
        # post-event members + adopted map minus this very adoption
        # reconstruct it exactly (every member computes the same ring
        # from the same inputs — the zero-coordination property).
        members = [str(m) for m in (event.get("members") or [])]
        pre_adopted = {
            v: h
            for v, h in self.fleet.membership.ring.adopted().items()
            if v != victim
        }
        pre_ring = fleet.HashRing(
            members + [victim], vnodes=self._fleet_vnodes,
            adopted=pre_adopted,
        )
        src_names = [str(s) for s in src_meta.get("service_names") or []]
        owned = {
            svc for svc in src_names
            if pre_ring.owner_of(
                svc, fleet.tenant_of(svc, self._tenant_map)
            ) == victim
        }
        try:
            import jax

            from ..models.detector import DetectorState

            with self.pipeline._dispatch_lock:
                import numpy as np

                dst = {
                    k: np.asarray(v)
                    for k, v in self.detector.state._asdict().items()
                }
                head = dst.get("lat_mean")
                num_rows = int(head.shape[0]) if head is not None else 0
                mask = fleet.service_row_mask(
                    src_names,
                    self.pipeline.tensorizer.service_names,
                    num_rows,
                    owned=owned,
                )
                merged = fleet.merge_shard_arrays(
                    dst, src_arrays, mask,
                    # Keyspace generation drift refuses the merge: a
                    # victim that ran an eviction sweep we never saw
                    # has recycled ids our positional mask would
                    # cross-attribute.
                    dst_generation=self.pipeline.tensorizer.generation,
                    src_generation=int(
                        src_meta.get("generation") or 0
                    ),
                )
                self.detector.state = DetectorState(
                    **{k: jax.device_put(v) for k, v in merged.items()}
                )
        except fleet.ShardMergeError as e:
            self._refuse_adoption("merge", victim)
            logging.getLogger(__name__).error(
                "adoption of %s refused: %s", victim, e
            )
            return
        except Exception:  # noqa: BLE001 — a failed adoption is an
            # audited orphan (like a refused manual merge), never a
            # dead heartbeat thread.
            self._refuse_adoption("error", victim)
            logging.getLogger(__name__).exception(
                "adoption of %s failed", victim
            )
            return
        # The victim's names are already interned (the pre-intern
        # contract the drift check just verified) — but late services
        # the victim interned past our table still need ids for the
        # query plane to answer by name.
        for name in src_names:
            self.pipeline.tensorizer.service_id(name)
        tta = max(time.monotonic() - float(event.get("t") or 0.0), 0.0)
        self._adoptions_total += 1
        self._last_adoption_tta = tta
        self.flight.record(
            "adoption", victim=victim, tta_s=round(tta, 4),
            services=sorted(owned),
            ring_version=event.get("ring_version"),
        )
        self.flight.dump(
            "adoption", victim=victim, tta_s=round(tta, 4),
            services=sorted(owned),
        )

    def _retarget_adoption_mirror(self, members: list) -> None:
        """Keep the standby mirror pointed at this shard's CURRENT
        ring-successor (pure function of the member list — every
        member re-derives the same pairing with no coordination)."""
        if not self._fleet_repl_addrs:
            return
        self_id = f"shard-{self._fleet_index}"
        succ = fleet.ring_successor(
            [str(m) for m in members], self_id
        )
        addr = self._fleet_repl_addrs.get(succ) if succ else None
        if addr == self._adoption_target:
            return
        self._adoption_target = addr
        if addr is None:
            # Alone on the ring (or the successor has no stream):
            # nothing to mirror — stop, keep the object for rejoin.
            if self._adoption_mirror is not None:
                try:
                    self._adoption_mirror.stop()
                except Exception:  # noqa: BLE001 — a half-dead client
                    pass
            return
        if self._adoption_mirror is None:
            self._adoption_mirror = replication.ReplicationStandby(
                addr,
                fence=self._adoption_fence or EpochFence(),
                standby_id=f"{self_id}-adopt",
                silence_reconnect_s=max(
                    self._fleet_heartbeat_s * 2.0, 2.0
                ),
            )
            self._adoption_mirror.start()
        else:
            self._adoption_mirror.retarget(addr)
        self.flight.record(
            "adoption-mirror", successor=succ, target=addr,
        )

    def _fleet_shard_count(self) -> int:
        """The autoscaler's proposal base: live members on the ring
        (single-shard daemons scale from 1)."""
        if self.fleet is None:
            return 1
        return self.fleet.membership.live_count()

    def _restart_fleet(self) -> None:
        if self.fleet is None:
            return
        try:
            self.fleet.stop()
        except Exception:  # noqa: BLE001 — a wedged loop may half-stop
            pass
        self.fleet.start()

    def _fleet_live_shards(self) -> list[str]:
        """The embedded aggregator's membership filter: fan out only
        to shards the heartbeat table believes alive (plus self)."""
        if self.fleet is None:
            return []
        snap = self.fleet.snapshot()
        live = [
            peer for peer, st in snap["peers"].items() if st["alive"]
        ]
        live.append(snap["shard"])
        return live

    def _export_fleet_stats(self) -> None:
        """anomaly_fleet_* gauges/counters from the membership table
        (delta-based counters, the shed/quarantine discipline)."""
        if self.fleet is None:
            return
        snap = self.fleet.snapshot()
        self.registry.gauge_set(
            tele_metrics.ANOMALY_FLEET_SHARDS_LIVE,
            float(snap["shards_live"]),
        )
        # Prometheus gauges are floats: fold the 64-bit digest into 31
        # bits so the exposition round-trips exactly (the comparison
        # across shards only needs equality, not the full digest).
        self.registry.gauge_set(
            tele_metrics.ANOMALY_FLEET_RING_VERSION,
            float(snap["ring_version"] % (1 << 31)),
        )
        self.registry.gauge_set(
            tele_metrics.ANOMALY_FLEET_FROZEN,
            1.0 if snap["frozen"] else 0.0,
        )
        seen = self._fleet_seen
        for key, metric in (
            ("reshards", tele_metrics.ANOMALY_RESHARDS),
            ("refused", tele_metrics.ANOMALY_RESHARDS_REFUSED),
        ):
            value = snap[
                "reshards_total" if key == "reshards"
                else "reshards_refused"
            ]
            delta = value - seen[key]
            if delta > 0:
                self.registry.counter_add(metric, float(delta))
                seen[key] = value
        spans = int(self.pipeline.stats.spans)
        delta = spans - seen["spans"]
        if delta > 0:
            self.registry.counter_add(
                tele_metrics.ANOMALY_FLEET_SHARD_SPANS, float(delta),
                shard=f"shard-{self._fleet_index}",
            )
            seen["spans"] = spans
        # Adoption trail (delta-based like every fleet counter; the
        # refused map is tiny — a handful of reason keys).
        delta = self._adoptions_total - self._adoption_seen["total"]
        if delta > 0:
            self.registry.counter_add(
                tele_metrics.ANOMALY_FLEET_ADOPTIONS, float(delta)
            )
            self._adoption_seen["total"] = self._adoptions_total
        for reason, count in list(self._adoptions_refused.items()):
            key = f"refused_{reason}"
            d = count - self._adoption_seen.get(key, 0)
            if d > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_FLEET_ADOPTIONS_REFUSED,
                    float(d), reason=reason,
                )
                self._adoption_seen[key] = count
        if self._last_adoption_tta is not None:
            self.registry.gauge_set(
                tele_metrics.ANOMALY_FLEET_ADOPTION_TTA,
                float(self._last_adoption_tta),
            )

    def _export_autoscale_stats(self) -> None:
        """anomaly_autoscale_* from the controller's counters (delta-
        based) + the live score/target gauges."""
        st = self.autoscaler.stats()
        seen = self._autoscale_seen
        for action in ("split", "join"):
            key = f"proposals_{action}"
            delta = st[key] - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_AUTOSCALE_PROPOSALS,
                    float(delta), action=action,
                )
                seen[key] = st[key]
        for reason in (
            "disabled", "role", "fenced", "bounds", "budget", "apply",
        ):
            key = f"refused_{reason}"
            delta = st[key] - seen.get(key, 0)
            if delta > 0:
                self.registry.counter_add(
                    tele_metrics.ANOMALY_AUTOSCALE_REFUSED,
                    float(delta), reason=reason,
                )
                seen[key] = st[key]
        self.registry.gauge_set(
            tele_metrics.ANOMALY_AUTOSCALE_SCORE, float(st["score"])
        )
        if st["target_shards"] is not None:
            self.registry.gauge_set(
                tele_metrics.ANOMALY_AUTOSCALE_TARGET,
                float(st["target_shards"]),
            )

    def _autoscale_observe(self, t_now: float) -> None:
        """One saturation window for the autoscaler (1 s cadence, on
        the primary step): watermark / shed / brownout / saturation
        signals, each normalized to [0, 1]."""
        pending = float(self.pipeline.pending_rows())
        high = float(getattr(self.pipeline, "_high_rows", 0) or 0)
        shed = self.pipeline.stats.shed_rows
        shed_total = (
            int(shed.get("ok", 0)) + int(shed.get("error", 0))
            + int(self.pipeline.stats.brownout_rows)
        )
        shed_active = shed_total > self._autoscale_shed_seen
        self._autoscale_shed_seen = shed_total
        max_level = max(
            float(getattr(self.pipeline, "brownout_max_level", 0) or 0),
            1.0,
        )
        signals = {
            "watermark": min(pending / high, 1.0) if high > 0 else 0.0,
            "shed": 1.0 if shed_active else 0.0,
            "brownout": min(
                float(self.pipeline.brownout_level) / max_level, 1.0
            ),
            "saturated": 1.0 if self.pipeline.saturated else 0.0,
        }
        self.autoscaler.observe(t_now, signals)

    # -- replication: standby step / promotion / fencing ----------------

    def _repl_counters(self) -> dict:
        if not hasattr(self, "_repl_seen"):
            self._repl_seen = {}
        return self._repl_seen

    def _export_counter_delta(self, metric: str, key: str, value: int, **labels):
        seen = self._repl_counters()
        delta = value - seen.get(key, 0)
        # delta > 0 only: a supervised replication restart swaps in a
        # fresh stats object (counts restart at 0), and a negative add
        # would make the Prometheus counter decrease — rate() would
        # read it as a bogus reset spike. _restart_replication also
        # clears the seen map so post-restart counts aren't swallowed.
        if delta > 0:
            self.registry.counter_add(metric, float(delta), **labels)
        seen[key] = value

    def _export_fence_stats(self) -> None:
        """Fence-rejected writes by path — the split-brain audit trail
        (anomaly_replication_fenced_total{path=checkpoint|offsets|…});
        frame-path rejections are exported from the replication stats,
        these are the checkpoint/commit halves."""
        for path, count in list(self._fence.fenced_by_path.items()):
            label = "offsets" if "offset" in path else path
            self._export_counter_delta(
                tele_metrics.ANOMALY_REPLICATION_FENCED,
                f"fence_{path}", count, path=label,
            )

    def _export_replication_stats(self) -> None:
        p = self.repl_primary
        self.registry.gauge_set(
            tele_metrics.ANOMALY_REPLICATION_LAG, p.lag_seconds()
        )
        self._export_counter_delta(
            tele_metrics.ANOMALY_REPLICATION_DELTAS, "shipped",
            p.deltas_shipped, direction="shipped",
        )
        self._export_counter_delta(
            tele_metrics.ANOMALY_REPLICATION_SNAPSHOTS, "snap_shipped",
            p.snapshots_shipped, direction="shipped",
        )
        self._export_counter_delta(
            tele_metrics.ANOMALY_REPLICATION_FENCED, "frame_fenced",
            p.fenced_events, path="frame",
        )
        self._export_counter_delta(
            tele_metrics.ANOMALY_FRAME_CORRUPT, "frames_corrupt_primary",
            p.frames_corrupt, hop="replication",
        )

    def _standby_step(self) -> None:
        """One standby housekeeping tick: watchdog + metrics (and, in
        read-replica mode, the query snapshot cache). No ingest, no
        Kafka, no checkpoints — beyond serving reads, the standby's
        job is staying current and noticing the primary die."""
        self._export_fence_stats()
        self._export_remediation_stats()
        if self.query_engine is not None and self._query_started:
            self._export_query_stats()
        st = self.repl_standby
        if st is not None:
            quiet_s = st.seconds_since_frame()
            self.registry.gauge_set(
                tele_metrics.ANOMALY_REPLICATION_LAG, quiet_s
            )
            self._export_counter_delta(
                tele_metrics.ANOMALY_REPLICATION_DELTAS, "applied",
                st.deltas_applied, direction="applied",
            )
            self._export_counter_delta(
                tele_metrics.ANOMALY_REPLICATION_SNAPSHOTS, "snap_applied",
                st.snapshots_applied, direction="applied",
            )
            self._export_counter_delta(
                tele_metrics.ANOMALY_REPLICATION_FENCED, "fenced_sent",
                st.fenced_sent, path="frame",
            )
            corrupt_prev = self._repl_counters().get("frames_corrupt", 0)
            self._export_counter_delta(
                tele_metrics.ANOMALY_FRAME_CORRUPT, "frames_corrupt",
                st.frames_corrupt, hop="replication",
            )
            if st.frames_corrupt > corrupt_prev:
                self.flight.record(
                    "quarantine", hop="replication",
                    frames=int(st.frames_corrupt - corrupt_prev),
                )
            if (
                self.role == ROLE_STANDBY
                and quiet_s > self._failover_timeout_s
                and st.applied_seq >= 0  # never promote off nothing
            ):
                if self._primary_confirmed_alive():
                    # Link fault, not primary death: promoting now would
                    # split-brain against a serving primary. Reset the
                    # watchdog and keep reconnecting.
                    st.last_frame_t = time.monotonic()
                else:
                    self.promote()
        self._supervisor.tick()

    def _primary_confirmed_alive(self) -> bool:
        """grpc.health double-check before promotion (only when
        ANOMALY_PRIMARY_HEALTH_ADDR is configured): True means the
        primary still answers SERVING and the silence is the LINK's
        fault."""
        if not self._primary_health_addr:
            return False
        try:
            from .health_probe import probe

            return probe(self._primary_health_addr, timeout_s=2.0)
        except Exception:  # noqa: BLE001 — no grpcio / unreachable:
            return False  # treat as dead, promotion proceeds

    def promote(self) -> None:
        """STANDBY → PROMOTING → PRIMARY: the failover path.

        Order matters: the epoch bump comes FIRST (every later write is
        stamped with it), then state hydration from the replicated
        mirror, then the Kafka seek to the replicated offset map
        (at-least-once: offsets only ever replicated after flush
        confirmation), then ingest comes up, then an immediate
        epoch-stamped checkpoint makes the promotion durable — a
        promoted standby that crashes and restarts keeps outranking
        the old primary."""
        self.role = ROLE_PROMOTING
        epoch = self._fence.bump()
        # Promotion steps land in the flight recorder AND dump an
        # evidence file: a failover is exactly the moment an operator
        # later asks "what did the daemon see".
        self.flight.record("role", state=ROLE_PROMOTING, epoch=epoch)
        self.flight.dump("promoting")
        try:
            # Everything fallible happens BEFORE the standby client is
            # stopped: if any step raises (wrong-shaped replicated
            # arrays, a broker fault in seek, a receiver bind failure),
            # we return to STANDBY with the mirror intact and the
            # watchdog re-fires after another failover timeout — a
            # failed promotion must be a retry, never a process parked
            # in PROMOTING with no ingest and no way forward.
            arrays, meta = {}, {}
            if self.repl_standby is not None:
                arrays, meta = self.repl_standby.snapshot()
            if arrays:
                import jax

                from ..models.detector import DetectorState

                # Hydration swaps the live state object: under the
                # dispatch lock, because the width-ladder warmup thread
                # (spawned in __init__ for every role) snapshots state
                # around its own dispatches — an unlocked swap here can
                # be clobbered by a warmup copy-back mid-promotion.
                with self.pipeline._dispatch_lock:
                    self.detector.state = DetectorState(
                        **{
                            k: jax.device_put(v)
                            for k, v in arrays.items()
                        }
                    )
                    self.detector.clock._t_prev = meta.get("clock_t_prev")
                # Positional adoption (the checkpoint-restore rule):
                # the mirrored table may carry EVICTED_SLOT tombstones
                # from the old primary's sweeps, and name-by-name
                # interning would compact past them, shifting ids off
                # the rows we just hydrated. The generation rides
                # along so this promoted primary refuses pre-sweep
                # frames exactly like the one it replaced.
                self.pipeline.tensorizer.adopt_names(
                    list(meta.get("service_names", []))
                )
                self.pipeline.tensorizer.generation = int(
                    meta.get("generation") or 0
                )
                self._offsets = {
                    int(p): int(o)
                    for p, o in (meta.get("offsets") or {}).items()
                }
                # Query-plane continuity: once role==PRIMARY the
                # engine reads the LIVE pipeline, whose exemplar/
                # anomaly/candidate rings are empty on a fresh
                # standby — refill them from the mirror or the
                # replicated history vanishes as soon as the snapshot
                # cache expires.
                self.pipeline.restore_query_meta(
                    meta.get("query") or {}
                )
            if self._orders is not None and self._offsets:
                # Replicated offsets win over broker-committed ones for
                # the same reason checkpoint offsets do: the sketch
                # state we just hydrated corresponds to THEM.
                self._orders.seek(self._offsets)
            # Ingest up: construct + start the receivers the standby
            # never built, join them to the supervision tree.
            if self.receiver is None:
                self.receiver = self._make_http_receiver(self.otlp_port)
                self.receiver.start()
            if self.grpc_receiver is None and self._grpc_port_req >= 0:
                try:
                    self.grpc_receiver = self._make_grpc_receiver(
                        self._grpc_port_req
                    )
                    self.grpc_receiver.start()
                except ImportError:
                    self.grpc_receiver = None
            self._start_frontdoor()
            self._register_serving_components()
        except Exception:  # noqa: BLE001 — promotion retries, never parks
            logging.getLogger(__name__).exception(
                "promotion failed; returning to standby for retry"
            )
            self.role = ROLE_STANDBY
            return
        if self.repl_standby is not None:
            try:
                self.repl_standby.stop()
            except Exception:  # noqa: BLE001 — a half-dead client must
                pass  # not block the failover
        self.role = ROLE_PRIMARY
        self.registry.counter_add(tele_metrics.ANOMALY_FAILOVERS, 1.0)
        self.flight.record(
            "role", state=ROLE_PRIMARY, epoch=epoch,
            offsets={str(k): v for k, v in self._offsets.items()},
        )
        # Queries fail over WITH the role: the engine's role-dispatched
        # snapshot now reads live state (an already-serving read
        # replica needs no rewiring); a standby that booted with
        # read-replica off starts its listeners here. A bind failure
        # (port clash on a shared host) must not kill a daemon that
        # just took over ingest — promote without the read path.
        try:
            self._start_query_plane()
        except Exception:  # noqa: BLE001 — read path is optional after promotion; ingest must live
            logging.getLogger(__name__).exception(
                "promoted, but the query listener failed to start — "
                "serving ingest without the read path"
            )
        # The promoted daemon owns the compaction duty now (its
        # appends stamp the bumped epoch — the old primary's are
        # refused). Optional like the read path: ingest must live.
        try:
            self._start_history_writer()
        except Exception:  # noqa: BLE001 — history is an optional tier; ingest must live
            logging.getLogger(__name__).exception(
                "promoted, but the history writer failed to start"
            )
        try:
            # The promoted daemon owns eviction duty now, same as
            # compaction: a standby never ran local sweeps.
            self._start_keyspace()
        except Exception:  # noqa: BLE001 — the keyspace plane is optional; ingest must live
            logging.getLogger(__name__).exception(
                "promoted, but the keyspace watchdog failed to start"
            )
        if self.ckpt_path:
            # Durable promotion (and the first fencing artifact the old
            # primary can trip over on a shared volume).
            self._supervisor.run_step("checkpoint", self._checkpoint)
        if self._repl_port >= 0:
            # Serve the NEXT standby (failure here is the supervised
            # replication component's to retry, not the promotion's).
            try:
                self._start_replication_primary()
            except Exception:  # noqa: BLE001 — the supervised replication component retries the listener
                logging.getLogger(__name__).exception(
                    "promoted, but the replication listener failed to "
                    "start — running unreplicated until it recovers"
                )
        logging.getLogger(__name__).warning(
            "promoted to primary at epoch %d (offsets %s)",
            epoch, dict(self._offsets),
        )

    def _become_fenced(self, at_boot: bool = False) -> None:
        self.role = ROLE_FENCED
        self.registry.counter_add(
            tele_metrics.ANOMALY_REPLICATION_FENCED, 1.0, path="role",
        )
        self.flight.record(
            "role", state=ROLE_FENCED, at_boot=at_boot,
            epoch=self._fence.epoch, observed=self._fence.observed,
        )
        self.flight.dump("fenced")
        if self.repl_primary is not None:
            try:
                self.repl_primary.stop()
            except Exception:  # noqa: BLE001 — fenced teardown is best-effort; the daemon is exiting serving anyway
                pass
        if self.history_writer is not None:
            # Deliberate stop (the supervised probe is role-gated):
            # every further append would be fence-refused anyway, and
            # sealing now keeps the log's tail durable for whoever owns
            # the volume next.
            try:
                self.history_writer.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        if self.keyspace is not None:
            # A fenced process must not keep mutating its state tree
            # (evictions bump the generation — noise for forensics).
            try:
                self.keyspace.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        # Stop SERVING too: a fenced replica that kept answering OTLP
        # would hold the orchestrator's readiness probes (the k8s
        # bundle probes grpc.health on :4317) and the collector's
        # traffic on a process whose durable writes are all rejected —
        # the failover would never actually move ingest. The supervised
        # receiver components are role-gated (below), so this is a
        # deliberate stop, not a crash they would undo.
        for recv in (self.receiver, self.grpc_receiver):
            if recv is None:
                continue
            try:
                recv.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self.receiver = None
        self.grpc_receiver = None
        logging.getLogger(__name__).error(
            "fenced%s: epoch %d superseded by %d — durable writes "
            "stopped (checkpoint/offset-commit/replication); redeploy "
            "this process as a standby or retire it",
            " at boot" if at_boot else "",
            self._fence.epoch, self._fence.observed,
        )

    def _pump_orders(self) -> None:
        # Saturation pause: Kafka is the one ingest leg with a durable
        # upstream buffer, so backpressure here is simply NOT polling —
        # offsets hold, the broker keeps the log, nothing is shed, and
        # the consumer resumes exactly where it paused once the queue
        # drains below the low watermark (at-least-once preserved).
        paused = self.pipeline.saturated
        if paused != self._kafka_paused:
            self._kafka_paused = paused
            self.registry.gauge_set(
                tele_metrics.ANOMALY_KAFKA_PAUSED, 1.0 if paused else 0.0
            )
        if paused:
            return
        # Deferred flush confirmations first: merge the offsets of any
        # earlier pool flush that has since resolved CLEANLY (a failed
        # flush keeps its offsets out of the checkpoint, so a restart
        # replays those records — at-least-once, never silent loss).
        # The list is BOUNDED: sheds are counted and force a checkpoint
        # barrier (persist what IS confirmed, bound the replay window).
        if len(self._deferred_offsets):
            with self._offsets_lock:
                self._offsets.update(self._deferred_offsets.resolve())
        dropped = self._deferred_offsets.dropped_total
        if dropped != self._defer_dropped_seen:
            self.registry.counter_add(
                tele_metrics.ANOMALY_OFFSET_DEFER_DROPPED,
                float(dropped - self._defer_dropped_seen),
            )
            self._defer_dropped_seen = dropped
        if self._deferred_offsets.take_barrier() and self.ckpt_path:
            self._supervisor.run_step("checkpoint", self._checkpoint)
        # One poll = one batch: records coalesce into a single
        # tensorize pass (through the ingest pool when enabled, so the
        # Kafka leg shares the pool's flush amortization) instead of a
        # per-record submit that took the pipeline lock per message.
        # Offsets merge into the checkpointable map only AFTER the
        # records reach the pipeline — "checkpoint offsets correspond
        # to submitted sketch rows" is the resume invariant.
        offsets, batch = self._orders.poll_batch(0.0)
        if not batch:
            # Tombstones / quarantined poison pills: their offsets
            # still advance, or a pill at the partition tail replays
            # (and re-logs) on every restart.
            with self._offsets_lock:
                    self._offsets.update(offsets)
            return
        if self.ingest_pool is not None:
            from .ingest_pool import IngestPoolSaturated

            try:
                ticket = self.ingest_pool.submit_records(batch)
                # Wait for the flush (one coalesced flush, not a round
                # trip per record); on timeout the confirmation — and
                # the offset merge — is deferred to a later pump.
                ticket.result(timeout=10.0)
                with self._offsets_lock:
                    self._offsets.update(offsets)
            except IngestPoolSaturated:
                # The pool queue is full: fall back to the direct path
                # rather than dropping.
                self.pipeline.submit(batch)
                with self._offsets_lock:
                    self._offsets.update(offsets)
            except TimeoutError:
                # Flush still pending (wedged worker — the
                # supervisor's probe/restart handles it); records sit
                # in the pool queue, offsets withheld until confirmed.
                self._deferred_offsets.add(ticket, offsets)
            # An IngestWorkerError resolution means the flush died
            # server-side: offsets are NOT merged (the records never
            # reached the pipeline), so a restart replays them.
        else:
            self.pipeline.submit(batch)
            with self._offsets_lock:
                    self._offsets.update(offsets)
        quarantined = self._orders.decode_failures
        if quarantined != self._quarantine_seen:
            self.registry.counter_add(
                tele_metrics.ANOMALY_QUARANTINE_TOTAL,
                float(quarantined - self._quarantine_seen),
                source="orders",
            )
            self.registry.gauge_set(
                tele_metrics.ANOMALY_QUARANTINE_LAST_ERROR_TS,
                self._orders.last_error_ts,
                source="orders",
            )
            self._quarantine_seen = quarantined

    def _checkpoint(self) -> None:
        # Fence first (a process that has OBSERVED a newer epoch must
        # not write even to an empty path), then the epoch-stamped save
        # (which additionally refuses to replace a newer-epoch snapshot
        # on a shared volume — checkpoint.StaleEpochError either way).
        self._fence.check(path="checkpoint")
        checkpoint.save(
            self.ckpt_path,
            self.detector,
            offsets=dict(self._offsets),
            service_names=self.pipeline.tensorizer.service_names,
            metrics_feed=self.metrics_feed,
            epoch=self._fence.epoch,
            # The keyspace generation restores WITH the name table:
            # a restored primary keeps refusing pre-sweep frames.
            generation=self.pipeline.tensorizer.generation,
            # The copy-out snapshots under the pipeline's dispatch
            # lock: the width-ladder warmup (and any future background
            # dispatcher) must never donate state mid-read.
            dispatch_lock=self.pipeline._dispatch_lock,
        )
        self._last_ckpt = time.monotonic()
        if self._orders is not None and self._offsets:
            # Epoch-tagged broker commit beside the snapshot: the
            # broker becomes a fencing witness any resurrected writer
            # consults at boot. Broker-down is NOT a checkpoint failure
            # (the snapshot, the real durability, already landed) — but
            # a StaleEpochError propagates: it means fence state
            # changed mid-step and the caller must see it.
            try:
                self._orders.commit(self._offsets, epoch=self._fence.epoch)
            except checkpoint.StaleEpochError:
                raise
            except Exception:  # noqa: BLE001 — transport-only failure
                pass

    def run(self, on_ready=None) -> None:
        """Blocking serve loop; returns after :meth:`stop`.

        ``on_ready(daemon)`` fires once after the listeners start —
        the hook for announcing resolved ports."""
        self.start()
        if on_ready is not None:
            on_ready(self)
        try:
            while not self._stop.wait(self.pump_interval_s):
                # Guarded: one bad step (a transient JAX/transport/
                # filesystem fault) backs off and retries — the serve
                # loop of an always-on sidecar must not be one
                # exception away from exit. A genuine crash loop
                # surfaces as anomaly_degraded + component "pump".
                self._supervisor.run_step("pump", self.step)
                # Tick again OUTSIDE the guarded step: step() ticks on
                # the happy path, but a persistently-failing pump must
                # not also starve every other component of its probes
                # and restarts — multi-fault incidents are exactly when
                # the supervision tree earns its keep.
                self._supervisor.tick()
        finally:
            self.shutdown()

    def stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        if self.fleet is not None:
            self.fleet.stop()
        if self._adoption_mirror is not None:
            self._adoption_mirror.stop()
        if self.aggregator_service is not None:
            self.aggregator_service.stop()
        if self.repl_standby is not None:
            self.repl_standby.stop()
        if self.repl_primary is not None:
            self.repl_primary.stop()
        if self.query_service is not None:
            self.query_service.stop()
        if self.query_grpc is not None:
            self.query_grpc.stop()
        if self.receiver is not None:
            self.receiver.stop()
        if self.grpc_receiver is not None:
            self.grpc_receiver.stop()
        if self.frontdoor is not None:
            # Quiesce + drain in-flight verdicts BEFORE the decode
            # pool closes: a ticket the pool will never resolve must
            # get its 503, not a hung socket.
            self.frontdoor.stop()
        if self._orders is not None:
            self._orders.close()
        if self.keyspace is not None:
            # Before the pipeline drains: a sweep mid-drain would race
            # the final flushes for the dispatch lock for no benefit.
            self.keyspace.close()
        # Stop the remediation worker before the pipeline drains: no
        # new reports can arrive, and a queued actuation against a dead
        # flagd must not pin shutdown past its bounded retries.
        self.remediation.close()
        self.autoscaler.close()
        if self.ingest_pool is not None:
            # Receivers are stopped, so no new jobs: flush the decode
            # queue into the pipeline, then stop the workers — BEFORE
            # the pipeline drains, so nothing in flight is lost.
            self.ingest_pool.close()
        self.pipeline.close()  # drain + stop the harvester thread if any
        # Final provenance drain AFTER the pipeline drain (bundles the
        # last batches flagged) and BEFORE the writer closes, so they
        # make the sealed log.
        try:
            self._export_provenance_stats()
        except Exception:  # noqa: BLE001 — shutdown must not hang on it
            pass
        if self.history_writer is not None:
            # After the pipeline drain (the last captured batches are
            # in the queue) and before the final checkpoint: one last
            # tick + seal so the log ends durable.
            self.history_writer.close()
        if self.ckpt_path and self.role == ROLE_PRIMARY:
            # A standby's state is the primary's to persist; a fenced
            # ex-primary's save would (correctly) raise — neither
            # writes a shutdown snapshot.
            self._checkpoint()
        if self._selftrace_poster is not None:
            # Ship whatever traces the drain produced, then stop the
            # sender — bounded: shutdown never hangs on a dead sink.
            self._selftrace_poster.flush(timeout_s=1.0)
            self._selftrace_poster.close()
        if self._explain_poster is not None:
            self._explain_poster.flush(timeout_s=1.0)
            self._explain_poster.close()
        self.exporter.stop()


def main() -> None:
    import faulthandler
    import signal

    # SIGUSR1 dumps all stacks — the debugging handle for a wedged
    # daemon (kill -USR1 <pid>), matching Go services' SIGQUIT habit.
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    daemon = DetectorDaemon()
    signal.signal(signal.SIGTERM, lambda *_: daemon.stop())
    signal.signal(signal.SIGINT, lambda *_: daemon.stop())

    def announce(d: DetectorDaemon) -> None:
        # Announce resolved ports (env may request ephemeral :0) so
        # operators and cross-process harnesses can discover them.
        grpc_port = d.grpc_receiver.port if d.grpc_receiver else -1
        http_port = d.receiver.port if d.receiver else -1
        repl_port = d.repl_primary.port if d.repl_primary else -1
        # A constructed-but-unstarted QueryService (standby with
        # read-replica off) would report its *requested* port; gate on
        # _query_started so -1 means "nothing listening", like repl.
        query_port = (
            d.query_service.port
            if d.query_service is not None and d._query_started
            else -1
        )
        print(
            f"anomaly-detector: otlp-http :{http_port} "
            f"otlp-grpc :{grpc_port} metrics :{d.exporter.port} "
            f"repl :{repl_port} query :{query_port} role {d.role}",
            flush=True,
        )

    daemon.run(on_ready=announce)


if __name__ == "__main__":
    main()
