"""Flight recorder: a bounded ring of structured runtime events.

The detector's state machine moves (role flips, brownout ladder steps,
fence hits, shed bursts, frame quarantines) used to leave only counter
bumps behind — when the daemon reached DEGRADED/SATURATED/FENCED, the
sequence of events that got it there was already gone. This module is
the black box: a fixed-size ring of structured events that costs one
locked append per event (events are transitions and 1 Hz snapshots,
never per-span work), queryable live via ``/query/flight`` on the
query plane, and **dumped as a quarantine-style evidence file on every
health/role transition** (the frame module's forensics discipline,
applied to behaviour instead of bytes).

What lands in the ring (the daemon's wiring; kinds are free-form
strings, the ring is schema-light on purpose):

- role/epoch changes (boot, promote begin/hydrated/done, fenced)
- shed/brownout ladder moves and saturation edges
- fence hits and frame quarantines (per hop)
- supervised-component crash-loop (DEGRADED) edges
- mitigation-loop moves (``mitigation`` acts/verifies/rollbacks) and
  counterfactual pre-flight verdicts (``preflight`` runs,
  ``preflight_refused`` evidence — each refusal also dumps
  ``flight-preflight-refused-*.json``, the proof an act did NOT fire)
- 1 Hz phase-timing snapshots (pool phase shares, spine overlap,
  lag p99) — the trend context around any transition

Dump policy: ``dump(reason)`` writes ``flight-<reason>-<ms>.json``
into the configured directory (``ANOMALY_SELFTRACE_FLIGHT_DIR``; empty
= ring-only, nothing written) with a per-reason cooldown so a flapping
transition cannot storm the disk. Files are self-contained JSON — the
postmortem artifact an operator attaches to an incident.

Knob registry: ``utils.config.SELFTRACE_KNOBS``
(``ANOMALY_SELFTRACE_FLIGHT_RING`` / ``ANOMALY_SELFTRACE_FLIGHT_DIR``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable


class FlightRecorder:
    """Fixed-size event ring + transition-evidence dumps (module doc).

    One lock guards the ring and the counters: every operation under
    it is a bounded append or a copy, never I/O — ``dump`` snapshots
    under the lock and writes the file outside it, so a slow disk
    can't stall the pump thread behind a recording.
    """

    def __init__(
        self,
        size: int = 512,
        dump_dir: str = "",
        clock: Callable[[], float] = time.time,
        dump_cooldown_s: float = 2.0,
    ):
        self._ring: deque = deque(maxlen=max(int(size), 1))
        self.dump_dir = dump_dir or ""
        self._clock = clock
        self._cooldown = float(dump_cooldown_s)
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}
        self._seq = 0
        self.events_total: dict[str, int] = {}
        self.dumps_total: dict[str, int] = {}
        self.dump_errors = 0

    @property
    def size(self) -> int:
        return self._ring.maxlen or 0

    def record(self, kind: str, **detail) -> None:
        """Append one event (any thread). ``detail`` must be
        JSON-able — it rides the evidence files and /query/flight."""
        with self._lock:
            self._seq += 1
            self._ring.append({
                "seq": self._seq,
                "t": self._clock(),
                "kind": kind,
                **detail,
            })
            self.events_total[kind] = self.events_total.get(kind, 0) + 1

    def snapshot(self) -> list[dict]:
        """Copy of the ring, oldest first (the /query/flight body)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def counts(self) -> tuple[dict[str, int], dict[str, int]]:
        """(events_total, dumps_total) copies for the metrics export."""
        with self._lock:
            return dict(self.events_total), dict(self.dumps_total)

    def dump(self, reason: str, force: bool = False, **context) -> str | None:
        """Write the ring as a postmortem evidence file; returns the
        path, or None (no directory configured / inside the per-reason
        cooldown / write failed — recording evidence must never
        compound the fault it records, the quarantine() rule)."""
        if not self.dump_dir:
            return None
        now = time.monotonic()
        with self._lock:
            if (
                not force
                and now - self._last_dump.get(reason, -self._cooldown)
                < self._cooldown
            ):
                return None
            self._last_dump[reason] = now
            events = [dict(ev) for ev in self._ring]
            self.dumps_total[reason] = self.dumps_total.get(reason, 0) + 1
        doc = {
            "reason": reason,
            "t": self._clock(),
            "events": events,
            **context,
        }
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f"flight-{reason}-{int(self._clock() * 1000)}.json",
            )
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, default=str)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except OSError:
            with self._lock:
                self.dump_errors += 1
            return None
