"""Minimal protobuf wire-format reader (stdlib-only, schema-agnostic).

The two ingest seams speak protobuf: the Kafka ``orders`` topic carries
``oteldemo.OrderResult`` (the reference serialises it in
/root/reference/src/checkout/main.go:550-559 and consumers ParseFrom it,
/root/reference/src/accounting/Consumer.cs:59-70) and OTLP/HTTP carries
``ExportTraceServiceRequest``. This environment has no generated stubs
and no grpcio, so ingestion uses this small wire scanner: it decodes the
universal wire format (varint / fixed32 / fixed64 / length-delimited)
into ``{field_number: [raw values]}`` and lets schema-aware projections
(``kafka_orders``, ``otlp``) pick out the handful of fields the detector
needs by field number. Unknown fields are skipped for free — the same
forward-compatibility contract protobuf itself guarantees.
"""

from __future__ import annotations

_WT_VARINT = 0
_WT_FIXED64 = 1
_WT_LEN = 2
_WT_FIXED32 = 5


class WireError(ValueError):
    """Malformed protobuf wire data."""


def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode one base-128 varint at ``pos``; returns (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise WireError("varint too long")


def scan_fields(buf: bytes) -> dict[int, list]:
    """One-level scan: field number → list of raw values.

    varint fields decode to int; fixed32/fixed64 to little-endian int;
    length-delimited to ``bytes`` (submessages are re-scanned by the
    caller that knows the schema).
    """
    fields: dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = read_varint(buf, pos)
        field_no, wire_type = tag >> 3, tag & 0x7
        if field_no == 0:
            raise WireError("field number 0")
        if wire_type == _WT_VARINT:
            val, pos = read_varint(buf, pos)
        elif wire_type == _WT_FIXED64:
            if pos + 8 > n:
                raise WireError("truncated fixed64")
            val = int.from_bytes(buf[pos : pos + 8], "little")
            pos += 8
        elif wire_type == _WT_FIXED32:
            if pos + 4 > n:
                raise WireError("truncated fixed32")
            val = int.from_bytes(buf[pos : pos + 4], "little")
            pos += 4
        elif wire_type == _WT_LEN:
            ln, pos = read_varint(buf, pos)
            if pos + ln > n:
                raise WireError("truncated bytes field")
            val = buf[pos : pos + ln]
            pos += ln
        else:
            raise WireError(f"unsupported wire type {wire_type}")
        fields.setdefault(field_no, []).append(val)
    return fields


def first(fields: dict[int, list], field_no: int, default=None):
    vals = fields.get(field_no)
    return vals[0] if vals else default


def to_int64(value: int) -> int:
    """Sign-extend a decoded varint: proto3 int32/int64 encode negatives
    as 64-bit two's complement, which :func:`read_varint` returns as the
    raw unsigned value. The decode-side counterpart of
    :func:`encode_varint`'s negative handling."""
    return value - (1 << 64) if value >= (1 << 63) else value


# --- encoding helpers (tests + loopback fixtures) ---------------------


def encode_varint(value: int) -> bytes:
    if value < 0:
        # Protobuf encodes negative int32/int64 as the 64-bit two's
        # complement (always 10 bytes on the wire).
        value &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_tag(field_no: int, wire_type: int) -> bytes:
    return encode_varint((field_no << 3) | wire_type)


def encode_len(field_no: int, payload: bytes) -> bytes:
    return encode_tag(field_no, _WT_LEN) + encode_varint(len(payload)) + payload


def encode_int(field_no: int, value: int) -> bytes:
    return encode_tag(field_no, _WT_VARINT) + encode_varint(value)


def encode_fixed64(field_no: int, value: int) -> bytes:
    return encode_tag(field_no, _WT_FIXED64) + value.to_bytes(8, "little")


def encode_double(field_no: int, value: float) -> bytes:
    import struct

    return encode_tag(field_no, _WT_FIXED64) + struct.pack("<d", value)
