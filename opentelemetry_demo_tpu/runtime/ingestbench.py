"""Host-ingest throughput measurement: OTLP bytes → pipeline columns.

The device side does tens of millions of spans/sec (bench.py); this
measures the other half of the ≥200k spans/sec budget (SURVEY.md §7
hard part (a)) — wire decode + attribute hashing + interning — so the
artifact can show the host keeps the chip fed. One methodology, two
callers: ``scripts/bench_ingest.py`` (the standalone CLI, all decode
paths + the worker-count sweep) and ``bench.py`` (the driver artifact).

Three engines, same bytes → ``SpanColumns`` work:

- ``measure_python``  — pure-Python record path (no compiler needed).
- ``measure_native``  — the r5 serial path: one ctypes decode + one
  tensorize per request, on one thread. Kept as the BEFORE number.
- ``measure_pooled``  — the parallel ingest engine
  (``runtime.ingest_pool``): batched ``decode_many``, pooled scratch
  buffers, coalesced tensorize, N workers. ``measure_scaling`` sweeps
  the worker count into the ``host_ingest_scaling`` curve bench.py
  prints.
"""

from __future__ import annotations

import time

import numpy as np

from . import native, wire
from .otlp import MONITORED_ATTR_KEYS, decode_export_request
from .tensorize import SpanTensorizer


def make_payloads(n_requests: int = 64, spans_per_request: int = 128,
                  seed: int = 0) -> list[bytes]:
    """Realistic OTLP ExportTraceServiceRequest payloads (shop-shaped
    service names, product-id attrs, ~2% error spans)."""
    rng = np.random.default_rng(seed)
    services = [
        "frontend", "checkout", "cart", "payment", "currency",
        "product-catalog", "shipping", "ad", "recommendation", "quote",
    ]

    def anyval(s):
        return wire.encode_len(1, s.encode())

    def kv(k, v):
        return wire.encode_len(1, k.encode()) + wire.encode_len(2, anyval(v))

    payloads = []
    for _ in range(n_requests):
        svc = services[int(rng.integers(0, len(services)))]
        # Joined once per request — += over a growing bytes would make
        # big-request generation quadratic (60k spans took minutes).
        span_bufs = []
        for _ in range(spans_per_request):
            start = int(rng.integers(10**18, 2 * 10**18))
            span = (
                wire.encode_len(1, bytes(rng.integers(0, 256, 16, dtype=np.uint8)))
                + wire.encode_len(5, b"oteldemo.rpc/Call")
                + wire.encode_fixed64(7, start)
                + wire.encode_fixed64(8, start + int(rng.integers(10**5, 10**9)))
                + wire.encode_len(9, kv("app.product.id", f"P-{int(rng.integers(0, 100))}"))
                + wire.encode_len(9, kv("rpc.system", "grpc"))
            )
            if rng.random() < 0.02:
                span += wire.encode_len(15, wire.encode_int(3, 2))
            span_bufs.append(wire.encode_len(2, span))
        resource = wire.encode_len(1, kv("service.name", svc))
        rs = wire.encode_len(1, resource) + wire.encode_len(2, b"".join(span_bufs))
        payloads.append(wire.encode_len(1, rs))
    return payloads


def measure(fn, payloads: list[bytes], n_spans: int, repeat: int = 5) -> float:
    """Best-of-``repeat`` spans/sec of ``fn`` over all payloads."""
    fn(payloads[0])  # warmup
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        for p in payloads:
            fn(p)
        best = min(best, time.perf_counter() - t0)
    return n_spans / best


def measure_native(n_requests: int = 64, spans_per_request: int = 128,
                   repeat: int = 5,
                   payloads: list[bytes] | None = None) -> float | None:
    """Native C++ columnar decode rate (spans/s), or None when the
    native library is unavailable in this environment. Pass prebuilt
    ``payloads`` (from :func:`make_payloads` with the same dims) to
    share generation across paths."""
    if not native.available():
        return None
    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    tz = SpanTensorizer(num_services=32)
    return measure(
        lambda p: tz.columns_from_columnar(
            native.decode_otlp(p, MONITORED_ATTR_KEYS)
        ),
        payloads,
        n_requests * spans_per_request,
        repeat=repeat,
    )


def measure_python(n_requests: int = 64, spans_per_request: int = 128,
                   repeat: int = 5,
                   payloads: list[bytes] | None = None) -> float:
    """Pure-Python record-path decode rate (spans/s)."""
    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    tz = SpanTensorizer(num_services=32)
    return measure(
        lambda p: tz.columns_from_records(decode_export_request(p)),
        payloads,
        n_requests * spans_per_request,
        repeat=repeat,
    )


def measure_pooled(workers: int = 2, n_requests: int = 64,
                   spans_per_request: int = 128, repeat: int = 4,
                   passes: int = 16, coalesce: int = 256,
                   payloads: list[bytes] | None = None) -> float | None:
    """Parallel-ingest-engine rate (spans/s), or None without native.
    Thin wrapper over :func:`measure_pooled_detail` for callers that
    only want the headline number."""
    got = measure_pooled_detail(
        workers=workers, n_requests=n_requests,
        spans_per_request=spans_per_request, repeat=repeat,
        passes=passes, coalesce=coalesce, payloads=payloads,
    )
    return None if got is None else got["spans_per_sec"]


def measure_pooled_detail(workers: int = 2, n_requests: int = 64,
                          spans_per_request: int = 128, repeat: int = 4,
                          passes: int = 16, coalesce: int = 256,
                          payloads: list[bytes] | None = None,
                          ) -> dict | None:
    """Parallel-ingest-engine rate + PHASE BREAKDOWN, or None without
    native.

    End-to-end through the REAL :class:`~.ingest_pool.IngestPool` —
    submit tickets, bounded queue, batched decode into pooled buffers,
    coalesced tensorize — into a null pipeline sink, so the number is
    the engine's, not a stripped-down proxy. ``passes`` replays the
    payload set per timed region so the queue stays deep enough for
    coalescing to engage (the production regime the pool exists for).

    ``phase_share`` attributes flush wall time between the native
    decode, the CRC manifest (verify), the intern/column pass
    (tensorize) and the pipeline merge (submit) — the attribution that
    makes the zero-copy spine's win visible instead of folded into one
    opaque number.
    """
    if not native.available():
        return None
    from .ingest_pool import IngestPool

    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    n_spans = n_requests * spans_per_request * passes
    tz = SpanTensorizer(num_services=32)
    sink = lambda cols: None  # noqa: E731 — decode+tensorize is the cost
    pool = IngestPool(
        sink, tz, workers=workers, coalesce_max=coalesce,
        max_pending=n_requests * passes + 8,
    )
    try:
        for p in payloads:  # warmup: compile nothing, size the scratch
            pool.submit(p)
        pool.drain()
        best = float("inf")
        for _ in range(repeat):
            t0 = time.perf_counter()
            for _ in range(passes):
                for p in payloads:
                    pool.submit(p)
            pool.drain()
            best = min(best, time.perf_counter() - t0)
        stats = pool.stats()
    finally:
        pool.close()
    phase = stats["phase_s"]
    from .ingest_pool import TOP_PHASES

    # Share over the TOP-LEVEL phases only: scan/extract are
    # sub-phases INSIDE the decode envelope (the native two-pass
    # split) — summing them into the denominator would double-count.
    total = sum(phase.get(k, 0.0) for k in TOP_PHASES) or 1.0
    decode_s = phase.get("decode", 0.0) or 1.0
    return {
        "spans_per_sec": n_spans / best,
        "phase_share": {
            k: round(phase.get(k, 0.0) / total, 4) for k in TOP_PHASES
        },
        # How the decode envelope itself splits between the boundary
        # scan and the column extraction (fractions of decode time;
        # the remainder is the ctypes/scratch glue around the call).
        "decode_split": {
            "scan": round(phase.get("scan", 0.0) / decode_s, 4),
            "extract": round(phase.get("extract", 0.0) / decode_s, 4),
        },
        "tickets_parked": stats["tickets_parked"],
        "tickets_recycled": stats["tickets_recycled"],
    }


def measure_raw(n_requests: int = 64, spans_per_request: int = 128,
                repeat: int = 5,
                payloads: list[bytes] | None = None) -> dict | None:
    """Raw two-pass scanner microbench (`make decodebench`): pass-1
    scan vs pass-2 extract vs whole-call throughput PER THREAD, with
    no pool, no tensorize, no CRC manifest — the number a future
    decode regression is attributable against without running the full
    engine. None when native is unavailable.
    """
    if not native.available():
        return None
    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    n_spans = n_requests * spans_per_request
    # The per-pass times come from INSIDE the one batched call
    # (ingest.cc stamps scan_s/extract_s around its own passes), so
    # neither number carries ctypes call overhead or buffer churn —
    # it is the native pass itself, per thread.
    total = sum(map(len, payloads))
    scratch = native.alloc_scratch(
        *native.scratch_dims(total, len(payloads))
    )
    phases: dict[str, float] = {}
    native.decode_otlp_many(
        payloads, MONITORED_ATTR_KEYS, scratch, phases=phases
    )  # warmup
    decode_t = float("inf")
    scan_t = float("inf")
    extract_t = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        native.decode_otlp_many(
            payloads, MONITORED_ATTR_KEYS, scratch, phases=phases
        )
        decode_t = min(decode_t, time.perf_counter() - t0)
        scan_t = min(scan_t, phases.get("scan") or decode_t)
        extract_t = min(extract_t, phases.get("extract") or decode_t)
    return {
        "scan_spans_per_sec": n_spans / scan_t,
        "extract_spans_per_sec": n_spans / extract_t,
        "decode_spans_per_sec": n_spans / decode_t,
        "scan_bytes_per_sec": total / scan_t,
        "payload_bytes": total,
    }


def measure_fat_payload_scaling(
    spans: int = 65536, threads_list=(1, 2), repeat: int = 3
) -> dict | None:
    """ONE oversized OTLP export decoded with N native extraction
    threads (the pass-2 sharding leg `make ingestbench` gates): a
    single fat payload must not serialize on one core. Returns
    {"1": spans/s, "2": spans/s, ..., "scaling": rate_N/rate_1} or
    None when native is unavailable.
    """
    if not native.available():
        return None
    payload = make_payloads(1, spans, seed=3)[0]
    scratch = native.alloc_scratch(
        *native.scratch_dims(len(payload), 1)
    )
    out: dict = {}
    for t in threads_list:
        best = float("inf")
        native.decode_otlp_many(
            [payload], MONITORED_ATTR_KEYS, scratch, threads=t,
            shard_min_bytes=0,
        )
        for _ in range(repeat):
            t0 = time.perf_counter()
            native.decode_otlp_many(
                [payload], MONITORED_ATTR_KEYS, scratch, threads=t,
                shard_min_bytes=0,
            )
            best = min(best, time.perf_counter() - t0)
        out[str(t)] = spans / best
    rates = [out[str(t)] for t in threads_list]
    out["scaling"] = round(rates[-1] / rates[0], 3) if rates[0] else None
    return out


def measure_scaling(workers_list=(1, 2, 3, 4), n_requests: int = 64,
                    spans_per_request: int = 128, repeat: int = 3,
                    payloads: list[bytes] | None = None,
                    detail: dict | None = None) -> dict[str, float]:
    """Worker-count → spans/s curve (the bench artifact's
    ``host_ingest_scaling``); {} when native is unavailable.

    Pass ``detail`` (a dict) to ALSO receive each worker count's phase
    breakdown (``detail[str(w)] = {"phase_share": ..., ...}``) — the
    tensorize+submit share the scaling sweep alone never showed.
    """
    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    out: dict[str, float] = {}
    for w in workers_list:
        got = measure_pooled_detail(
            workers=w, n_requests=n_requests,
            spans_per_request=spans_per_request, repeat=repeat,
            payloads=payloads,
        )
        if got is None:
            return {}
        out[str(w)] = round(got["spans_per_sec"], 1)
        if detail is not None:
            detail[str(w)] = {
                k: v for k, v in got.items() if k != "spans_per_sec"
            }
    return out
