"""ctypes bindings for the native C++ libraries (native/*.cc).

Two kernels live behind this module:

- **ingest** — protobuf payloads → columnar numpy arrays, the host
  half of the ≥200k spans/sec budget (SURVEY.md §7 hard part (a):
  "protobuf decode and hashing must be vectorized/C-accelerated and
  batched"). Semantics pinned to the Python reference decoders by
  tests/test_native_ingest.py.
- **currency** — Money conversion/sum carry arithmetic (the reference
  keeps currency native in C++, server.cpp; so does this framework).
  Semantics pinned by tests/test_native_currency.py.

Build-on-demand: each library is one translation unit compiled with
``g++ -O3`` (~1 s, cached by mtime against its source). Environments
without a compiler simply report ``available() == False`` and callers
fall back to the pure-Python paths — same results, less throughput.

**GIL contract** (the ingest pool's scaling story): libraries load via
``ctypes.CDLL`` — NOT ``ctypes.PyDLL`` — so ctypes RELEASES the GIL
for the duration of every foreign call and re-acquires it on return.
While one decode worker is inside ``otd_decode_otlp_many``, other
workers run Python (or their own native calls) in true parallel; N
decode workers therefore scale until they saturate cores, not the
interpreter lock. The C code touches no Python objects (payload bytes
pass as borrowed ``c_char_p`` pointers kept alive by the caller's
references; outputs are raw numpy-owned memory), which is what makes
the GIL-free window safe. Pinned by
tests/test_ingest_pool.py::test_native_decode_releases_gil — a Python
counter thread must keep making progress while a big decode call is
in flight.

The r15 two-pass scanner extends the same contract INSIDE the window:
``otd_decode_otlp_many`` may spawn ``threads`` native OS threads to
shard its pass-2 extraction. Those threads live entirely within the
GIL-free foreign call (spawned and joined before ctypes re-acquires),
touch only the raw C buffers, and never see a Python object — so the
safety argument is unchanged and the sharding is invisible to the
interpreter beyond the call returning sooner.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import NamedTuple, Sequence

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")

_lock = threading.Lock()
_libs: dict[str, ctypes.CDLL] = {}
_errors: dict[str, str] = {}


class ColumnarSpans(NamedTuple):
    """Decoded OTLP spans as columns (one row per span, document order).

    ``svc_idx`` points into ``services`` (one entry per resource-spans
    block). ``None`` means the resource had no service.name — the
    record-level decoder's "unknown" — which is distinct from a
    present-but-empty name (interned as ``""``, exactly as the record
    path does).
    """

    duration_us: np.ndarray  # float32[N]
    trace_key: np.ndarray  # uint64[N] — first 8 bytes of trace_id, LE
    is_error: np.ndarray  # uint8[N]
    attr_crc: np.ndarray  # uint32[N] — CRC32 of the chosen attr value
    attr_present: np.ndarray  # uint8[N]
    svc_idx: np.ndarray  # int32[N]
    event_count: np.ndarray  # int32[N] — span events on the span
    has_exception: np.ndarray  # uint8[N] — exception/error event present
    services: list[str | None]


class ColumnarOrders(NamedTuple):
    """Decoded OrderResult batch as columns (one row per message)."""

    value_units: np.ndarray  # float32[N] — shipping cost (value lane)
    order_key: np.ndarray  # uint64[N] — first 8 bytes of order id
    attr_crc: np.ndarray  # uint32[N] — CRC32 of first product id


def _build(name: str) -> str | None:
    """Compile native/<name>.cc if missing/stale; returns error string."""
    src = os.path.join(_DIR, f"{name}.cc")
    out = os.path.join(_DIR, "_build", f"libotd_{name}.so")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return None
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-Wall",
        "-Wextra",
        # ingest.cc's sharded decode_many spawns std::thread workers;
        # -pthread is required for that on Linux and harmless for the
        # single-threaded translation units sharing this build rule.
        "-pthread",
        "-shared",
        "-o",
        out,
        src,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"{cmd[0]}: {e}"
    if proc.returncode != 0:
        return proc.stderr.strip() or f"{cmd[0]} exited {proc.returncode}"
    return None


def _lib_for(name: str) -> ctypes.CDLL | None:
    """Build+load native/<name>.cc on first use (cached, thread-safe)."""
    lib = _libs.get(name)  # lock-free hot path (GIL-safe dict read)
    if lib is not None:
        return lib
    with _lock:
        if name in _libs:
            return _libs[name]
        if name in _errors:
            return None
        err = _build(name)
        if err is not None:
            _errors[name] = err
            return None
        lib = ctypes.CDLL(os.path.join(_DIR, "_build", f"libotd_{name}.so"))
        _CONFIGURE[name](lib)
        _libs[name] = lib
        return lib


def _configure_ingest(lib: ctypes.CDLL) -> None:
    # Payload pointers are declared c_char_p so Python bytes pass
    # zero-copy (the C side only reads; lengths travel separately,
    # so embedded NULs are fine).
    lib.otd_decode_otlp.restype = ctypes.c_int
    lib.otd_decode_otlp.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,           # buf, len
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,  # keys
        ctypes.c_int,                               # cap
        ctypes.c_void_p, ctypes.c_void_p,           # duration, trace
        ctypes.c_void_p, ctypes.c_void_p,           # err, crc
        ctypes.c_void_p, ctypes.c_void_p,           # present, svc_idx
        ctypes.c_void_p, ctypes.c_void_p,           # event_count, has_exc
        ctypes.c_char_p, ctypes.c_size_t,           # svc_buf, cap
        ctypes.c_void_p, ctypes.c_int,              # svc_len, rs_cap
        ctypes.POINTER(ctypes.c_int32),             # n_services
    ]
    lib.otd_decode_otlp_many.restype = ctypes.c_int
    lib.otd_decode_otlp_many.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,  # bufs, lens
        ctypes.c_int,                               # n_payloads
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,  # keys
        ctypes.c_int,                               # cap
        ctypes.c_void_p, ctypes.c_void_p,           # duration, trace
        ctypes.c_void_p, ctypes.c_void_p,           # err, crc
        ctypes.c_void_p, ctypes.c_void_p,           # present, svc_idx
        ctypes.c_void_p, ctypes.c_void_p,           # event_count, has_exc
        ctypes.c_char_p, ctypes.c_size_t,           # svc_buf, cap
        ctypes.c_void_p, ctypes.c_int,              # svc_len, rs_cap
        ctypes.POINTER(ctypes.c_int32),             # n_services
        ctypes.c_void_p,                            # payload_rows
        ctypes.c_int, ctypes.c_longlong,            # n_threads, shard_min
        ctypes.POINTER(ctypes.c_double),            # scan_s
        ctypes.POINTER(ctypes.c_double),            # extract_s
    ]
    # Two-pass split, exposed raw for the decodebench microbench and
    # the boundary-adversarial fuzz suite: pass 1 (structural scan →
    # span index) and pass 2 (index → columns).
    lib.otd_scan_otlp.restype = ctypes.c_int
    lib.otd_scan_otlp.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,           # buf, len
        ctypes.c_void_p, ctypes.c_void_p,           # span_off, span_len
        ctypes.c_void_p, ctypes.c_int,              # span_svc, span_cap
        ctypes.c_char_p, ctypes.c_size_t,           # svc_buf, cap
        ctypes.c_void_p, ctypes.c_int,              # svc_len, rs_cap
        ctypes.POINTER(ctypes.c_int32),             # n_services
    ]
    lib.otd_extract_otlp.restype = ctypes.c_int
    lib.otd_extract_otlp.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,           # buf, len
        ctypes.c_void_p, ctypes.c_void_p,           # span_off, span_len
        ctypes.c_void_p, ctypes.c_int,              # span_svc, n_spans
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,  # keys
        ctypes.c_void_p, ctypes.c_void_p,           # duration, trace
        ctypes.c_void_p, ctypes.c_void_p,           # err, crc
        ctypes.c_void_p, ctypes.c_void_p,           # present, svc_idx
        ctypes.c_void_p, ctypes.c_void_p,           # event_count, has_exc
    ]
    lib.otd_decode_orders.restype = ctypes.c_int
    lib.otd_decode_orders.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.otd_crc32.restype = ctypes.c_uint32
    lib.otd_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    # CRC-32C (frame checksum): void* so ndarray memory passes by
    # address without a tobytes copy — checksumming the SOURCE view is
    # what makes frame.encode's scratch-race detection work.
    lib.otd_crc32c.restype = ctypes.c_uint32
    lib.otd_crc32c.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32
    ]
    # Install the USD-normalization table for the order value lane once
    # per load — the same factors kafka_orders.order_to_record applies
    # on the Python path (currency_data is a leaf module; no cycle).
    lib.otd_set_order_rates.restype = None
    lib.otd_set_order_rates.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int
    ]
    from ..currency_data import EUR_RATES, to_usd_factor

    # The C side clamps at 64 entries SILENTLY — growing EUR_RATES past
    # that would diverge native (factor 1.0) from Python (real factor).
    assert len(EUR_RATES) <= 64, "EUR_RATES exceeds native rate-table cap"
    codes = b"".join(
        code.encode().ljust(8, b"\0")[:8] for code in EUR_RATES
    )
    factors = (ctypes.c_double * len(EUR_RATES))(
        *(to_usd_factor(code) for code in EUR_RATES)
    )
    lib.otd_set_order_rates(codes, factors, len(EUR_RATES))


def _configure_currency(lib: ctypes.CDLL) -> None:
    for fn in (lib.otd_money_convert, lib.otd_money_sum):
        fn.restype = ctypes.c_int
    lib.otd_money_convert.argtypes = [
        ctypes.c_double, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.otd_money_sum.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]


def _configure_shipping(lib: ctypes.CDLL) -> None:
    lib.otd_quote_money.restype = ctypes.c_int
    lib.otd_quote_money.argtypes = [
        ctypes.c_double, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.otd_tracking_id.restype = ctypes.c_int
    lib.otd_tracking_id.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_char_p
    ]


def _configure_frontdoor(lib: ctypes.CDLL) -> None:
    # The native HTTP front door (frontdoor.cc): acceptor + per-conn
    # threads live entirely on the C side; these entry points are the
    # pump's batch drain and verdict write-back. otd_fd_next blocks
    # with the GIL released (ctypes.CDLL — the same contract as the
    # decode calls), so a waiting pump costs the interpreter nothing.
    lib.otd_fd_start.restype = ctypes.c_int64
    lib.otd_fd_start.argtypes = [
        ctypes.c_int32, ctypes.c_int64,             # port, max_body
        ctypes.c_int32, ctypes.c_int64,             # max_conns, hdr_timeout
    ]
    lib.otd_fd_port.restype = ctypes.c_int32
    lib.otd_fd_port.argtypes = [ctypes.c_int64]
    lib.otd_fd_next.restype = ctypes.c_int64
    lib.otd_fd_next.argtypes = [
        ctypes.c_int64,                             # handle
        ctypes.c_void_p, ctypes.c_void_p,           # ids, kinds
        ctypes.c_void_p, ctypes.c_void_p,           # ptrs, lens
        ctypes.c_int64, ctypes.c_int64,             # max_n, timeout_ms
    ]
    lib.otd_fd_respond.restype = ctypes.c_int32
    lib.otd_fd_respond.argtypes = [
        ctypes.c_int64, ctypes.c_int64,             # handle, req id
        ctypes.c_int32, ctypes.c_int32,             # status, retry_after
    ]
    lib.otd_fd_stats.restype = None
    lib.otd_fd_stats.argtypes = [ctypes.c_int64, ctypes.c_void_p]
    lib.otd_fd_quiesce.restype = None
    lib.otd_fd_quiesce.argtypes = [ctypes.c_int64]
    lib.otd_fd_stop.restype = None
    lib.otd_fd_stop.argtypes = [ctypes.c_int64]


_CONFIGURE = {
    "ingest": _configure_ingest,
    "frontdoor": _configure_frontdoor,
    "currency": _configure_currency,
    "shipping": _configure_shipping,
}


def _load() -> ctypes.CDLL | None:
    return _lib_for("ingest")


def available() -> bool:
    return _load() is not None


def load_error() -> str | None:
    """Why the ingest library is unavailable (None when it loaded)."""
    _load()
    return _errors.get("ingest")


def currency_available() -> bool:
    return _lib_for("currency") is not None


def frontdoor_available() -> bool:
    return _lib_for("frontdoor") is not None


def frontdoor_load_error() -> str | None:
    _lib_for("frontdoor")
    return _errors.get("frontdoor")


# Signal kinds a front-door ticket carries (frontdoor.cc constants):
# the pump routes traces to the decode pool's pointer path and
# metrics/logs — scrape-cadence traffic — to the Python decoders.
FD_KIND_TRACES = 0
FD_KIND_METRICS = 1
FD_KIND_LOGS = 2

# otd_fd_stats slot names, in the C enum's order (frontdoor.cc
# StatIdx) — keep in sync.
FD_STAT_NAMES = (
    "accepted", "live_conns", "enqueued", "pending", "bad_length",
    "oversized", "chunked", "truncated", "disconnect", "overcap",
    "health", "notfound", "bytes_in", "responded",
)


class FrontDoorBatch(NamedTuple):
    """Reusable drain buffers for :func:`frontdoor_next` — allocated
    once per pump so the steady-state drain performs zero numpy
    allocations."""

    ids: np.ndarray  # int64[max_n] — ticket ids
    kinds: np.ndarray  # int32[max_n] — FD_KIND_*
    ptrs: np.ndarray  # uint64[max_n] — native body addresses
    lens: np.ndarray  # int64[max_n] — body lengths


def frontdoor_alloc_batch(max_n: int) -> FrontDoorBatch:
    return FrontDoorBatch(
        np.empty(max_n, np.int64), np.empty(max_n, np.int32),
        np.empty(max_n, np.uint64), np.empty(max_n, np.int64),
    )


def frontdoor_start(
    port: int, max_body: int, max_conns: int = 64,
    header_timeout_ms: int = 10000,
) -> int:
    """Start a native front door; returns the server handle.

    Raises ``RuntimeError`` when the library is unavailable or the
    port cannot be bound (the daemon surfaces either as a boot error —
    an opt-in front door that silently didn't bind would make the
    operator think the fast path is serving).
    """
    lib = _lib_for("frontdoor")
    if lib is None:
        raise RuntimeError(
            f"native frontdoor unavailable: {frontdoor_load_error()}"
        )
    h = lib.otd_fd_start(
        int(port), int(max_body), int(max_conns), int(header_timeout_ms)
    )
    if h < 0:
        raise RuntimeError(f"frontdoor bind failed on port {port}")
    return int(h)


def frontdoor_port(handle: int) -> int:
    lib = _lib_for("frontdoor")
    assert lib is not None
    return int(lib.otd_fd_port(int(handle)))


def frontdoor_next(
    handle: int, batch: FrontDoorBatch, timeout_ms: int = 100
) -> int:
    """Drain up to ``len(batch.ids)`` complete request tickets into
    ``batch`` (blocking up to ``timeout_ms`` with the GIL released).
    Returns the count, 0 on timeout, or -1 once the server is stopping
    and the queue is empty — the pump's exit signal."""
    lib = _lib_for("frontdoor")
    assert lib is not None
    return int(lib.otd_fd_next(
        int(handle), batch.ids.ctypes.data, batch.kinds.ctypes.data,
        batch.ptrs.ctypes.data, batch.lens.ctypes.data,
        batch.ids.shape[0], int(timeout_ms),
    ))


def frontdoor_body(ptr: int, length: int) -> ctypes.Array:
    """Borrow a ticket's native body buffer as a ctypes view — len()
    and the decode pointer path both work on it, with ZERO copy. The
    buffer stays valid until :func:`frontdoor_respond` for its id (the
    frontdoor.cc ownership rule); callers must respond only after the
    decode consumed the bytes."""
    return (ctypes.c_char * int(length)).from_address(int(ptr))


def frontdoor_respond(
    handle: int, req_id: int, status: int, retry_after: int = 0
) -> None:
    """Deliver the verdict for a ticket: the native side writes the
    canned response and recycles the body buffer."""
    lib = _lib_for("frontdoor")
    assert lib is not None
    lib.otd_fd_respond(
        int(handle), int(req_id), int(status), int(retry_after)
    )


def frontdoor_stats(handle: int) -> dict[str, int]:
    lib = _lib_for("frontdoor")
    assert lib is not None
    out = np.zeros(len(FD_STAT_NAMES), np.int64)
    lib.otd_fd_stats(int(handle), out.ctypes.data)
    return {k: int(v) for k, v in zip(FD_STAT_NAMES, out)}


def frontdoor_quiesce(handle: int) -> None:
    """Graceful-drain phase 1: stop accepting; queued tickets keep
    flowing to the pump, new requests answer 503."""
    lib = _lib_for("frontdoor")
    assert lib is not None
    lib.otd_fd_quiesce(int(handle))


def frontdoor_stop(handle: int) -> None:
    """Full stop: 503 every still-queued ticket, wake the pump
    (frontdoor_next returns -1), join every native thread."""
    lib = _lib_for("frontdoor")
    assert lib is not None
    lib.otd_fd_stop(int(handle))


_INT64_MIN, _INT64_MAX = -(1 << 63), (1 << 63) - 1

# Monitored-key ctypes arrays, cached per key tuple: the key set is a
# process-lifetime constant (otlp.MONITORED_ATTR_KEYS), so rebuilding
# the encoded array per decode call was pure per-flush overhead.
_keys_cache: dict[tuple, ctypes.Array] = {}


def _keys_array(attr_keys: Sequence[str]) -> ctypes.Array:
    t = tuple(attr_keys)
    arr = _keys_cache.get(t)
    if arr is None:
        arr = (ctypes.c_char_p * len(t))(*[k.encode() for k in t])
        _keys_cache[t] = arr
    return arr


def money_convert(
    rate: float, units: int, nanos: int
) -> tuple[int, int, int]:
    """(code, units, nanos): code 0 ok, -2 invalid money, -3 overflow.

    The facade (services.currency) maps -2 to MoneyError and falls back
    to Python arithmetic on -3 (arbitrary-precision territory). Inputs
    outside int64 report -3 here — ctypes would otherwise truncate them
    to their low 64 bits before the C++ guard could see them.
    """
    if not (_INT64_MIN <= units <= _INT64_MAX):
        return -3, 0, 0
    lib = _lib_for("currency")
    assert lib is not None
    ou = ctypes.c_int64(0)
    on = ctypes.c_int32(0)
    code = lib.otd_money_convert(
        rate, units, nanos, ctypes.byref(ou), ctypes.byref(on)
    )
    return code, ou.value, on.value


def money_sum(
    u1: int, n1: int, u2: int, n2: int
) -> tuple[int, int, int]:
    """(code, units, nanos) — same code contract as money_convert."""
    if not (
        _INT64_MIN <= u1 <= _INT64_MAX and _INT64_MIN <= u2 <= _INT64_MAX
    ):
        return -3, 0, 0
    lib = _lib_for("currency")
    assert lib is not None
    ou = ctypes.c_int64(0)
    on = ctypes.c_int32(0)
    code = lib.otd_money_sum(
        u1, n1, u2, n2, ctypes.byref(ou), ctypes.byref(on)
    )
    return code, ou.value, on.value


def shipping_available() -> bool:
    return _lib_for("shipping") is not None


def quote_money(per_item: float, count: int) -> tuple[int, int, int]:
    """(code, units, nanos): code 0 ok, -1 bad count, -3 overflow.

    Quote total = round(per_item * count, 2), split from_float-style —
    the native half of services.shipping (see native/shipping.cc)."""
    lib = _lib_for("shipping")
    assert lib is not None
    ou = ctypes.c_int64(0)
    on = ctypes.c_int32(0)
    code = lib.otd_quote_money(per_item, count, ctypes.byref(ou), ctypes.byref(on))
    return code, ou.value, on.value


def tracking_id(name: bytes) -> str:
    """UUID v5 (URL namespace) over ``name`` — uuid.uuid5 parity."""
    lib = _lib_for("shipping")
    assert lib is not None
    out = ctypes.create_string_buffer(36)
    lib.otd_tracking_id(name, len(name), out)
    return out.raw.decode("ascii")


def crc32(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.otd_crc32(data, len(data)))


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) — the frame checksum (runtime/frame.py).

    Accepts bytes, bytearray, or a C-contiguous ndarray; array memory
    is checksummed in place (no copy). ``crc`` seeds a running
    checksum (0 to start). Slicing-by-8 in C, GIL released.
    """
    lib = _load()
    assert lib is not None
    if isinstance(data, np.ndarray):
        a = data if data.flags.c_contiguous else np.ascontiguousarray(data)
        return int(lib.otd_crc32c(a.ctypes.data, a.nbytes, crc))
    if isinstance(data, bytearray):
        n = len(data)
        buf = (ctypes.c_char * n).from_buffer(data) if n else b""
        return int(lib.otd_crc32c(buf, n, crc))
    return int(lib.otd_crc32c(bytes(data), len(data), crc))


def decode_otlp(
    payload: bytes, attr_keys: Sequence[str]
) -> ColumnarSpans:
    """Columnar decode of an ExportTraceServiceRequest.

    Raises ``ValueError`` on malformed wire data — the same verdicts as
    ``otlp.decode_export_request`` (the HTTP receiver maps either to a
    400).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {load_error()}")
    keys = _keys_array(attr_keys)
    cap = len(payload) // 16 + 64
    # One name byte per payload byte is the ceiling (names are payload
    # substrings); one resource-spans entry needs ≥2 payload bytes.
    svc_cap = len(payload) + 1
    rs_cap = len(payload) // 2 + 2
    svc_buf = ctypes.create_string_buffer(svc_cap)
    svc_len = np.empty(rs_cap, np.int32)
    n_services = ctypes.c_int32(0)
    retried = False
    while True:
        duration = np.empty(cap, np.float32)
        trace = np.empty(cap, np.uint64)
        err = np.empty(cap, np.uint8)
        crc = np.empty(cap, np.uint32)
        present = np.empty(cap, np.uint8)
        svc_idx = np.empty(cap, np.int32)
        event_count = np.empty(cap, np.int32)
        has_exc = np.empty(cap, np.uint8)
        n = lib.otd_decode_otlp(
            payload, len(payload), keys, len(attr_keys), cap,
            duration.ctypes.data, trace.ctypes.data,
            err.ctypes.data, crc.ctypes.data,
            present.ctypes.data, svc_idx.ctypes.data,
            event_count.ctypes.data, has_exc.ctypes.data,
            svc_buf, svc_cap,
            svc_len.ctypes.data, rs_cap,
            ctypes.byref(n_services),
        )
        if n == -2 and not retried:  # pathological tiny-span payloads
            cap = len(payload) // 2 + 64
            retried = True
            continue
        if n < 0:
            raise ValueError(f"malformed OTLP payload (code {n})")
        services: list[str | None] = []
        pos = 0
        for ln in svc_len[: n_services.value]:
            if ln < 0:
                services.append(None)
            else:
                services.append(
                    svc_buf.raw[pos : pos + ln].decode("utf-8", "replace")
                )
                pos += ln
        return ColumnarSpans(
            duration[:n].copy(), trace[:n].copy(), err[:n].copy(),
            crc[:n].copy(), present[:n].copy(), svc_idx[:n].copy(),
            event_count[:n].copy(), has_exc[:n].copy(),
            services,
        )


class DecodeScratch(NamedTuple):
    """Reusable output buffers for :func:`decode_otlp_many`.

    One scratch set services one in-flight decode; the ingest pool
    keeps a freelist of them (``ingest_pool.ScratchPool``) sized by
    high-watermark so steady-state decode performs ZERO numpy
    allocations — the per-request ``np.empty``×8 churn of the serial
    path was a measured ~2× of its span budget. The decode RESULT
    returned to callers is views into these arrays, so a scratch must
    not be released back to its pool until the caller has copied the
    rows out (the pool's coalesce step does exactly that).
    """

    cap: int
    svc_cap: int
    rs_cap: int
    duration: np.ndarray  # float32[cap]
    trace: np.ndarray  # uint64[cap]
    err: np.ndarray  # uint8[cap]
    crc: np.ndarray  # uint32[cap]
    present: np.ndarray  # uint8[cap]
    svc_idx: np.ndarray  # int32[cap]
    event_count: np.ndarray  # int32[cap]
    has_exc: np.ndarray  # uint8[cap]
    svc_buf: ctypes.Array  # char[svc_cap]
    svc_len: np.ndarray  # int32[rs_cap]


def alloc_scratch(cap: int, svc_cap: int, rs_cap: int) -> DecodeScratch:
    return DecodeScratch(
        cap, svc_cap, rs_cap,
        np.empty(cap, np.float32), np.empty(cap, np.uint64),
        np.empty(cap, np.uint8), np.empty(cap, np.uint32),
        np.empty(cap, np.uint8), np.empty(cap, np.int32),
        np.empty(cap, np.int32), np.empty(cap, np.uint8),
        ctypes.create_string_buffer(svc_cap), np.empty(rs_cap, np.int32),
    )


def scratch_dims(
    payload_bytes: int, n_payloads: int, retry: bool = False
) -> tuple[int, int, int]:
    """(cap, svc_cap, rs_cap) for a coalesced batch — the per-payload
    heuristics of :func:`decode_otlp` summed (``retry`` switches to the
    len/2 span ceiling the single-payload path retries with)."""
    denom = 2 if retry else 16
    return (
        payload_bytes // denom + 64 * max(n_payloads, 1),
        payload_bytes + 1,
        payload_bytes // 2 + 2 * max(n_payloads, 1),
    )


# Default byte floor below which decode_otlp_many never shards a batch
# across native threads: under ~256 KiB the extraction wall is small
# enough that a std::thread spawn/join costs more than it hides.
# Overridden per call (the pool passes ANOMALY_INGEST_SHARD_MIN_BYTES).
SHARD_MIN_BYTES_DEFAULT = 262144


def decode_otlp_many(
    payloads: Sequence[bytes],
    attr_keys: Sequence[str],
    scratch: DecodeScratch | None = None,
    threads: int = 0,
    shard_min_bytes: int = SHARD_MIN_BYTES_DEFAULT,
    phases: dict | None = None,
) -> tuple[ColumnarSpans, np.ndarray]:
    """Batched columnar decode: many requests, ONE ctypes round trip.

    Returns ``(columns, payload_rows)`` where ``columns`` spans every
    well-formed payload (rows append in argument order, ``svc_idx``
    into a batch-wide service list) and ``payload_rows[i]`` is payload
    i's row count or ``-1`` when that payload was malformed — the
    per-request verdict the receivers turn into a 400 for exactly the
    bad request while its batchmates proceed.

    Two-pass under the hood (ingest.cc): a structural scan builds the
    batch-wide span index, then extraction fills the columns — sharded
    across up to ``threads`` native OS threads at span-record
    boundaries (mid-payload included, so ONE oversized export spreads
    over cores) whenever the batch carries ≥ ``shard_min_bytes``.
    ``threads<=1`` keeps the serial extraction. ``phases`` (optional
    dict) receives the per-pass wall seconds as ``{"scan": s,
    "extract": s}`` — the ingest pool feeds them to the
    anomaly_phase_seconds histograms.

    With ``scratch`` provided the returned arrays are VIEWS into it
    (zero-copy — the ingest pool's hot path; copy before releasing the
    scratch). Without, fresh copies are returned, matching
    :func:`decode_otlp`. Raises ``ValueError`` only for errors that
    poison the whole batch (over-limit key count); per-payload wire
    garbage never raises here.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {load_error()}")
    n_payloads = len(payloads)
    try:
        bufs = (ctypes.c_char_p * max(n_payloads, 1))(*payloads)
    except TypeError:
        # Buffer-backed payloads (the front door's native body views):
        # cast the address instead of copying — the borrowed-pointer
        # contract is identical, the owner (frontdoor.cc) keeps the
        # buffer alive until its ticket is answered.
        bufs = (ctypes.c_char_p * max(n_payloads, 1))()
        for i, p in enumerate(payloads):
            bufs[i] = (
                p if isinstance(p, bytes)
                else ctypes.cast(p, ctypes.c_char_p)
            )
    lens = np.fromiter(
        map(len, payloads), np.uint64, count=n_payloads
    ) if n_payloads else np.zeros(1, np.uint64)
    total = int(lens.sum()) if n_payloads else 0
    payload_rows = np.empty(max(n_payloads, 1), np.int32)
    keys = _keys_array(attr_keys)
    scan_s = ctypes.c_double(0.0)
    extract_s = ctypes.c_double(0.0)
    retried = False
    while True:
        need = scratch_dims(total, n_payloads, retried)
        s = scratch
        if s is None or s.cap < need[0] or s.svc_cap < need[1] or s.rs_cap < need[2]:
            s = alloc_scratch(*need)
        n_services = ctypes.c_int32(0)
        n = lib.otd_decode_otlp_many(
            bufs, lens.ctypes.data, n_payloads,
            keys, len(attr_keys), s.cap,
            s.duration.ctypes.data, s.trace.ctypes.data,
            s.err.ctypes.data, s.crc.ctypes.data,
            s.present.ctypes.data, s.svc_idx.ctypes.data,
            s.event_count.ctypes.data, s.has_exc.ctypes.data,
            s.svc_buf, s.svc_cap,
            s.svc_len.ctypes.data, s.rs_cap,
            ctypes.byref(n_services), payload_rows.ctypes.data,
            int(threads), int(shard_min_bytes),
            ctypes.byref(scan_s), ctypes.byref(extract_s),
        )
        if n in (-2, -3) and not retried:
            # Pathological tiny-span payloads overflowed the heuristic
            # capacity: retry once at the hard ceiling (decode_otlp's
            # same ladder), bypassing the too-small caller scratch.
            retried = True
            scratch = None
            continue
        if n < 0:
            raise ValueError(f"otlp batch decode failed (code {n})")
        if phases is not None:
            phases["scan"] = scan_s.value
            phases["extract"] = extract_s.value
        # Copy ONLY the used name-byte prefix, once: `svc_buf.raw` would
        # copy the whole (payload-sized) buffer per access — measured at
        # ~90% of a big flush's wall time before this went string_at.
        lens_list = s.svc_len[: n_services.value].tolist()
        used = sum(ln for ln in lens_list if ln > 0)
        blob = ctypes.string_at(s.svc_buf, used)
        services: list[str | None] = []
        pos = 0
        for ln in lens_list:
            if ln < 0:
                services.append(None)
            else:
                services.append(
                    blob[pos : pos + ln].decode("utf-8", "replace")
                )
                pos += ln
        cols = ColumnarSpans(
            s.duration[:n], s.trace[:n], s.err[:n], s.crc[:n],
            s.present[:n], s.svc_idx[:n], s.event_count[:n],
            s.has_exc[:n], services,
        )
        if scratch is None:  # no caller-owned buffers: hand out copies
            cols = ColumnarSpans(
                *(a[:n].copy() for a in cols[:8]), services
            )
        return cols, payload_rows[:n_payloads]


class SpanIndex(NamedTuple):
    """Pass-1 structural index over ONE payload (`scan_otlp`): span
    record boundaries plus the resource-spans service table — exactly
    what pass 2 (`extract_otlp`) consumes. Offsets are relative to the
    scanned payload's first byte."""

    span_off: np.ndarray  # int32[N] — span submessage offset
    span_len: np.ndarray  # int32[N] — span submessage length
    span_svc: np.ndarray  # int32[N] — resource-spans entry per span
    services: list[str | None]


def scan_otlp(payload: bytes) -> SpanIndex:
    """Pass 1 alone: structural scan → span index (no column work).

    The raw-scanner surface `make decodebench` prices and the fuzz
    suite's boundary oracle (truncation exactly at a pass-1 boundary).
    Raises ``ValueError`` on malformed framing — span-interior damage
    is invisible to the scan by design (pass 2's verdict).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {load_error()}")
    cap = len(payload) // 2 + 64  # hard ceiling: a span costs ≥2 bytes
    rs_cap = len(payload) // 2 + 2
    svc_cap = len(payload) + 1
    span_off = np.empty(cap, np.int32)
    span_len = np.empty(cap, np.int32)
    span_svc = np.empty(cap, np.int32)
    svc_buf = ctypes.create_string_buffer(svc_cap)
    svc_len = np.empty(rs_cap, np.int32)
    n_services = ctypes.c_int32(0)
    n = lib.otd_scan_otlp(
        payload, len(payload),
        span_off.ctypes.data, span_len.ctypes.data, span_svc.ctypes.data,
        cap, svc_buf, svc_cap, svc_len.ctypes.data, rs_cap,
        ctypes.byref(n_services),
    )
    if n < 0:
        raise ValueError(f"malformed OTLP payload (code {n})")
    services: list[str | None] = []
    pos = 0
    blob = ctypes.string_at(
        svc_buf, sum(int(ln) for ln in svc_len[: n_services.value] if ln > 0)
    )
    for ln in svc_len[: n_services.value]:
        if ln < 0:
            services.append(None)
        else:
            services.append(blob[pos : pos + ln].decode("utf-8", "replace"))
            pos += ln
    return SpanIndex(
        span_off[:n].copy(), span_len[:n].copy(), span_svc[:n].copy(),
        services,
    )


def extract_otlp(
    payload: bytes, index: SpanIndex, attr_keys: Sequence[str]
) -> ColumnarSpans:
    """Pass 2 alone: a `scan_otlp` index → columns (no re-scan).

    Completes the decode the way `decode_otlp` would have — the
    decodebench pairing that isolates extract throughput from scan
    throughput. Raises ``ValueError`` on a malformed span interior.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {load_error()}")
    n = index.span_off.shape[0]
    duration = np.empty(n, np.float32)
    trace = np.empty(n, np.uint64)
    err = np.empty(n, np.uint8)
    crc = np.empty(n, np.uint32)
    present = np.empty(n, np.uint8)
    svc_idx = np.empty(n, np.int32)
    event_count = np.empty(n, np.int32)
    has_exc = np.empty(n, np.uint8)
    keys = _keys_array(attr_keys)
    rc = lib.otd_extract_otlp(
        payload, len(payload),
        index.span_off.ctypes.data, index.span_len.ctypes.data,
        index.span_svc.ctypes.data, n,
        keys, len(attr_keys),
        duration.ctypes.data, trace.ctypes.data,
        err.ctypes.data, crc.ctypes.data,
        present.ctypes.data, svc_idx.ctypes.data,
        event_count.ctypes.data, has_exc.ctypes.data,
    )
    if rc < 0:
        raise ValueError(f"malformed OTLP payload (code {rc})")
    return ColumnarSpans(
        duration, trace, err, crc, present, svc_idx, event_count, has_exc,
        list(index.services),
    )


def decode_orders(payloads: Sequence[bytes]) -> ColumnarOrders:
    """Columnar decode of a batch of OrderResult payloads."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {load_error()}")
    n = len(payloads)
    bufs = (ctypes.c_char_p * max(n, 1))(*payloads) if n else (
        ctypes.c_char_p * 1
    )()
    lens = np.asarray([len(p) for p in payloads] or [0], np.uint64)
    value = np.empty(max(n, 1), np.float32)
    key = np.empty(max(n, 1), np.uint64)
    crc = np.empty(max(n, 1), np.uint32)
    rc = lib.otd_decode_orders(
        bufs, lens.ctypes.data, n,
        value.ctypes.data, key.ctypes.data, crc.ctypes.data,
    )
    if rc < 0:
        raise ValueError(f"malformed OrderResult payload (code {rc})")
    return ColumnarOrders(value[:n], key[:n], crc[:n])
