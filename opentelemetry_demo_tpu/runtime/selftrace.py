"""Detector self-telemetry: batch-lifecycle traces into the shop's stack.

The detector observes the shop but was blind to itself: the per-phase
flush timers (decode/verify/tensorize/stage/put/dispatch/harvest) lived
only as bench-time pool/spine counters, and a DEGRADED/SATURATED/FENCED
transition left nothing behind but a counter bump. This module closes
the loop the way the reference's services do — the sidecar emits its
OWN traces into the same telemetry pipeline it monitors (PAPER.md's
collector seam: the otlphttp exporters and the Jaeger surface):

- **One trace per dispatched batch**, spanning the full lifecycle —
  decode → CRC-verify → tensorize → spine-stage → device-put →
  dispatch → harvest → flag. The ingest-side phases arrive as *flush
  segments* recorded by the decode pool (bounded ring; a sampled batch
  absorbs the segments of the flushes that fed the queue since the
  last sampled batch — the pump merges flushes into batches, so the
  attribution is flush-granular by construction, and honest about it).
- **Span links carry the exemplar trace ids** the pipeline captures at
  flag time (the PR 6 query-plane rings): the flag span of a detector
  batch trace links back to the concrete shop traces it flagged, so a
  Jaeger view of the detector's own batch jumps straight to the
  evidence.
- **Head sampling is deterministic splitmix64** over the batch
  sequence number (``ANOMALY_SELFTRACE_SAMPLE``): the same batch is
  sampled on every replica at every restart, an unsampled batch costs
  one integer hash and a compare, and two processes never disagree
  about which batches carry traces.
- **Export rides the existing background poster** (`otlp_export.
  BackgroundPoster`): encode happens at harvest time (off the dispatch
  tick), the POST happens on the poster's sender thread — the hot path
  never touches the network. Span/flag names come from the constant
  tables below; the ``trace-discipline`` staticcheck pass fences every
  call site to them (the metric-surface rule, applied to spans).

The same module owns the **phase vocabulary** for the promoted
``anomaly_phase_seconds{phase=}`` histograms (telemetry.metrics): one
table, so the tracer's span names, the histogram's label values and
the Grafana panels can never drift.

Knob registry: ``utils.config.SELFTRACE_KNOBS`` (enable / sample /
endpoint / flight ring / flight dir), threaded daemon → compose → k8s
like every family.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Callable

from . import wire

# Service name the detector's own traces carry (the resource attr the
# collector/Jaeger group by — the sidecar appears beside the shop's
# services in the same UI).
SELF_SERVICE = "anomaly-detector"

# -- span-name table (the trace-discipline registry) -------------------
#
# Every span a detector batch trace may carry. The staticcheck
# ``trace-discipline`` pass fences span/phase construction sites to
# these constants (mirroring the metric-surface pass): an inline
# literal span name could typo silently and fork the vocabulary the
# dashboards and the Jaeger searches are written against.
SPAN_BATCH = "detector.batch"
SPAN_DECODE = "detector.decode"
SPAN_DECODE_SCAN = "detector.decode_scan"
SPAN_DECODE_EXTRACT = "detector.decode_extract"
SPAN_VERIFY = "detector.crc_verify"
SPAN_TENSORIZE = "detector.tensorize"
SPAN_SUBMIT = "detector.submit"
SPAN_STAGE = "detector.spine_stage"
SPAN_PUT = "detector.device_put"
SPAN_DISPATCH = "detector.dispatch"
SPAN_HARVEST = "detector.harvest"
SPAN_FLAG = "detector.flag"

# -- phase-label table (anomaly_phase_seconds{phase=} vocabulary) ------
PHASE_DECODE = "decode"
# Sub-phases of the native decode (the two-pass scanner, ingest.cc):
# pass-1 structural scan vs pass-2 column extraction. They overlap
# PHASE_DECODE (which stays the whole-call envelope), so phase SHARE
# computations must not sum them into the denominator — see
# ingest_pool.TOP_PHASES.
PHASE_SCAN = "scan"
PHASE_EXTRACT = "extract"
PHASE_VERIFY = "verify"
PHASE_TENSORIZE = "tensorize"
PHASE_SUBMIT = "submit"
PHASE_STAGE = "stage"
PHASE_PUT_WAIT = "put_wait"
PHASE_DISPATCH = "dispatch"
PHASE_HARVEST = "harvest"
PHASE_HARVEST_LAG = "harvest_lag"
PHASE_FLAG = "flag"

# Phase → span-name projection (the flush segments arrive keyed by
# phase label; the trace renders them as spans).
SPAN_FOR_PHASE = {
    PHASE_DECODE: SPAN_DECODE,
    PHASE_SCAN: SPAN_DECODE_SCAN,
    PHASE_EXTRACT: SPAN_DECODE_EXTRACT,
    PHASE_VERIFY: SPAN_VERIFY,
    PHASE_TENSORIZE: SPAN_TENSORIZE,
    PHASE_SUBMIT: SPAN_SUBMIT,
    PHASE_STAGE: SPAN_STAGE,
    PHASE_PUT_WAIT: SPAN_PUT,
    PHASE_DISPATCH: SPAN_DISPATCH,
    PHASE_HARVEST: SPAN_HARVEST,
    PHASE_FLAG: SPAN_FLAG,
}

# Histogram buckets (seconds) for the phase/put-wait/harvest-lag
# histograms: phases are µs-to-ms host work, harvest lag stretches to
# the tunneled-RTT regime — one ladder covers both ends.
PHASE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0,
)

_MASK64 = (1 << 64) - 1
_SPLIT_GAMMA = 0x9E3779B97F4A7C15
_SPLIT_M1 = 0xBF58476D1CE4E5B9
_SPLIT_M2 = 0x94D049BB133111EB


def splitmix64(x: int) -> int:
    """Scalar splitmix64, bit-identical to ``ops.hashing.splitmix64_np``
    (pinned by tests) — pure-int so the per-batch sampling decision
    never pays numpy scalar overhead on the pump thread."""
    x = (x + _SPLIT_GAMMA) & _MASK64
    z = x
    z ^= z >> 30
    z = (z * _SPLIT_M1) & _MASK64
    z ^= z >> 27
    z = (z * _SPLIT_M2) & _MASK64
    z ^= z >> 31
    return z


def sampled(seq: int, rate: float) -> bool:
    """Deterministic head-sampling decision for batch ``seq``.

    The hash (not the raw counter) drives the decision so rate=1/N
    doesn't degenerate to strided sampling that aliases against any
    periodic load shape; determinism means every replica and every
    restart agrees about which batches carry traces."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return splitmix64(seq) < int(rate * float(1 << 64))


class BatchTrace:
    """One sampled batch's lifecycle: spans accumulated across the
    pump and harvester threads (handed off through the pipeline's
    in-flight deque — never concurrently mutated), exported once at
    finish."""

    __slots__ = ("seq", "trace_id", "t0_wall", "t0_perf", "spans", "attrs")

    def __init__(self, seq: int):
        self.seq = int(seq)
        # Deterministic ids: two halves of the splitmix stream, so a
        # test (or an operator replaying a drive) can predict the
        # Jaeger trace id of batch N.
        self.trace_id = (
            splitmix64(2 * self.seq).to_bytes(8, "big")
            + splitmix64(2 * self.seq + 1).to_bytes(8, "big")
        )
        self.t0_wall = time.time()
        self.t0_perf = time.perf_counter()
        # (name, start_offset_s, duration_s, attrs tuple, links tuple)
        self.spans: list[tuple] = []
        self.attrs: list[tuple[str, str]] = []

    def span(
        self,
        name: str,
        duration_s: float,
        end_perf: float | None = None,
        attrs: tuple = (),
        links: tuple = (),
    ) -> None:
        """Record one phase span. ``end_perf`` defaults to now; the
        span's start is derived (end − duration). Offsets may predate
        the trace object (ingest segments recorded before the batch
        assembled) — only start<end matters on the wire."""
        end = (
            time.perf_counter() if end_perf is None else end_perf
        ) - self.t0_perf
        self.spans.append(
            (name, end - max(duration_s, 0.0), max(duration_s, 0.0),
             tuple(attrs), tuple(links))
        )


def _span_id(trace_id: bytes, index: int) -> bytes:
    seed = int.from_bytes(trace_id[:8], "big") ^ index
    return splitmix64(seed).to_bytes(8, "big")


def _kv(key: str, value: str) -> bytes:
    from .otlp_export import _kv_str

    return _kv_str(key, str(value))


def encode_selftrace_request(
    trace: BatchTrace, service: str = SELF_SERVICE
) -> bytes:
    """BatchTrace → ExportTraceServiceRequest protobuf.

    One ResourceSpans block (service.name = the detector), one root
    ``detector.batch`` span parenting every phase span. Span links
    (Link: trace_id=1, span_id=2, attributes=4 — trace/v1 field 13 on
    Span) carry the flagged shop traces: the link's trace id is the
    exemplar's 8-byte prefix zero-padded to 16, exactly the id prefix
    a Jaeger search matches. Inverse: :func:`decode_selftrace_request`
    (round-trip pinned by tests/test_selftrace.py).
    """
    t0_ns = int(trace.t0_wall * 1e9)
    root_sid = _span_id(trace.trace_id, 0)
    offsets = [s[1] for s in trace.spans] or [0.0]
    ends = [s[1] + s[2] for s in trace.spans] or [0.0]
    root_start = t0_ns + int(min(min(offsets), 0.0) * 1e9)
    root_end = t0_ns + int(max(max(ends), 0.0) * 1e9)
    spans_out = b""
    for i, (name, start_off, dur, attrs, links) in enumerate(trace.spans):
        start = t0_ns + int(start_off * 1e9)
        end = start + int(dur * 1e9)
        span = (
            wire.encode_len(1, trace.trace_id)
            + wire.encode_len(2, _span_id(trace.trace_id, i + 1))
            + wire.encode_len(4, root_sid)
            + wire.encode_len(5, name.encode())
            + wire.encode_int(6, 1)  # SPAN_KIND_INTERNAL
            + wire.encode_fixed64(7, max(start, 0))
            + wire.encode_fixed64(8, max(end, 0))
        )
        for k, v in attrs:
            span += wire.encode_len(9, _kv(k, v))
        for link_hex in links:
            raw = bytes.fromhex(link_hex)
            link = (
                wire.encode_len(1, (raw + b"\0" * 16)[:16])
                + wire.encode_len(2, raw[:8].ljust(8, b"\0"))
                + wire.encode_len(4, _kv("exemplar.trace_prefix", link_hex))
            )
            span += wire.encode_len(13, link)
        spans_out += wire.encode_len(2, span)
    root = (
        wire.encode_len(1, trace.trace_id)
        + wire.encode_len(2, root_sid)
        + wire.encode_len(5, SPAN_BATCH.encode())
        + wire.encode_int(6, 1)
        + wire.encode_fixed64(7, max(root_start, 0))
        + wire.encode_fixed64(8, max(root_end, root_start, 0))
    )
    for k, v in [("batch.seq", str(trace.seq))] + list(trace.attrs):
        root += wire.encode_len(9, _kv(k, v))
    spans_out += wire.encode_len(2, root)
    resource = wire.encode_len(1, _kv("service.name", service))
    rs = wire.encode_len(1, resource) + wire.encode_len(2, spans_out)
    return wire.encode_len(1, rs)


def _decode_kv(buf: bytes) -> tuple[str, str]:
    f = wire.scan_fields(buf)
    key = wire.first(f, 1, b"").decode()
    val = b""
    any_val = wire.first(f, 2)
    if any_val is not None:
        val = wire.first(wire.scan_fields(any_val), 1, b"")
        if isinstance(val, bytes):
            val = val.decode()
    return key, str(val)


def decode_selftrace_request(payload: bytes) -> list[dict]:
    """Inverse of :func:`encode_selftrace_request` over the fields the
    self-tracer writes — the test/forensics reader. Returns one dict
    per span: name / trace_id / span_id / parent_span_id (hex),
    start/end ns, attrs dict, links (list of trace-id hex)."""
    out: list[dict] = []
    req = wire.scan_fields(payload)
    for rs_buf in req.get(1, []):
        rs = wire.scan_fields(rs_buf)
        service = None
        res_buf = wire.first(rs, 1)
        if res_buf is not None:
            res = wire.scan_fields(res_buf)
            for attr_buf in res.get(1, []):
                k, v = _decode_kv(attr_buf)
                if k == "service.name":
                    service = v
        # ResourceSpans.scope_spans (2) wraps the spans once; the
        # spans are field 2 of the ScopeSpans submessage (the same
        # wrap-once layout otlp_export writes).
        span_bufs = []
        for ss_buf in rs.get(2, []):
            span_bufs.extend(wire.scan_fields(ss_buf).get(2, []))
        for span_buf in span_bufs:
            span = wire.scan_fields(span_buf)
            attrs = dict(
                _decode_kv(a) for a in span.get(9, [])
            )
            links = []
            for link_buf in span.get(13, []):
                link = wire.scan_fields(link_buf)
                tid = wire.first(link, 1, b"")
                links.append(tid.hex())
            out.append({
                "service": service,
                "name": wire.first(span, 5, b"").decode(),
                "trace_id": wire.first(span, 1, b"").hex(),
                "span_id": wire.first(span, 2, b"").hex(),
                "parent_span_id": (
                    wire.first(span, 4, b"") or b""
                ).hex(),
                "start_ns": wire.first(span, 7, 0),
                "end_ns": wire.first(span, 8, 0),
                "attrs": attrs,
                "links": links,
            })
    return out


def make_exporter(endpoint: str, timeout_s: float = 2.0, queue_max: int = 64):
    """A BackgroundPoster shipping encoded trace requests to an OTLP
    endpoint — the shared trace-transport selection
    (``otlp_export.make_traces_poster``: ``grpc://`` picks gRPC,
    anything else posts to ``/v1/traces``). The ONE network leg, and
    it lives entirely on the poster's sender thread."""
    from .otlp_export import make_traces_poster

    return make_traces_poster(endpoint, timeout_s, queue_max)


class SelfTracer:
    """Low-overhead batch-lifecycle tracer (see module doc).

    ``submit(body)`` receives each encoded ExportTraceServiceRequest —
    normally a :class:`otlp_export.BackgroundPoster`'s ``submit`` (the
    network never runs on the caller's thread); tests pass a capture
    list. An unsampled batch costs one splitmix64 + compare; a
    disabled tracer is simply ``None`` at every call site.

    Thread contract: ``flush_segment`` is called by decode-pool
    workers (bounded deque, GIL-atomic appends); ``begin``/``finish``
    run on the pump/harvester threads, and a BatchTrace is only ever
    touched by the thread currently holding the batch (the pipeline's
    in-flight hand-off orders the accesses).
    """

    def __init__(
        self,
        submit: Callable[[bytes], None] | None = None,
        sample: float = 0.01,
        segment_ring: int = 8,
        service: str = SELF_SERVICE,
    ):
        self._submit = submit
        self.sample = float(sample)
        self.service = service
        self._seq = itertools.count()
        # Recent pool flush segments (ts, {phase: seconds}): the next
        # sampled batch absorbs them as ingest-phase spans. Bounded —
        # under sparse sampling old segments fall off rather than grow.
        self._segments: deque = deque(maxlen=max(int(segment_ring), 1))
        self.traces_started = 0
        self.traces_exported = 0
        self.spans_exported = 0
        self.links_exported = 0

    def flush_segment(self, phases: dict) -> None:
        """Record one decode-pool flush's phase durations (worker
        thread). Cheap: one dict copy + a bounded append."""
        self._segments.append((time.perf_counter(), dict(phases)))

    def begin(self) -> BatchTrace | None:
        """Per-batch sampling gate (pump thread): a BatchTrace for a
        sampled batch, None otherwise. Consumes pending flush segments
        into ingest-phase spans when sampled (unsampled batches leave
        them for the next sampled one; the ring bounds staleness)."""
        seq = next(self._seq)
        if not sampled(seq, self.sample):
            return None
        trace = BatchTrace(seq)
        self.traces_started += 1
        while self._segments:
            t_seg, phases = self._segments.popleft()
            for phase, dur in phases.items():
                name = SPAN_FOR_PHASE.get(phase)
                if name is not None:
                    trace.span(name, dur, end_perf=t_seg)
        return trace

    def finish(self, trace: BatchTrace) -> bytes:
        """Encode + hand off one completed trace (harvester/pump
        thread). Returns the encoded request (tests read it back)."""
        body = encode_selftrace_request(trace, self.service)
        self.traces_exported += 1
        self.spans_exported += len(trace.spans) + 1  # + root
        self.links_exported += sum(len(s[4]) for s in trace.spans)
        if self._submit is not None:
            self._submit(body)
        return body

    def stats(self) -> dict:
        return {
            "sample": self.sample,
            "traces_started": self.traces_started,
            "traces_exported": self.traces_exported,
            "spans_exported": self.spans_exported,
            "links_exported": self.links_exported,
            "segments_pending": len(self._segments),
        }
