"""Component supervision: the sidecar outlives its own failures.

The reference drives every *shop-side* failure through flagd fault
flags, and the detector measures time-to-detect for all of them — but a
detector whose own ingest thread dies on a broker restart is blind in
exactly the incident it exists for. This module is the supervision tree
for the daemon's components (Kafka orders pump, OTLP receivers, report
harvester, checkpoint writer): each is registered with a restart hook
and/or a liveness probe, crashes trigger bounded exponential backoff
with jitter, and a restart budget detects crash loops.

Design rules:

- **Never give up.** A component that exhausts its restart budget is
  marked DEGRADED (gauge + per-component gRPC health NOT_SERVING), and
  retries continue at the max backoff — an always-on sidecar that stops
  retrying has turned a transient fault into a permanent outage.
- **No supervisor thread.** Restarts run on the daemon's pump thread
  via :meth:`tick` (called every step) and :meth:`run_step` (guarded
  inline calls). A supervisor with its own thread would itself need
  supervising.
- **Health is observable.** State surfaces three ways: Prometheus
  (``anomaly_component_restarts_total{component=...}``,
  ``anomaly_component_up{component=...}``, ``anomaly_degraded``), the
  gRPC health service (service name ``anomaly.component.<name>`` —
  probe with ``runtime.health_probe --component <name>``), and logs.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Callable

from .grpc_health import NOT_SERVING, SERVING

log = logging.getLogger(__name__)

# Component states.
UP = "up"
BACKOFF = "backoff"  # crashed; a restart attempt is scheduled
DEGRADED = "degraded"  # crash loop: restart budget exhausted in-window
# Daemon-level state (not a per-component one): the pipeline is
# deliberately shedding/throttling under overload. Distinct from
# DEGRADED — nothing is crashing, the runtime is executing its overload
# plan — and ORDERED below it: a crash loop is always the worse news,
# so overall_state() reports DEGRADED even while also saturated.
SATURATED = "saturated"

# gRPC health service-name prefix for per-component status.
HEALTH_PREFIX = "anomaly.component."


class _Component:
    __slots__ = (
        "name", "restart", "probe", "probe_interval_s", "base_backoff_s",
        "max_backoff_s", "restart_budget", "budget_window_s",
        "consecutive_failures", "crash_times", "next_attempt_at",
        "next_probe_at", "state", "restarts", "last_error",
    )

    def __init__(self, name, restart, probe, probe_interval_s,
                 base_backoff_s, max_backoff_s, restart_budget,
                 budget_window_s, now):
        self.name = name
        self.restart = restart
        self.probe = probe
        self.probe_interval_s = probe_interval_s
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.restart_budget = restart_budget
        self.budget_window_s = budget_window_s
        self.consecutive_failures = 0
        self.crash_times: deque = deque()
        self.next_attempt_at = 0.0
        # First probe one interval out: the component just booted and a
        # probe raced against its own startup would count a false crash.
        self.next_probe_at = now + probe_interval_s
        self.state = UP
        self.restarts = 0
        self.last_error: str | None = None


class Supervisor:
    """Registry of supervised components with backoff'd restarts.

    ``registry`` is a :class:`telemetry.metrics.MetricRegistry` (or
    None); ``time_fn``/``rng`` are injectable for tests so backoff and
    budget windows run on a virtual clock.
    """

    def __init__(self, registry=None, time_fn: Callable[[], float] = time.monotonic,
                 rng: random.Random | None = None):
        self._registry = registry
        self._time = time_fn
        self._rng = rng or random.Random(0xC0FFEE)
        self._components: dict[str, _Component] = {}
        self._lock = threading.RLock()
        self._saturation_probe: Callable[[], bool] | None = None
        self._last_saturated: bool | None = None
        self._role_probe: Callable[[], tuple[str, int]] | None = None
        self._last_role: tuple[str, int] | None = None
        self._roles_seen: set[str] = set()

    # -- registration ---------------------------------------------------

    def register(
        self,
        name: str,
        restart: Callable[[], None] | None = None,
        probe: Callable[[], bool] | None = None,
        probe_interval_s: float = 0.0,
        base_backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        restart_budget: int = 5,
        budget_window_s: float = 60.0,
    ) -> None:
        """Register a component.

        ``restart()`` re-creates/starts the component (may raise — that
        counts as another crash). ``probe()`` returns True while the
        component is healthy; probed from :meth:`tick` every
        ``probe_interval_s``. Components driven through
        :meth:`run_step` need neither — the guarded call itself is the
        probe. More than ``restart_budget`` crashes inside
        ``budget_window_s`` is a crash loop → DEGRADED.
        """
        with self._lock:
            self._components[name] = _Component(
                name, restart, probe, probe_interval_s, base_backoff_s,
                max_backoff_s, restart_budget, budget_window_s, self._time(),
            )

    def registered(self, name: str) -> bool:
        """True if ``name`` is already registered.

        Re-registering would mint a fresh crash/backoff budget, so
        callers whose start path can run more than once (promotion,
        restart-after-failure) gate on this to stay idempotent."""
        with self._lock:
            return name in self._components
        self._export(self._components[name])

    # -- crash accounting ----------------------------------------------

    def _crashed(self, c: _Component, err: BaseException | str) -> None:
        now = self._time()
        c.consecutive_failures += 1
        c.restarts += 1
        c.last_error = f"{type(err).__name__}: {err}" if isinstance(
            err, BaseException) else str(err)
        c.crash_times.append(now)
        while c.crash_times and now - c.crash_times[0] > c.budget_window_s:
            c.crash_times.popleft()
        in_loop = len(c.crash_times) > c.restart_budget
        # Bounded exponential backoff with full jitter in [0.5x, 1.5x):
        # synchronized thundering-herd reconnects are exactly what a
        # recovering broker does not need. A crash-looping component is
        # pinned at max backoff.
        base = c.max_backoff_s if in_loop else min(
            c.base_backoff_s * (2.0 ** (c.consecutive_failures - 1)),
            c.max_backoff_s,
        )
        c.next_attempt_at = now + base * (0.5 + self._rng.random())
        prev = c.state
        c.state = DEGRADED if in_loop else BACKOFF
        if c.state == DEGRADED and prev != DEGRADED:
            log.error(
                "component %s entered crash loop (%d crashes in %.0fs): %s",
                c.name, len(c.crash_times), c.budget_window_s, c.last_error,
            )
        else:
            log.warning(
                "component %s crashed (%s); restart #%d in %.2fs",
                c.name, c.last_error, c.restarts,
                c.next_attempt_at - now,
            )
        if self._registry is not None:
            from ..telemetry import metrics as tm

            self._registry.counter_add(
                tm.ANOMALY_COMPONENT_RESTARTS, 1.0, component=c.name
            )
        self._export(c)

    def _recovered(self, c: _Component) -> None:
        if c.state == UP and c.consecutive_failures == 0:
            return
        if c.state != UP:
            log.info("component %s recovered after %d restarts",
                     c.name, c.consecutive_failures)
        c.consecutive_failures = 0
        c.state = UP
        self._export(c)

    def _export(self, c: _Component) -> None:
        if self._registry is None:
            return
        from ..telemetry import metrics as tm

        self._registry.gauge_set(
            tm.ANOMALY_COMPONENT_UP, 1.0 if c.state == UP else 0.0,
            component=c.name,
        )
        self._registry.gauge_set(
            tm.ANOMALY_DEGRADED,
            1.0 if any(x.state == DEGRADED for x in self._components.values())
            else 0.0,
        )

    # -- driving --------------------------------------------------------

    def run_step(self, name: str, fn: Callable, *args, **kwargs):
        """Guarded inline call: ``fn(*args)`` with crashes quarantined.

        Returns ``fn``'s result; returns None (without calling) while
        the component sits in its backoff window, and None when the call
        raises (the exception is recorded, never propagated — one bad
        poll must not kill the pump loop).
        """
        with self._lock:
            c = self._components[name]
            if c.state != UP and self._time() < c.next_attempt_at:
                return None
        try:
            out = fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — quarantine IS the point
            with self._lock:
                self._crashed(c, e)
            return None
        with self._lock:
            self._recovered(c)
        return out

    def report_crash(self, name: str, err: BaseException | str) -> None:
        """External crash report (e.g. a receiver thread's last words)."""
        with self._lock:
            self._crashed(self._components[name], err)

    def tick(self, now: float | None = None) -> None:
        """One supervision pass: restart due components, run due probes.

        Called from the daemon's pump loop — cheap when nothing is
        wrong (a dict scan and a few clock reads).
        """
        now = self._time() if now is None else now
        if self._registry is not None and self._saturation_probe is not None:
            sat = self.saturated()
            if sat != self._last_saturated:  # edge-triggered gauge write
                self._last_saturated = sat
                from ..telemetry import metrics as tm

                self._registry.gauge_set(
                    tm.ANOMALY_SATURATED, 1.0 if sat else 0.0
                )
        if self._registry is not None and self._role_probe is not None:
            role_epoch = self.role()
            if role_epoch is not None and role_epoch != self._last_role:
                # Edge-triggered like saturation: role flips are rare
                # (failover), scrapes are not.
                self._last_role = role_epoch
                role, epoch = role_epoch
                self._roles_seen.add(role)
                from ..telemetry import metrics as tm

                for seen in self._roles_seen:
                    self._registry.gauge_set(
                        tm.ANOMALY_ROLE, 1.0 if seen == role else 0.0,
                        role=seen,
                    )
                self._registry.gauge_set(tm.ANOMALY_EPOCH, float(epoch))
        with self._lock:
            comps = list(self._components.values())
        for c in comps:
            with self._lock:
                due_restart = (
                    c.state != UP and c.restart is not None
                    and now >= c.next_attempt_at
                )
            if due_restart:
                try:
                    c.restart()
                except Exception as e:  # noqa: BLE001 — failed restart = crash
                    with self._lock:
                        self._crashed(c, e)
                    continue
                with self._lock:
                    self._recovered(c)
                    c.next_probe_at = now + c.probe_interval_s
                continue
            if c.probe is not None and c.state == UP and now >= c.next_probe_at:
                c.next_probe_at = now + c.probe_interval_s
                try:
                    ok = bool(c.probe())
                except Exception:  # noqa: BLE001 — a raising probe = down
                    ok = False
                if not ok:
                    with self._lock:
                        self._crashed(c, "probe failed")
                else:
                    with self._lock:
                        self._recovered(c)

    # -- saturation (overload, not crashes) -----------------------------

    def set_saturation_probe(self, probe: Callable[[], bool]) -> None:
        """Register the overload signal (``pipeline.saturated``): the
        supervisor doesn't own backpressure, it REPORTS it — on
        ``overall_state()``, the /healthz surface, and the
        ``anomaly_saturated`` gauge exported from :meth:`tick`."""
        self._saturation_probe = probe

    def saturated(self) -> bool:
        if self._saturation_probe is None:
            return False
        try:
            return bool(self._saturation_probe())
        except Exception:  # noqa: BLE001 — a broken probe must not kill tick
            return False

    # -- replication role (failover, not crashes) -----------------------

    def set_role_probe(self, probe: Callable[[], tuple[str, int]]) -> None:
        """Register the replication-role signal (``(role, epoch)`` from
        the daemon's state machine — runtime.replication role
        constants). Like saturation, the supervisor doesn't own
        failover, it REPORTS it: ``anomaly_role{role=...}`` /
        ``anomaly_epoch`` from :meth:`tick`, and ``role()`` for the
        /healthz surface. A promotion (the standby watchdog firing) is
        driven by the daemon's supervised pump step, so the promotion
        path inherits the same crash quarantine every component gets."""
        self._role_probe = probe

    def role(self) -> tuple[str, int] | None:
        """Current ``(role, epoch)``, or None when replication is off
        (single-process deployments never see the role family)."""
        if self._role_probe is None:
            return None
        try:
            role, epoch = self._role_probe()
            return str(role), int(epoch)
        except Exception:  # noqa: BLE001 — a broken probe must not kill tick
            return None

    def overall_state(self) -> str:
        """One word for the whole daemon: DEGRADED beats SATURATED
        beats UP (a crash loop is strictly worse news than deliberate
        load shedding — see the state constants)."""
        if self.degraded():
            return DEGRADED
        if self.saturated():
            return SATURATED
        return UP

    # -- introspection --------------------------------------------------

    def state(self, name: str) -> str:
        return self._components[name].state

    def states(self) -> dict[str, str]:
        with self._lock:
            return {n: c.state for n, c in self._components.items()}

    def restarts(self, name: str) -> int:
        return self._components[name].restarts

    def degraded(self) -> bool:
        with self._lock:
            return any(c.state == DEGRADED for c in self._components.values())

    def health_status(self, service: str) -> int | None:
        """grpc.health.v1 hook: SERVING/NOT_SERVING for
        ``anomaly.component.<name>`` service names, None for others
        (the health service then falls back to its known-set rules)."""
        if not service.startswith(HEALTH_PREFIX):
            return None
        c = self._components.get(service[len(HEALTH_PREFIX):])
        if c is None:
            return None
        return SERVING if c.state == UP else NOT_SERVING
