"""Minimal Kafka client: simple consumer + producer over the wire subset.

The consumer follows the classic "simple consumer with group offset
storage" pattern: manual partition assignment from Metadata, positions
restored via OffsetFetch (falling back to earliest), Fetch polls, and
OffsetCommit with generation -1 / empty member id — real Kafka protocol
semantics that skip the group-membership state machine (JoinGroup/
SyncGroup/Heartbeat), which only matters for multi-instance rebalancing;
the sidecar scales by partition assignment, not rebalance (SURVEY.md
§2.3 consumer groups → sharded ingestion).

Matches the contract of the reference consumers: poll loop
(src/fraud-detection/.../main.kt:54-69), committed offsets as the resume
point (src/accounting/Consumer.cs:77-80).
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import NamedTuple

from . import kafka_wire as kw


class FetchedMessage(NamedTuple):
    partition: int
    offset: int
    key: bytes | None
    value: bytes | None
    headers: tuple = ()  # ((str, bytes|None), ...) — v2 record headers


class KafkaConnection:
    """One broker connection: framed request/response with correlation."""

    def __init__(self, host: str, port: int, client_id: str = "otel-demo-tpu",
                 timeout_s: float = 5.0):
        self.client_id = client_id
        self._corr = itertools.count(1)
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, port), timeout=timeout_s)

    def request(self, api_key: int, api_version: int, body: bytes) -> kw.Reader:
        corr = next(self._corr)
        frame = kw.encode_request(api_key, api_version, corr, self.client_id, body)
        with self._lock:
            self._sock.sendall(frame)
            resp = kw.read_frame(self._sock)
        if resp is None:
            raise kw.KafkaWireError("broker closed connection")
        r = kw.Reader(resp)
        got = r.int32()
        if got != corr:
            raise kw.KafkaWireError(f"correlation mismatch {got} != {corr}")
        return r

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _parse_bootstrap(bootstrap: str) -> tuple[str, int]:
    host, _, port = bootstrap.partition(":")
    return host or "127.0.0.1", int(port or 9092)


class KafkaProducer:
    """Produce v3 (v2 RecordBatch + headers) with broker-assigned
    offsets (acks=1 semantics) — the modern protocol minimum, so the
    same client speaks to the in-repo broker and a real Kafka ≥3.0."""

    def __init__(self, bootstrap: str):
        self._conn = KafkaConnection(*_parse_bootstrap(bootstrap))

    def send(self, topic: str, value: bytes, key: bytes | None = None,
             partition: int = 0, headers=()) -> int:
        """Returns the broker-assigned base offset. ``headers``:
        iterable of (str, bytes|None) pairs or a {str: bytes} mapping —
        trace context crosses the async boundary here, the reference's
        producer-header injection (main.go:631-637)."""
        batch = kw.encode_record_batch(
            [(key, value, headers)],
            base_timestamp_ms=int(time.time() * 1000),
        )
        body = (
            kw.enc_string(None)  # transactional_id
            + kw.enc_int16(1)  # required_acks
            + kw.enc_int32(1000)  # timeout
            + kw.enc_array(
                [(topic, [(partition, batch)])],
                lambda t: kw.enc_string(t[0])
                + kw.enc_array(
                    t[1],
                    lambda p: kw.enc_int32(p[0]) + kw.enc_int32(len(p[1])) + p[1],
                ),
            )
        )
        r = self._conn.request(kw.PRODUCE, 3, body)

        def read_partition():
            partition_ = r.int32()
            error = r.int16()
            base_offset = r.int64()
            r.int64()  # log_append_time
            return partition_, error, base_offset

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        _name, parts = topics[0]
        partition_, error, base_offset = parts[0]
        if error != kw.NO_ERROR:
            raise kw.KafkaProduceError(error, partition_)
        return base_offset

    def close(self) -> None:
        self._conn.close()


class KafkaConsumer:
    """Simple consumer with consumer-group offset storage."""

    def __init__(
        self,
        bootstrap: str,
        group_id: str,
        topic: str,
        max_bytes: int = 1 << 20,
        auto_commit: bool = True,
    ):
        self.group_id = group_id
        self.topic = topic
        self.max_bytes = max_bytes
        self.auto_commit = auto_commit
        self._conn = KafkaConnection(*_parse_bootstrap(bootstrap))
        self._partitions = self._fetch_partitions()
        # Restore committed positions; fall back to earliest.
        committed = self.committed()
        self._positions = {
            p: committed.get(p, -1) if committed.get(p, -1) >= 0 else 0
            for p in self._partitions
        }

    # -- metadata / offsets --------------------------------------------

    def _fetch_partitions(self) -> list[int]:
        body = kw.enc_array([self.topic], kw.enc_string)
        r = self._conn.request(kw.METADATA, 0, body)
        r.array(lambda: (r.int32(), r.string(), r.int32()))  # brokers

        def read_partition():
            r.int16()  # error
            partition = r.int32()
            r.int32()  # leader
            r.array(r.int32)
            r.array(r.int32)
            return partition

        topics = r.array(lambda: (r.int16(), r.string(), r.array(read_partition)))
        for _err, name, parts in topics:
            if name == self.topic:
                return sorted(parts)
        return [0]

    def committed(self) -> dict[int, int]:
        """Consumer-group committed offsets (next-to-read), -1 = none."""
        return {p: off for p, (off, _meta) in self.committed_meta().items()}

    def committed_meta(self) -> dict[int, tuple[int, str]]:
        """Committed offsets WITH their metadata strings.

        The metadata slot is where epoch-tagged commits
        (``kafka_orders.OrdersSource.commit``) park the writer's
        fencing epoch — a resurrected stale primary reads it at boot
        and learns it has been promoted past before its first write."""
        body = kw.enc_string(self.group_id) + kw.enc_array(
            [(self.topic, self._partitions if hasattr(self, "_partitions") else [0])],
            lambda t: kw.enc_string(t[0]) + kw.enc_array(t[1], kw.enc_int32),
        )
        r = self._conn.request(kw.OFFSET_FETCH, 1, body)

        def read_partition():
            partition = r.int32()
            offset = r.int64()
            metadata = r.string()
            r.int16()  # error
            return partition, (offset, metadata or "")

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        out: dict[int, tuple[int, str]] = {}
        for _name, parts in topics:
            out.update(dict(parts))
        return out

    def commit(
        self,
        offsets: dict[int, int] | None = None,
        metadata: str = "",
    ) -> None:
        """Commit next-to-read offsets (defaults to current positions).

        ``metadata`` rides in the protocol's per-partition metadata
        string (stored by the broker, returned by OFFSET_FETCH) — the
        epoch-tag channel for fenced commits."""
        offsets = offsets if offsets is not None else dict(self._positions)
        body = (
            kw.enc_string(self.group_id)
            + kw.enc_int32(-1)  # generation: simple consumer
            + kw.enc_string("")  # member id
            + kw.enc_int64(-1)  # retention: broker default
            + kw.enc_array(
                [(self.topic, sorted(offsets.items()))],
                lambda t: kw.enc_string(t[0])
                + kw.enc_array(
                    t[1],
                    lambda p: kw.enc_int32(p[0])
                    + kw.enc_int64(p[1])
                    + kw.enc_string(metadata),
                ),
            )
        )
        r = self._conn.request(kw.OFFSET_COMMIT, 2, body)
        topics = r.array(
            lambda: (r.string(), r.array(lambda: (r.int32(), r.int16())))
        )
        for _name, parts in topics:
            for partition, error in parts:
                if error != kw.NO_ERROR:
                    raise kw.KafkaWireError(
                        f"offset commit error {error} on partition {partition}"
                    )

    @property
    def positions(self) -> dict[int, int]:
        return dict(self._positions)

    def seek(self, partition: int, offset: int) -> None:
        """Set the next-to-read position; a partition the boot-time
        metadata didn't list is added to the fetch set rather than
        silently dropped (stale metadata must not cause replay)."""
        if partition not in self._positions:
            self._partitions = sorted(set(self._partitions) | {partition})
        self._positions[partition] = offset

    def _reset_offset(self, partition: int) -> None:
        """OFFSET_OUT_OF_RANGE recovery: reset to earliest (the
        ``auto.offset.reset=earliest`` rule the reference consumers
        configure) via ListOffsets."""
        body = (
            kw.enc_int32(-1)
            + kw.enc_array(
                [(self.topic, [(partition, -2, 1)])],  # ts -2 = earliest
                lambda t: kw.enc_string(t[0])
                + kw.enc_array(
                    t[1],
                    lambda p: kw.enc_int32(p[0])
                    + kw.enc_int64(p[1])
                    + kw.enc_int32(p[2]),
                ),
            )
        )
        r = self._conn.request(kw.LIST_OFFSETS, 0, body)

        def read_partition():
            part = r.int32()
            err = r.int16()
            offsets = r.array(r.int64)
            return part, err, offsets

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        for _name, parts in topics:
            for part, err, offsets in parts:
                if part == partition and err == kw.NO_ERROR and offsets:
                    self._positions[partition] = offsets[0]

    # -- poll -----------------------------------------------------------

    def poll(self, max_wait_ms: int = 100) -> list[FetchedMessage]:
        """Fetch v4 (v2 RecordBatch + headers) — the modern protocol
        minimum, same rationale as the producer's v3."""
        body = (
            kw.enc_int32(-1)  # replica_id
            + kw.enc_int32(max_wait_ms)
            + kw.enc_int32(1)  # min_bytes
            + kw.enc_int32(self.max_bytes)  # whole-response cap
            + kw.enc_int8(0)  # isolation_level: read_uncommitted
            + kw.enc_array(
                [(self.topic, [(p, self._positions[p], self.max_bytes)
                               for p in self._partitions])],
                lambda t: kw.enc_string(t[0])
                + kw.enc_array(
                    t[1],
                    lambda p: kw.enc_int32(p[0])
                    + kw.enc_int64(p[1])
                    + kw.enc_int32(p[2]),
                ),
            )
        )
        r = self._conn.request(kw.FETCH, 4, body)
        r.int32()  # throttle_time_ms

        def read_partition():
            partition = r.int32()
            error = r.int16()
            hw = r.int64()
            r.int64()  # last_stable_offset
            r.array(lambda: (r.int64(), r.int64()))  # aborted_transactions
            size = r.int32()
            batches = r.buf[r.pos : r.pos + size]
            r.pos += size
            return partition, error, hw, batches

        topics = r.array(lambda: (r.string(), r.array(read_partition)))
        out: list[FetchedMessage] = []
        for _name, parts in topics:
            for partition, error, _hw, batches in parts:
                if error == kw.OFFSET_OUT_OF_RANGE:
                    # Retention deleted our position (or a checkpoint
                    # predates the log start): reset to earliest rather
                    # than wedging on retries forever.
                    self._reset_offset(partition)
                    continue
                if error != kw.NO_ERROR:
                    continue  # transient: position holds, retry later
                for rec in kw.decode_record_batches(batches):
                    if rec.offset < self._positions[partition]:
                        continue  # batch starts below our position
                    out.append(
                        FetchedMessage(
                            partition, rec.offset, rec.key, rec.value,
                            rec.headers,
                        )
                    )
                    self._positions[partition] = rec.offset + 1
        if out and self.auto_commit:
            self.commit()
        return out

    def close(self) -> None:
        self._conn.close()
