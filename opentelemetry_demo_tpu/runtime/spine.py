"""Device-put spine: staged host ring + async double-buffered puts.

The last host-side hop of the ingest spine (ROADMAP item 1): between
the pipeline's batch assembly and the donated ``observe_packed`` step
sits a pack (pad + hash) and a host→device transfer. Without the
spine both run on the pump thread inside the dispatch tick, so the
transfer of batch *k+1* cannot begin until batch *k*'s dispatch tick
is over. This module moves pack+put onto a dedicated **stager thread**
working through a small ring of pre-allocated host staging buffers:

- ``stage(cols, width, ...)`` (pump thread) enqueues the assembled
  columns and returns immediately; the stager packs them into ring
  slot ``seq % depth`` (``SpanTensorizer.pack_columns_into`` — zero
  allocations, stable host memory) and issues ``jax.device_put`` for
  every lane. ``device_put`` is asynchronous on real accelerators, so
  the transfer of batch *k+1* rides the wire WHILE the device executes
  batch *k*'s donated step — the overlap the e2e SLO measures.
- ``take(wait=...)`` (pump thread) pops the oldest staged batch. With
  a step in flight the pump takes only batches whose put already
  completed (``overlap_hits``); with the device idle — or under
  ``drain()`` — it waits (``overlap_misses``), so the low-rate regime
  pays no added latency beyond the put itself.
- **Double-buffer discipline**: a ring slot is repacked only after the
  device arrays created from its PREVIOUS use are ready
  (``jax.block_until_ready`` — i.e. the transfer consumed the host
  bytes). Depth 2 is classic double buffering: pack k+1 while k
  transfers; deeper rings absorb put-latency jitter.

The spine owns NO detector state: dispatch (and every
``detector.state`` touch) stays on the pump thread under the
pipeline's ``_dispatch_lock``, so the PR 7 donation-race pass has
nothing new to flag — the stager only ever touches its own ring and
the host column views (whose lifetime the ingest pool's scratch
tickets already manage). tests/test_spine.py hammers dispatch-vs-put
concurrency under donation to pin that.

Knobs ride ``utils.config.SPINE_KNOBS`` (ring depth / overlap /
chunk rows), threaded daemon → compose → k8s like every family.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .tensorize import SpanColumns, SpanTensorizer, TensorBatch


class SpineError(RuntimeError):
    """A staging job failed (pack or device put) — surfaced to the
    dispatcher that tries to take the batch, never swallowed."""


class StagedBatch:
    """One assembled batch riding the spine: host columns in, device
    arrays out once the stager's put has been issued.

    ``trace`` carries the batch's sampled self-trace (runtime.selftrace
    BatchTrace, or None) across the stage→take hand-off; ``stage_dur``
    / ``wait_s`` are this batch's OWN pack+put-issue and take-side
    put-wait seconds — the per-batch samples behind the
    anomaly_phase_seconds{phase="stage"} and
    anomaly_spine_put_wait_seconds histograms (the cumulative
    ``stage_s``/``take_wait_s`` pool stats stay for the benches)."""

    __slots__ = (
        "cols", "width", "t_now", "t_oldest", "batch", "error", "ready",
        "trace", "stage_dur", "wait_s",
    )

    def __init__(
        self, cols: SpanColumns, width: int, t_now, t_oldest, trace=None
    ):
        self.cols = cols
        self.width = width
        self.t_now = t_now
        self.t_oldest = t_oldest
        self.batch: TensorBatch | None = None  # device arrays
        self.error: BaseException | None = None
        self.ready = threading.Event()
        self.trace = trace
        self.stage_dur = 0.0
        self.wait_s = 0.0


class DevicePutSpine:
    """Staging ring + stager thread (see module doc)."""

    def __init__(
        self,
        tensorizer: SpanTensorizer,
        depth: int = 2,
        overlap: bool = True,
        chunk_rows: int = 0,
        device_put=None,
    ):
        if depth < 1:
            raise ValueError(f"spine ring depth must be >= 1 (got {depth})")
        self.tensorizer = tensorizer
        self.depth = int(depth)
        self.overlap = bool(overlap)
        self.chunk_rows = int(chunk_rows)
        self._device_put = device_put
        # Ring slots: per-slot {width: host TensorBatch} (the adaptive
        # controller moves along a pow2 width ladder; each width's
        # buffers are allocated once and reused).
        self._slots: list[dict[int, TensorBatch]] = [
            {} for _ in range(self.depth)
        ]
        # Device arrays from each slot's previous use: the transfer
        # that must complete before the slot's host memory is repacked.
        self._slot_prev: list[TensorBatch | None] = [None] * self.depth
        self._seq = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._jobs: deque[StagedBatch] = deque()
        self._staged: deque[StagedBatch] = deque()
        self._stop = False
        # Stats (read by the daemon's scrape via stats()).
        self.puts_total = 0
        self.overlap_hits = 0  # take() found the put already complete
        self.overlap_misses = 0  # take() had to wait on the put
        self.stage_s = 0.0  # stager: pack + put issue + slot wait
        self.take_wait_s = 0.0  # pump: time blocked in waiting takes
        self._thread = threading.Thread(
            target=self._run, name="spine-stager", daemon=True
        )
        self._thread.start()

    # -- pump-thread API ----------------------------------------------

    def stage(
        self, cols: SpanColumns, width: int, t_now, t_oldest, trace=None
    ) -> None:
        """Enqueue one assembled batch for pack+put (never blocks —
        the PUMP enforces the ring bound by wait-dispatching the head
        before staging past ``depth``; the pump thread is the spine's
        only consumer, so blocking here would deadlock it against
        itself). ``trace`` rides the StagedBatch to dispatch."""
        staged = StagedBatch(cols, int(width), t_now, t_oldest, trace=trace)
        with self._work:
            if self._stop:
                raise SpineError("spine is closed")
            self._jobs.append(staged)
            self._staged.append(staged)
            self._work.notify_all()

    def take(
        self, wait: bool, timeout: float = 30.0
    ) -> StagedBatch | None:
        """Oldest staged batch, device-resident — or None when nothing
        is ready and ``wait`` is False (the overlap regime: the pump
        dispatches it next tick, after the put finished behind the
        in-flight step)."""
        with self._lock:
            staged = self._staged[0] if self._staged else None
        if staged is None:
            return None
        if staged.ready.is_set():
            hit = True
        elif not wait:
            return None
        else:
            hit = False
            t0 = time.perf_counter()
            if not staged.ready.wait(timeout):
                raise SpineError(
                    f"staged batch not ready after {timeout}s "
                    "(stager dead or device put wedged)"
                )
            staged.wait_s = time.perf_counter() - t0
            with self._lock:
                self.take_wait_s += staged.wait_s
        with self._work:
            # Still the head (single consumer — the pump thread).
            if self._staged and self._staged[0] is staged:
                self._staged.popleft()
            if hit:
                self.overlap_hits += 1
            else:
                self.overlap_misses += 1
            self._work.notify_all()
        if staged.error is not None:
            raise SpineError(
                f"staging failed: {type(staged.error).__name__}: "
                f"{staged.error}"
            ) from staged.error
        return staged

    def pending(self) -> int:
        with self._lock:
            return len(self._staged)

    def discard_pending(self) -> int:
        """Drop every undispatched staged batch (detector flag turned
        off mid-stream), returning the row count dropped — the
        pipeline counts them beside its own pending-queue drop.
        Non-blocking: unstarted jobs are cancelled outright, and a
        batch the stager is packing RIGHT NOW simply completes into an
        orphan (its put is wasted, nothing references it) — waiting on
        a wedged put here would stall the pump's disabled branch."""
        with self._work:
            dropped = list(self._staged)
            self._staged.clear()
            gone = {id(s) for s in dropped}
            self._jobs = deque(
                j for j in self._jobs if id(j) not in gone
            )
            self._work.notify_all()
        return sum(s.cols.rows for s in dropped)

    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stop

    def close(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify_all()
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        with self._lock:
            puts = self.puts_total
            hits = self.overlap_hits
            misses = self.overlap_misses
            taken = hits + misses
            return {
                "ring_depth": self.depth,
                "staged": len(self._staged),
                "puts_total": puts,
                "overlap_hits": hits,
                "overlap_misses": misses,
                # Of the batches dispatched so far, the fraction whose
                # host→device put completed entirely behind the
                # in-flight step — transfer hidden by compute.
                "overlap_ratio": (hits / taken) if taken else 0.0,
                "stage_s": self.stage_s,
                "take_wait_s": self.take_wait_s,
            }

    # -- stager thread -------------------------------------------------

    def _host_slot(self, idx: int, width: int) -> TensorBatch:
        slot = self._slots[idx].get(width)
        if slot is None:
            slot = self._slots[idx][width] = self.tensorizer.alloc_batch(
                width
            )
        return slot

    def _put(self, host: TensorBatch) -> TensorBatch:
        if self._device_put is not None:
            return TensorBatch(*(self._device_put(a) for a in host))
        import jax

        return TensorBatch(*(jax.device_put(a) for a in host))

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._jobs and not self._stop:
                    self._work.wait(0.05)
                if self._stop:
                    # Fail any batch nobody will ever put: a waiting
                    # take()/discard must not hang on a dead stager.
                    for staged in self._jobs:
                        staged.error = SpineError("spine closed mid-stage")
                        staged.ready.set()
                    self._jobs.clear()
                    return
                staged = self._jobs.popleft()
            t0 = time.perf_counter()
            try:
                idx = self._seq % self.depth
                self._seq += 1
                prev = self._slot_prev[idx]
                if prev is not None:
                    # Double-buffer guard: never repack host memory a
                    # previous put may still be reading. block_until_
                    # ready on PUT arrays waits for the transfer only
                    # (they are inputs, not computation results).
                    import jax

                    jax.block_until_ready(tuple(prev))
                slot = self._host_slot(idx, staged.width)
                host = self.tensorizer.pack_columns_into(
                    slot, staged.cols, chunk_rows=self.chunk_rows
                )
                dev = self._put(host)
                self._slot_prev[idx] = dev
                staged.batch = dev
                staged.stage_dur = time.perf_counter() - t0
                with self._lock:
                    self.puts_total += 1
                    self.stage_s += staged.stage_dur
            except Exception as e:  # noqa: BLE001 — surfaced via
                # staged.error to the taking dispatcher; the stager
                # thread itself must survive (it is the only producer
                # of ready events and close() joins it).
                staged.error = e
            finally:
                staged.ready.set()
