"""Python control plane for the native OTLP front door.

The data plane lives in native/frontdoor.cc: accept → HTTP/1.1
framing → body bytes recv'd DIRECTLY into a recycled native buffer →
(id, kind, ptr, len) ticket → verdict → canned response — zero Python
in the per-payload loop. This module is everything that rightly stays
Python, because it needs pipeline state:

- the pump threads that drain tickets in BATCHES (one GIL-released
  ``native.frontdoor_next`` call per batch) and route them: trace
  bodies go to the decode pool's POINTER path (``pool.submit`` of a
  zero-copy ctypes view — ``decode_otlp_many`` scans the native buffer
  in place), metrics/logs take the Python decoders at scrape cadence;
- the verdict taxonomy, bit-compatible with ``runtime/otlp.py``'s
  receiver: pipeline saturation → 429 + integer Retry-After (rounded
  up), pool saturation → 429 + Retry-After: 1, a server-side flush
  failure → 500, and the per-request DECODE verdict carried by the
  :class:`DecodeTicket` → 400 for exactly the bad request while its
  batchmates proceed. A WEDGED flush gets its verdict DEFERRED, not
  short-circuited to 503: the pool still holds a zero-copy view of
  the ticket's native buffer, and ``frontdoor_respond`` is what hands
  the buffer back to the connection thread for recycling — responding
  early would let the decode scan freed/reused memory. The ticket is
  parked on a stalled list the pump re-polls each drain, and the
  eventual REAL verdict (200/400/500) goes out when the flush lands;
  Metrics/logs stay exempt from the saturation gate (they arrive at
  scrape cadence — the same exemption the Python receiver applies);
- reject bookkeeping: the natively-decided verdicts (bad_length,
  oversized, chunked, truncated, disconnect) are counted by
  frontdoor.cc and mirrored into ``rejects``/``on_reject`` here, so
  ``anomaly_ingest_rejected_total{transport="frontdoor"}`` tells one
  honest story regardless of which side decided;
- graceful drain: quiesce (stop accepting; in-flight verdicts keep
  flowing) → wait for quiescence → full native stop → join pumps.

Deliberately ABSENT from this module: ``http.server``,
``socketserver``, and any per-request Python object on the trace
path — scripts/sanitycheck.py pins both (the zero-Python-HTTP
monopoly), and tests/test_frontdoor.py proves the taxonomy against
the Python receiver on a shared corpus.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable

from . import native
from .ingest_pool import IngestPool, IngestPoolSaturated, IngestWorkerError

# Native reject slots mirrored into the receiver-style rejects dict
# (same reason spellings as runtime/otlp.py where the verdict exists
# there; "chunked" is native-only — the Python receiver never sees a
# chunked body as such).
_NATIVE_REJECT_REASONS = (
    "bad_length", "oversized", "chunked", "truncated", "disconnect",
)


class FrontDoorServer:
    """Own one native front door + its pump threads.

    ``pool`` is the shared :class:`~.ingest_pool.IngestPool` — the
    front door is a second producer into the same bounded queue, so
    the bounded-admission contract (nothing unbounded ahead of the
    pipeline) is inherited, not re-implemented.
    """

    def __init__(
        self,
        pool: IngestPool,
        port: int = 0,
        max_body_bytes: int = 16 << 20,
        pumps: int = 1,
        batch_max: int = 64,
        max_conns: int = 64,
        header_timeout_ms: int = 10000,
        retry_after: Callable[[], float | None] | None = None,
        on_reject: Callable[[str], None] | None = None,
        on_metric_records: Callable | None = None,
        on_log_records: Callable | None = None,
        ticket_timeout_s: float = 30.0,
    ):
        self._pool = pool
        self._retry_after = retry_after
        self._on_reject = on_reject
        self._on_metric_records = on_metric_records
        self._on_log_records = on_log_records
        self._ticket_timeout_s = ticket_timeout_s
        self.max_body_bytes = max_body_bytes
        self.rejects: dict[str, int] = {}
        self._rejects_lock = threading.Lock()
        self._native_seen = {r: 0 for r in _NATIVE_REJECT_REASONS}
        self._handle = native.frontdoor_start(
            port, max_body_bytes, max_conns, header_timeout_ms
        )
        self.port = native.frontdoor_port(self._handle)
        self._batch_max = max(int(batch_max), 1)
        self._stopped = False
        self._pumps = [
            threading.Thread(
                target=self._pump, name=f"frontdoor-pump-{i}", daemon=True
            )
            for i in range(max(int(pumps), 1))
        ]
        for t in self._pumps:
            t.start()

    # -- reject bookkeeping --------------------------------------------

    def _reject(self, reason: str, n: int = 1) -> None:
        with self._rejects_lock:
            self.rejects[reason] = self.rejects.get(reason, 0) + n
        if self._on_reject is not None:
            for _ in range(n):
                self._on_reject(reason)

    def _sync_native_rejects(self) -> None:
        """Fold frontdoor.cc's natively-decided reject counters into
        the receiver-style dict (delta since last sync, so calling
        this from stats() and the pump keeps one honest total)."""
        raw = native.frontdoor_stats(self._handle)
        for reason in _NATIVE_REJECT_REASONS:
            delta = raw[reason] - self._native_seen[reason]
            if delta > 0:
                self._native_seen[reason] = raw[reason]
                self._reject(reason, delta)

    # -- the pump -------------------------------------------------------

    def _pump(self) -> None:
        batch = native.frontdoor_alloc_batch(self._batch_max)
        pending: list[tuple[int, object]] = []
        # Tickets whose flush outlived _ticket_timeout_s: the pool
        # STILL holds a zero-copy view of their native buffers, so the
        # verdict (and with it the buffer hand-back) is deferred until
        # the flush actually resolves — see _sweep_stalled.
        stalled: list[tuple[int, object]] = []
        h = self._handle
        while True:
            n = native.frontdoor_next(h, batch, timeout_ms=100)
            if n < 0:
                # Server stopping, queue drained. Give any still-
                # stalled flush one last bounded wait so its verdict
                # (a no-op respond by now — native stop already
                # answered the conn 503) marks the buffer released
                # before this thread exits.
                self._sweep_stalled(stalled, final=True)
                return
            for i in range(n):
                rid = int(batch.ids[i])
                kind = int(batch.kinds[i])
                ptr = int(batch.ptrs[i])
                ln = int(batch.lens[i])
                if kind == native.FD_KIND_TRACES:
                    self._admit_trace(rid, ptr, ln, pending)
                else:
                    self._serve_signal(rid, kind, ptr, ln)
            # Resolve this drain's tickets in order: each carries its
            # OWN decode verdict (the 400-for-exactly-the-bad-request
            # contract), resolved together by the pool's batched flush.
            for rid, ticket in pending:
                try:
                    ticket.result(timeout=self._ticket_timeout_s)
                    status, ra = 200, 0
                except TimeoutError:
                    # Wedged flush: the ticket's buffer is STILL queued
                    # in the pool. Responding now would return the
                    # buffer to the connection thread for resize/
                    # recycle while the decode worker can still scan
                    # it (use-after-free) — frontdoor_body's contract
                    # is respond only AFTER the decode consumed the
                    # bytes. Park it; the verdict goes out on a later
                    # sweep, when the flush has really resolved.
                    stalled.append((rid, ticket))
                    continue
                except IngestWorkerError:
                    # Server-side flush failure: our bug, not the
                    # client's bytes — 5xx, never "malformed".
                    status, ra = 500, 0
                except Exception:  # noqa: BLE001 — the decode verdict
                    self._reject("malformed")
                    status, ra = 400, 0
                native.frontdoor_respond(h, rid, status, ra)
            pending.clear()
            if stalled:
                self._sweep_stalled(stalled)
            if n > 0:
                self._sync_native_rejects()

    def _sweep_stalled(
        self, stalled: list[tuple[int, object]], final: bool = False
    ) -> None:
        """Respond to parked wedged-flush tickets whose flush has since
        landed (non-blocking poll per ticket; ``final`` blocks one
        ticket-timeout each — the pump's exit path). Unresolved tickets
        stay parked: their native buffers are still borrowed by the
        pool, and ``pending`` in the native stats stays >0 for them,
        which is what makes ``stop()``'s drain wait cover them too."""
        kept: list[tuple[int, object]] = []
        for rid, ticket in stalled:
            if not final and not ticket.done():
                kept.append((rid, ticket))
                continue
            try:
                ticket.result(
                    timeout=self._ticket_timeout_s if final else 0.0
                )
                status, ra = 200, 0
            except TimeoutError:
                if final:
                    # Truly wedged past the exit grace: nothing safe
                    # left to do — dropping the respond keeps our side
                    # of the never-release-a-borrowed-buffer contract.
                    continue
                kept.append((rid, ticket))
                continue
            except IngestWorkerError:
                status, ra = 500, 0
            except Exception:  # noqa: BLE001 — the decode verdict
                self._reject("malformed")
                status, ra = 400, 0
            native.frontdoor_respond(self._handle, rid, status, ra)
        stalled[:] = kept

    def _admit_trace(
        self, rid: int, ptr: int, ln: int, pending: list
    ) -> None:
        # Saturation gate first (the PR 2 Retry-After contract): the
        # native side already read the whole body — the drain that
        # keeps a 429 from RSTing a mid-send client happened on the C
        # side by construction.
        if self._retry_after is not None:
            hint = self._retry_after()
            if hint is not None:
                self._reject("saturated")
                native.frontdoor_respond(
                    self._handle, rid, 429, max(int(-(-hint // 1)), 1)
                )
                return
        body = native.frontdoor_body(ptr, ln)
        try:
            ticket = self._pool.submit(body)
        except IngestPoolSaturated:
            self._reject("saturated")
            native.frontdoor_respond(self._handle, rid, 429, 1)
            return
        pending.append((rid, ticket))

    def _serve_signal(self, rid: int, kind: int, ptr: int, ln: int) -> None:
        # Metrics/logs: scrape-cadence traffic — one bytes copy here
        # is noise, and the Python decoders are the single source of
        # truth for these signals (same as the Python receiver).
        data = ctypes.string_at(ptr, ln) if ln else b""
        try:
            if kind == native.FD_KIND_METRICS:
                if self._on_metric_records is not None:
                    from . import otlp_metrics

                    self._on_metric_records(
                        otlp_metrics.decode_metrics_request(data)
                    )
            elif kind == native.FD_KIND_LOGS:
                if self._on_log_records is not None:
                    from .otlp import decode_logs_request

                    self._on_log_records(decode_logs_request(data))
            native.frontdoor_respond(self._handle, rid, 200, 0)
        except Exception:  # noqa: BLE001 — malformed exports answer 400
            self._reject("malformed")
            native.frontdoor_respond(self._handle, rid, 400, 0)

    # -- observability --------------------------------------------------

    def stats(self) -> dict:
        self._sync_native_rejects()
        raw = native.frontdoor_stats(self._handle)
        with self._rejects_lock:
            rejects = dict(self.rejects)
        return {**raw, "rejects": rejects, "port": self.port}

    # -- lifecycle ------------------------------------------------------

    def stop(self, drain_timeout_s: float = 5.0) -> None:
        """Graceful drain: quiesce, let in-flight verdicts land, full
        native stop, join pumps. Idempotent."""
        if self._stopped:
            return
        self._stopped = True
        import time

        native.frontdoor_quiesce(self._handle)
        # "pending" counts every ticket whose conn has not received a
        # verdict — including pump-parked wedged-flush tickets whose
        # buffers the pool still borrows — so this wait also keeps the
        # hard stop (which frees conn buffers) away from live views
        # for as long as the drain budget allows.
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            if native.frontdoor_stats(self._handle)["pending"] == 0:
                break
            time.sleep(0.02)
        native.frontdoor_stop(self._handle)
        for t in self._pumps:
            t.join(timeout=5.0)
        self._sync_native_rejects()
